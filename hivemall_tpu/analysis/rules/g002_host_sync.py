"""G002 host-sync-in-hot-loop: implicit device->host reads on the hot path.

Scope: the per-step modules in ``config.HOT_LOOP_MODULES`` (core/engine.py,
parallel/sharded_train.py, parallel/mix.py, models/trees/grow.py, plus the
epoch driver models/base.py). Inside those modules the rule flags, on
device values:

- ``float(x)`` / ``int(x)`` / ``bool(x)`` / ``np.asarray(x)`` /
  ``np.array(x)`` / ``x.item()`` / ``x.tolist()`` inside any host-side
  ``for``/``while`` loop — each one blocks dispatch until the device
  catches up, serializing the step stream (the per-step host overhead the
  terascale-learning paper eliminates);
- the same calls anywhere in a method named ``step``/``_step``/``epoch``
  (those receive device state by contract, loop or not);
- ``jax.device_get(x[i])`` / ``jax.device_get(state.field)`` in a loop —
  per-element transfers. One whole-value/tuple ``jax.device_get`` per loop
  body is the *approved* batched boundary read (move convergence/metrics
  reads to epoch or level boundaries and fetch everything in one transfer).

Device values are identified by the module model's taint walker; host
functions only taint jnp/jax results and jitted-callable results, so
already-fetched host state (``jax.device_get(...)`` results, numpy arrays)
never false-positives.
"""

from __future__ import annotations

import ast
from typing import List

from .. import config
from ..findings import Finding, Severity
from ..modmodel import (ModuleModel, dotted_name, enclosing_loop, walk_scope)

RULE_ID = "G002"


def _is_hot_module(model: ModuleModel) -> bool:
    """Hot-path modules from config, plus any module that opts in with a
    `# graftcheck: hot-module` marker (used by fixtures and future hot
    paths outside the canonical four)."""
    return (model.rel_path in config.HOT_LOOP_MODULES
            or "# graftcheck: hot-module" in model.source)


def _sync_call_kind(call: ast.Call):
    """(kind, arg) when `call` is a sync-inducing read, else None."""
    callee = dotted_name(call.func)
    if callee in config.SYNC_CALLS and len(call.args) >= 1:
        return callee, call.args[0]
    if callee is not None and "." in callee:
        root, tail = callee.split(".", 1)
        if root in ("np", "numpy") and tail in config.SYNC_NP_CALLS \
                and len(call.args) >= 1:
            return callee, call.args[0]
    if isinstance(call.func, ast.Attribute) \
            and call.func.attr in config.SYNC_METHODS and not call.args:
        return f".{call.func.attr}()", call.func.value
    return None


def _is_device_get(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name is not None and name.rsplit(".", 1)[-1] == "device_get"


def check(model: ModuleModel) -> List[Finding]:
    if not _is_hot_module(model):
        return []
    findings: List[Finding] = []

    def emit(node: ast.AST, msg: str) -> None:
        findings.append(Finding(model.rel_path, node.lineno, RULE_ID,
                                Severity.ERROR, msg,
                                model.snippet(node.lineno)))

    for fn in model.functions:
        if model.is_traced(fn):
            continue  # traced code cannot host-sync; G006 covers its effects
        hot_fn = bool(config.HOT_FN_RE.match(fn.name))
        tainted, callables = model.taint_function(fn, taint_params=hot_fn)
        for node in walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            in_loop = enclosing_loop(node) is not None
            if not in_loop and not hot_fn:
                continue
            sync = _sync_call_kind(node)
            if sync is not None:
                kind, arg = sync
                if model.expr_tainted(arg, tainted, callables):
                    where = "hot loop" if in_loop else f"`{fn.name}()`"
                    emit(node, f"`{kind}` on a device value inside {where} "
                               f"— blocks dispatch per step; batch the read "
                               f"to an epoch boundary with one "
                               f"jax.device_get")
                continue
            if in_loop and _is_device_get(node) and node.args:
                arg = node.args[0]
                if isinstance(arg, (ast.Subscript, ast.Attribute)) \
                        and model.expr_tainted(arg, tainted, callables):
                    emit(node, "per-element jax.device_get in a hot loop — "
                               "fetch the whole batch/tuple in ONE "
                               "device_get at the loop boundary")
    return findings
