"""G022 FFI unvalidated pointer: an array's raw pointer crosses the ABI without a dominating dtype+contiguity proof.

``x.ctypes.data_as(...)`` hands the C side a raw address plus *nothing
else* — no dtype, no strides, no length. If ``x`` arrived as float64
where the C signature reads float32, or as a Fortran-ordered or strided
array, the native loop reads (or writes) garbage at full speed: silent
memory corruption, not a traceback. Every pointer that crosses must be
dominated by a proof: ``np.ascontiguousarray(..., dtype=...)``, a fresh
dtype-pinned constructor (``np.zeros(n, dtype)``), an ``.astype`` copy,
the sanctioned ``plan_abi_arrays`` validator (which raises on any
drift), an explicit ``dtype``+``C_CONTIGUOUS`` guard statement, or a
helper whose every return is itself proven.

Fix: when the base's defining assignment is a single-line
``np.asarray(..., dtype=...)``, rewrite it to
``np.ascontiguousarray(..., dtype=...)`` — same dtype pin, adds the
contiguity guarantee. Other cases need a human (add a coercion or a
guard).

Expression temporaries and views are G023's subject; this rule covers
named bindings (and const-keyed subscripts like ``state["w"]``).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..ffi import (get_ffi, name_validated, pointer_args,
                   subscript_validated)
from ..findings import Edit, Finding, Fix, Severity
from ..modmodel import ModuleModel, walk_scope
from ..program import ProgramModel

RULE_ID = "G022"


def _asarray_fix(model: ModuleModel, fn: Optional[ast.AST], name: str,
                 before_line: int) -> Optional[Fix]:
    """When the last defining assignment is a one-line
    ``np.asarray(..., dtype present)``, upgrading it to
    ``np.ascontiguousarray`` is sufficient and safe."""
    if fn is None:
        return None
    best: Optional[ast.Assign] = None
    for node in walk_scope(fn):
        if isinstance(node, ast.Assign) and node.lineno < before_line:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    best = node
    if best is None or best.lineno != getattr(best, "end_lineno",
                                              best.lineno):
        return None
    value = best.value
    if not isinstance(value, ast.Call):
        return None
    from ..modmodel import dotted_name
    callee = dotted_name(value.func) or ""
    if callee not in ("np.asarray", "numpy.asarray"):
        return None
    has_dtype = len(value.args) >= 2 or any(
        kw.arg == "dtype" for kw in value.keywords)
    if not has_dtype:
        return None
    root = callee.rsplit(".", 1)[0]
    return Fix(edits=(Edit(best.lineno, f"{root}.asarray",
                           f"{root}.ascontiguousarray"),))


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    ffi = get_ffi(program)
    for path in sorted(scanned):
        mod = ffi.modules.get(path)
        if mod is None:
            continue
        model = program.modules[path]
        seen = set()
        for fc in mod.calls:
            for pa in pointer_args(program, path, mod, fc):
                if pa.kind == "name":
                    assert isinstance(pa.base, ast.Name)
                    if name_validated(program, path, model, fc.fn,
                                      pa.base.id, fc.node.lineno):
                        continue
                    label = f"`{pa.base.id}`"
                    fix = _asarray_fix(model, fc.fn, pa.base.id,
                                       fc.node.lineno)
                elif pa.kind == "namedsub":
                    if subscript_validated(model, fc.fn, pa.base,
                                           fc.node.lineno):
                        continue
                    src = ast.get_source_segment(model.source, pa.base)
                    label = f"`{src}`"
                    fix = None
                else:
                    continue  # views/temps are G023's subject
                key = (fc.node.lineno, label)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    path, fc.node.lineno, RULE_ID, Severity.ERROR,
                    f"raw pointer of {label} passed to native "
                    f"`{fc.symbol}` without a dominating dtype+"
                    f"C-contiguity validation — a wrong-dtype or strided "
                    f"array here is silent memory corruption on the C "
                    f"side; coerce with np.ascontiguousarray({label[1:-1]}"
                    f", dtype=...) or validate via plan_abi_arrays",
                    model.snippet(fc.node.lineno), fix=fix))
    return findings
