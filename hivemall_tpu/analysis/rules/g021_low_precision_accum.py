"""G021 accumulate-in-low-precision: reductions whose accumulator is <32-bit.

The one place widening is *required*: a ``sum``/``mean``/``cumsum``/
``segment_sum`` or a ``.at[...].add`` scatter whose accumulator dtype
equals a bf16/f16 input. Reduced floats carry 8-11 mantissa bits — a
16k-element bf16 sum has absorbed-update error on the order of the values
themselves, and an online-learning scatter-add that accumulates bf16
*loses* small gradient contributions entirely (the reference shipped its
half-float codec for storage, never for accumulation). The dtype-flow
model proves the operand/table dtype; the fix is an explicit widened
accumulator (``dtype=jnp.float32`` on the reduction, or accumulate f32
and cast once at the table write — the models/base.py storage policy).

Scoped to the dtype-sensitive packages plus the hot-path scopes; unknown
dtypes (parameters, dynamic tables) are trusted, and a reduction that
already passes a wider ``dtype=`` is the sanctioned idiom.
"""

from __future__ import annotations

from typing import List, Set

from .. import config
from ..dtypeflow import get_model, in_hot_scope
from ..findings import Finding, Severity
from ..program import ProgramModel

RULE_ID = "G021"


def _module_in_scope(path: str, source: str) -> bool:
    return (path.startswith(config.DTYPE_MODULE_PREFIXES
                            + config.DTYPEFLOW_HOT_PREFIXES)
            or path in config.DTYPEFLOW_HOT_MODULES
            or "# graftcheck: dtype-module" in source
            or config.HOT_MARKER in source)


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    flow = get_model(program)
    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None:
            continue
        if not (_module_in_scope(path, model.source)
                or any(in_hot_scope(path, model, fn)
                       for fn in model.functions)):
            continue
        seen: Set[int] = set()
        for fn in model.functions:
            facts = flow.facts(path, fn)
            for red in facts.reductions:
                if red.widened or red.operand_dt is None \
                        or not red.operand_dt.reduced_float \
                        or red.node.lineno in seen:
                    continue
                seen.add(red.node.lineno)
                findings.append(Finding(
                    path, red.node.lineno, RULE_ID, Severity.ERROR,
                    f"{red.tail} over a {red.operand_dt.name} operand "
                    f"accumulates in {red.operand_dt.name} — 8-11 mantissa "
                    f"bits absorb small contributions entirely; widen the "
                    f"accumulator (dtype=jnp.float32) and cast once at the "
                    f"result write",
                    model.snippet(red.node.lineno)))
            for sc in facts.scatters:
                if sc.table_dt is None or not sc.table_dt.reduced_float \
                        or sc.node.lineno in seen:
                    continue
                seen.add(sc.node.lineno)
                findings.append(Finding(
                    path, sc.node.lineno, RULE_ID, Severity.ERROR,
                    f".at[].{sc.method} into a {sc.table_dt.name} table "
                    f"accumulates updates in {sc.table_dt.name} — online "
                    f"updates smaller than ~1/256 of the weight vanish; "
                    f"accumulate f32 and cast once at the table write "
                    f"(the models/base.py storage policy)",
                    model.snippet(sc.node.lineno)))
    return findings
