"""G035 donated-buffer-use-after-call: the loop-carried and cross-module gap.

G005(b) catches straight-line reads after a donating jit call, but only
when the donating alias is declared in the same module (``name =
jax.jit(fn, donate_argnums=...)``) and only lexically *after* the call.
Two live classes escape it:

(a) **loop-carried reuse**: a donating call inside a loop whose donated
    name is never rebound anywhere in the loop body — iteration 1 hands
    the buffer to XLA, iteration 2 passes a deleted array. The sanctioned
    carry rebinds the result (``cv, ci = self._step(..., cv, ci)``, the
    retrieval top-K idiom); a loop that donates the same binding every
    pass is flagged.
(b) **interprocedurally-donating callees**: ``self._step =
    self._build_block_step()`` where the factory ``return``s
    ``jax.jit(step, donate_argnums=...)`` — or the memo-thunk form
    ``self._step = _retrieval_jit(key, lambda: _build_step())``. G005's
    alias map cannot see these; traceflow resolves them, and this rule
    runs G005's straight-line scan over exactly the resolved-only aliases
    (module-local aliases stay G005's subject — no double findings).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..findings import Finding, Severity
from ..modmodel import walk_scope
from ..program import ProgramModel
from ..traceflow import module_info
from .g005_donation import _assigned_names, _donated_name, _scan_block, \
    _target_names

RULE_ID = "G035"


def _loop_assigned(loop) -> Set[str]:
    out: Set[str] = set()
    if isinstance(loop, ast.For):
        out.update(_target_names(loop.target))
    for stmt in loop.body:
        out.update(_assigned_names(stmt))
    return out


def check_program(program: ProgramModel, scanned: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()

    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None:
            continue
        info = module_info(model)
        # module aliases + interprocedurally-resolved ones (pattern a
        # needs both: the loop-carry gap exists for either kind)
        donating: Dict[str, object] = {
            name: wrap for name, wrap in model.jit_aliases.items()
            if wrap.donate_argnums}
        donating.update(info.donating)

        def emit(node: ast.AST, msg: str, sev: str) -> None:
            if (path, node.lineno) in seen:
                return
            seen.add((path, node.lineno))
            findings.append(Finding(path, node.lineno, RULE_ID, sev, msg,
                                    model.snippet(node.lineno)))

        if not donating:
            continue
        for fn in model.functions:
            if model.is_traced(fn):
                continue
            # (a) loop-carried donation without a rebind in the loop body
            loops_checked: Set[Tuple[int, str]] = set()
            for node in walk_scope(fn):
                if not isinstance(node, (ast.For, ast.While)):
                    continue
                rebound = None
                for call in ast.walk(node):
                    if not isinstance(call, ast.Call):
                        continue
                    victim = _donated_name(call, donating)
                    if victim is None:
                        continue
                    if rebound is None:
                        rebound = _loop_assigned(node)
                    if victim in rebound:
                        continue
                    key = (node.lineno, victim)
                    if key in loops_checked:
                        continue
                    loops_checked.add(key)
                    emit(call,
                         f"`{victim}` is donated to a jitted call every "
                         f"iteration but never rebound in the loop body — "
                         f"iteration 2 passes a buffer XLA already owns "
                         f"(deleted-array error); carry the result "
                         f"(`{victim} = step(..., {victim})`) or drop "
                         f"donation", Severity.ERROR)
            # (b) straight-line scan over the resolved-only aliases
            if info.donating:
                _scan_block(model, fn, list(fn.body), info.donating, emit)
    return findings
