"""G028 silent-fallback: an except clause degrades service without a LOUD reason.

The repo convention — "fall back LOUDLY" (docs/serving.md) — says every
handler that switches to degraded work (stale artifact, skipped eval,
default scores, disabled feature) must surface a *named* reason:
``warnings.warn``, a logging call, a trace instant, a metrics counter,
or the exception value itself stored somewhere a human will read. Until
now only point tests enforced it; a quiet ``except Exception:
use_stale()`` ships a silent data-quality regression.

Flagged: a handler that does real work (not just ``pass`` — that's
G029) but neither re-raises, surfaces loudly (``config.LOUD_CALL_TAILS``
/ ``LOUD_CALL_ROOTS``), resolves a Future (``set_exception`` hands the
reason to the caller), nor uses the bound exception variable. Two idioms are exempt: handlers
catching only API-probe types (``ImportError`` and friends,
``config.PROBE_EXCEPTION_TYPES``) — version probing — and a NARROW
catch whose whole body substitutes one literal default
(``except ValueError: n = 20``) — a total function, not a degradation.

Machine fix: splice ``warn(...)`` ahead of the handler's first simple
statement (plus ``from warnings import warn``), naming the caught
exception when the handler binds one.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .. import config
from ..exceptionflow import classify_handler, in_exception_scope
from ..findings import Edit, Finding, Fix, Severity
from ..modmodel import ModuleModel
from ..program import ProgramModel

RULE_ID = "G028"

_SIMPLE_STMTS = (ast.Expr, ast.Assign, ast.AugAssign, ast.AnnAssign,
                 ast.Return, ast.Delete, ast.Global, ast.Nonlocal)


def _probe_only(info) -> bool:
    return info.names is not None and all(
        n in config.PROBE_EXCEPTION_TYPES for n in info.names)


def _all_constants(value: ast.expr) -> bool:
    # a bare Name counts: `except ValueError: return default` substitutes
    # the already-bound default, the same total-function shape
    if isinstance(value, (ast.Tuple, ast.List)):
        return all(isinstance(e, (ast.Constant, ast.Name))
                   for e in value.elts)
    return isinstance(value, (ast.Constant, ast.Name))


def _constant_default(handler: ast.ExceptHandler) -> bool:
    """A single-statement handler substituting a literal default
    (``except ValueError: n = 20`` / ``return None``): the
    parse-with-default total-function idiom, not a degraded path —
    exempt when the catch is NARROW (a broad catch hiding behind a
    default still deserves a named reason or a rationale)."""
    if len(handler.body) != 1:
        return False
    stmt = handler.body[0]
    if isinstance(stmt, ast.Return):
        return stmt.value is None or _all_constants(stmt.value)
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
        return _all_constants(stmt.value)
    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        return _all_constants(stmt.value)
    return False


def _warn_fix(model: ModuleModel, handler: ast.ExceptHandler,
              info) -> Optional[Fix]:
    """Prepend a warn() to the handler's first statement when it is a
    single-line simple statement (a compound or multi-line first
    statement can't take a within-line splice)."""
    first = handler.body[0]
    if not isinstance(first, _SIMPLE_STMTS) \
            or first.lineno != getattr(first, "end_lineno", first.lineno):
        return None
    old = model.snippet(first.lineno)
    if not old or old.startswith("warn"):
        return None
    caught = "/".join(info.names) if info.names else "exception"
    if info.exc_var:
        splice = (f"warn(f\"G028 fallback: {caught}: "
                  f"{{{info.exc_var}!r}}\", RuntimeWarning); ")
    else:
        splice = f"warn(\"G028 fallback on {caught}\", RuntimeWarning); "
    return Fix(edits=(Edit(first.lineno, old, splice + old),),
               add_import=("warnings", "warn"))


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None or not in_exception_scope(path, model):
            continue
        for node in ast.walk(model.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            info = classify_handler(node)
            if not info.has_work or info.reraises or info.loud \
                    or info.resolves_future or info.uses_exc \
                    or _probe_only(info):
                continue
            if not info.broad and _constant_default(node):
                continue
            caught = ", ".join(info.names) if info.names else "everything"
            findings.append(Finding(
                path, node.lineno, RULE_ID, Severity.WARNING,
                f"silent fallback: this handler (catching {caught}) "
                f"switches to degraded work without surfacing a reason — "
                f"warn/log/count the failure or store the exception so "
                f"the degradation is diagnosable (repo convention: fall "
                f"back LOUDLY)", model.snippet(node.lineno),
                fix=_warn_fix(model, node, info)))
    return findings
