"""G027 future-leak: a handed-out Future that an exception path never resolves.

The serving stack's contract is promise-shaped: ``submit`` hands the
caller a Future and the batcher/cache/coalescing machinery guarantees
someone eventually calls ``set_result`` or ``set_exception`` on it. A
statement that can raise *after* the Future escaped (queued, stored on
self, registered with the cache) and *before* its resolution breaks the
contract silently — the client blocks in ``Future.result()`` forever,
the hung-client bug class PR 13/15 each fixed one instance of by hand.

The rule uses the exception-flow model's Future lifecycle: a direct
``x = Future()`` local that escapes (passed to a call, stored into an
attribute/subscript) is flagged at every statement that can provably
raise out of the owner (explicit ``raise`` or a resolvable callee with a
non-empty raise summary) after the escape, unless the raise is covered
by a handler or ``finally`` that resolves the Future, or a
straight-line resolution already ran. Returning a Future is a hand-off
of the resolution duty, not an escape.

Scope: serving/pipeline/runtime plus ``# graftcheck: failure-path-module``.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ..exceptionflow import get_model, in_exception_scope
from ..findings import Finding, Severity
from ..modmodel import dotted_name, walk_scope
from ..program import ProgramModel

RULE_ID = "G027"

_RESOLVE_TAILS = ("set_result", "set_exception")


def _ancestors(node: ast.AST, fn: ast.AST):
    cur = getattr(node, "graftcheck_parent", None)
    while cur is not None and cur is not fn:
        yield cur
        cur = getattr(cur, "graftcheck_parent", None)


def _escape_line(fn: ast.AST, name: str) -> Optional[int]:
    """First line where the Future named ``name`` leaves the owner's
    hands: passed as an argument, or stored into an attr/subscript."""
    first: Optional[int] = None

    def note(line: int) -> None:
        nonlocal first
        if first is None or line < first:
            first = line

    for node in walk_scope(fn):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            if callee.split(".", 1)[0] == name:
                continue  # a method ON the future is not an escape
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == name:
                    note(node.lineno)
        elif isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Name) and node.value.id == name:
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        note(node.lineno)
    return first


def _resolutions(fn: ast.AST, name: str) -> List[ast.Call]:
    out = []
    for node in walk_scope(fn):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d is not None and d.split(".", 1)[0] == name \
                    and d.rsplit(".", 1)[-1] in _RESOLVE_TAILS:
                out.append(node)
    return out


def _linear(node: ast.AST, fn: ast.AST) -> bool:
    """Executed unconditionally on the owner's straight-line path: no
    branch, loop, or handler between the node and the function."""
    return not any(isinstance(a, (ast.If, ast.While, ast.For,
                                  ast.AsyncFor, ast.ExceptHandler))
                   for a in _ancestors(node, fn))


def _subtree_resolves(nodes, name: str) -> bool:
    for root in nodes:
        for node in ast.walk(root):
            if isinstance(node, ast.Call):
                d = dotted_name(node.func)
                if d is not None and d.split(".", 1)[0] == name \
                        and d.rsplit(".", 1)[-1] in _RESOLVE_TAILS:
                    return True
    return False


def _covered(site: ast.AST, fn: ast.AST, name: str) -> bool:
    """A Try around the raising site resolves the Future on unwind —
    in a handler body or a finally block."""
    child = site
    for anc in _ancestors(site, fn):
        if isinstance(anc, ast.Try):
            # `child` is the chain element directly under the Try: an
            # ExceptHandler when the site raises from a handler body (the
            # try's own handlers no longer apply), a body stmt otherwise
            if _subtree_resolves(anc.finalbody, name):
                return True
            if not isinstance(child, ast.ExceptHandler) \
                    and _subtree_resolves(list(anc.handlers), name):
                return True
        child = anc
    return False


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    ef = get_model(program)
    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None or not in_exception_scope(path, model):
            continue
        for fn in model.functions:
            futures = ef.future_locals(fn)
            if not futures:
                continue
            raise_sites: Optional[List[Tuple[str, ast.AST]]] = None
            for name, created in sorted(futures.items()):
                escape = _escape_line(fn, name)
                if escape is None:
                    continue
                if raise_sites is None:
                    raise_sites = list(ef.escaping_raises(path, fn))
                linear_res = [r.lineno for r in _resolutions(fn, name)
                              if _linear(r, fn)]
                seen_lines: Set[int] = set()
                for exc, site in raise_sites:
                    line = site.lineno
                    if line <= escape or line in seen_lines:
                        continue
                    if any(r < line for r in linear_res):
                        continue  # already resolved on this path
                    if _covered(site, fn, name):
                        continue
                    seen_lines.add(line)
                    findings.append(Finding(
                        path, line, RULE_ID, Severity.ERROR,
                        f"Future `{name}` (created line {created.lineno}, "
                        f"handed out line {escape}) can leak: this "
                        f"statement can raise {exc} and unwind past its "
                        f"resolution — the holder blocks in result() "
                        f"forever; resolve it in an except/finally "
                        f"(set_exception) before letting the unwind "
                        f"continue", model.snippet(line)))
    return findings
