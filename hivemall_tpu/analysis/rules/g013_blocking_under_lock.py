"""G013 blocking-under-lock: device sync / IO / jit dispatch while a lock is held.

On the serving hot path every request handler funnels through a handful
of locks (batcher CV, registry lock, metrics registry). A blocking call
made while one of them is held — ``jax.device_get`` /
``.block_until_ready()`` (device sync), a cold jit dispatch or
``warmup()`` (compiles under the lock), file/socket IO, ``time.sleep``,
``Future.result()`` / ``set_result()`` / ``set_exception()`` (the last
two run done-callbacks synchronously), a thread ``join`` — serializes
every other thread behind that lock: the hot-swap-stall failure mode
where one deploy freezes all in-flight predictions.

Scope: ``hivemall_tpu/serving/`` and ``runtime/metrics*`` (the
configured hot path) plus modules opting in with
``# graftcheck: serving-module``. ``cv.wait()`` on the *held* condition
variable is the sanctioned idiom (it releases the lock) and is never
flagged; lock acquisitions under a lock are G016's subject, not G013's.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .. import config
from ..concurrency import CallEv, get_model, in_g013_scope
from ..findings import Finding, Severity
from ..program import ProgramModel

RULE_ID = "G013"


def _receiver_lock(dotted: str) -> Optional[str]:
    """The self-lock field name for ``self.X.wait``-shaped callees."""
    parts = dotted.split(".")
    if parts[0] == "self" and len(parts) == 3:
        return parts[1]
    if len(parts) == 2:
        return "@" + parts[0]
    return None


def _blocking_reason(program: ProgramModel, path: str, ev: CallEv
                     ) -> Optional[str]:
    d = ev.dotted
    tail = d.rsplit(".", 1)[-1]
    root = d.split(".", 1)[0]
    if tail == "wait":
        rec = _receiver_lock(d)
        if rec is not None and rec in ev.held:
            return None  # waiting on the held CV releases it: the idiom
        return "a blocking wait() on an object whose lock this thread " \
               "does not hold"
    if tail in ("acquire", "notify", "notify_all", "release"):
        return None  # lock protocol; nesting is G016's subject
    if d == "open":
        return "file IO (open)"
    if tail in config.BLOCKING_DEVICE_TAILS:
        return f"a device synchronization ({tail})"
    if tail in config.BLOCKING_IO_TAILS and root not in \
            config.BLOCKING_SAFE_ROOTS:
        return f"blocking IO ({tail})"
    if "." in d and root not in config.BLOCKING_SAFE_ROOTS:
        if tail in config.BLOCKING_FUTURE_TAILS:
            if tail in ("set_result", "set_exception"):
                return f"Future.{tail}() — done-callbacks run " \
                       f"synchronously on this thread, under the lock"
            if tail == "result":
                return "Future.result() — blocks until another thread " \
                       "completes"
            if tail == "join":
                return "a thread join"
            return f"a blocking rendezvous ({tail})"
        if tail in config.JITTED_ATTR_CALLEES:
            return f"a jitted dispatch ({tail})"
    if tail in config.BLOCKING_JIT_TAILS:
        return f"a jit dispatch/compile trigger ({tail})"
    if "." not in d:
        got = program.resolve_fn(path, d, ev.node)
        if got is not None:
            t_model = program.modules.get(got[0])
            if t_model is not None and got[1] in t_model.traced:
                return f"a call to the traced/jitted function {d}()"
    return None


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    cm = get_model(program)
    seen: Set[Tuple[str, int, str]] = set()

    def flag(path: str, ev: CallEv, reason: str) -> None:
        key = (path, ev.line, reason)
        if key in seen:
            return
        seen.add(key)
        model = program.modules[path]
        locks = sorted(lk.lstrip("@") for lk in ev.held)
        findings.append(Finding(
            path, ev.line, RULE_ID, Severity.ERROR,
            f"{reason} while holding `{'`, `'.join(locks)}` — every thread "
            f"that needs the lock stalls behind this call; move it outside "
            f"the locked region (collect under the lock, act after "
            f"releasing)", model.snippet(ev.line)))

    def sweep(path: str, events) -> None:
        for ev in events:
            if not ev.held:
                continue
            reason = _blocking_reason(program, path, ev)
            if reason is not None:
                flag(path, ev, reason)

    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None or not in_g013_scope(path, model):
            continue
        for (c_path, _), cls in sorted(cm.classes.items()):
            if c_path == path:
                sweep(path, cls.eff_calls)
        sweep(path, (ev for f_path, _, ev in cm.fn_calls
                     if f_path == path))
    return findings
