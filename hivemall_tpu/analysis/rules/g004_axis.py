"""G004 axis-name-mismatch: collective axis literals vs the declared mesh.

``jax.lax.psum(x, "worker")`` against a mesh whose axis is ``"workers"``
fails only at run time, inside shard_map, on hardware. The mesh axis
registry is small and static — ``parallel/mesh.py`` declares WORKER_AXIS /
SHARD_AXIS and every trainer threads those through — so any *string
literal* axis name that is not a declared axis is a typo.

Declared axes = config.DEFAULT_AXIS_NAMES, plus (when mesh.py is in the
scanned set or importable) its module-level string constants, plus literal
axis tuples passed to ``Mesh(...)`` / ``make_mesh*(axis_name=...)`` in the
module under scan (modules may define private meshes). Variable axis names
are trusted — they trace back to the registry by construction.
"""

from __future__ import annotations

import ast
import os
from typing import List, Set

from .. import config
from ..findings import Finding, Severity
from ..modmodel import ModuleModel, dotted_name

RULE_ID = "G004"

_AXIS_KWARGS = ("axis_name", "axis_names", "replica_axis", "shard_axis")


_MESH_AXES_CACHE: dict = {}


def _mesh_file_axes() -> Set[str]:
    """Module-level string constants of parallel/mesh.py, parsed (not
    imported — graftcheck must not pull in jax) and mtime-cached: a full
    -tree scan calls this once per scanned module."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    mesh_py = os.path.join(os.path.dirname(here), "parallel", "mesh.py")
    axes: Set[str] = set()
    try:
        mtime = os.path.getmtime(mesh_py)
        cached = _MESH_AXES_CACHE.get(mesh_py)
        if cached is not None and cached[0] == mtime:
            return cached[1]
        with open(mesh_py, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return axes
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Constant) \
                and isinstance(node.value.value, str) \
                and any(isinstance(t, ast.Name) and t.id.endswith("_AXIS")
                        for t in node.targets):
            axes.add(node.value.value)
    _MESH_AXES_CACHE[mesh_py] = (mtime, axes)
    return axes


def _declared_axes(model: ModuleModel) -> Set[str]:
    axes = set(config.DEFAULT_AXIS_NAMES) | _mesh_file_axes()
    for node in ast.walk(model.tree):
        # local string constants named *_AXIS count as declarations
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Constant) \
                and isinstance(node.value.value, str):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id.endswith("_AXIS"):
                    axes.add(node.value.value)
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func) or ""
        tail = callee.rsplit(".", 1)[-1]
        # only mesh CONSTRUCTORS declare axes; axis kwargs on collectives
        # are uses and must validate against the declarations
        if tail != "Mesh" and not tail.startswith("make_mesh"):
            continue
        if tail == "Mesh" and len(node.args) >= 2:
            names = node.args[1]
            if isinstance(names, (ast.Tuple, ast.List)):
                for elt in names.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str):
                        axes.add(elt.value)
        for kw in node.keywords:
            if kw.arg in _AXIS_KWARGS and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                axes.add(kw.value.value)
    return axes


def check(model: ModuleModel) -> List[Finding]:
    axes = _declared_axes(model)
    findings: List[Finding] = []
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func) or ""
        tail = callee.rsplit(".", 1)[-1]
        if tail not in config.COLLECTIVE_CALLS:
            continue
        # axis name: second positional (psum(x, axis)) or axis_name= kwarg;
        # axis_index takes it first.
        cand = None
        if tail == "axis_index":
            cand = node.args[0] if node.args else None
        elif len(node.args) >= 2:
            cand = node.args[1]
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis"):
                cand = kw.value
        if isinstance(cand, ast.Constant) and isinstance(cand.value, str) \
                and cand.value not in axes:
            findings.append(Finding(
                model.rel_path, node.lineno, RULE_ID, Severity.ERROR,
                f"collective `{tail}` over axis '{cand.value}' which is not "
                f"a declared mesh axis ({', '.join(sorted(axes))}) — typo'd "
                f"axis names fail only at run time inside shard_map",
                model.snippet(node.lineno)))
    return findings
