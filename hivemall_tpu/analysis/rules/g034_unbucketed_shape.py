"""G034 unbucketed-shape-dispatch: novel shapes reaching jitted callables.

A jitted scorer compiles once per input *shape*. The serving stack keeps
that bounded with the bucket ladder: every request batch is padded to one
of a fixed set of widths (``pad_to_bucket`` picks the width,
``bucket_rows``/``pad_rows_to_multiple`` pad the arrays) before dispatch,
and the warmup matrix pre-compiles exactly those shapes. A call site that
feeds a jitted callable an array sliced to a *data-dependent* length
bypasses the ladder — one fresh compile per novel length, in production,
after warmup said everything was compiled.

Scope: the jit-hot modules (serving dispatch + kernels/ops,
``traceflow.in_traceflow_scope``). Flagged only on proof: the callee is a
known jit alias (``name = jax.jit(...)``) or a def traced in its own
module, and the argument is (or was last assigned from) a subscript with a
non-literal slice bound that is not routed through a shape canonicalizer
(``config.SHAPE_CANONICALIZERS`` — a bound computed by ``pad_to_bucket``
IS the ladder). Machine fix for the single-argument shape:
``scorer(batch)`` -> ``scorer(bucket_rows(batch))[:batch.shape[0]]``.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .. import config
from ..findings import Edit, Finding, Fix, Severity
from ..modmodel import dotted_name, walk_scope
from ..program import ProgramModel
from ..traceflow import in_traceflow_scope

RULE_ID = "G034"


def _routed(expr: Optional[ast.AST]) -> bool:
    if expr is None:
        return False
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            if callee.rsplit(".", 1)[-1] in config.SHAPE_CANONICALIZERS:
                return True
    return False


def _dynamic_bound(program, model, path: str, expr: ast.expr,
                   scope) -> Optional[ast.expr]:
    """The offending non-literal slice bound when ``expr`` (or the value
    its name was last assigned from) is an unrouted dynamic-length slice."""
    node: ast.AST = expr
    if isinstance(node, ast.Name):
        assign = program._find_assignment(model, node.id, scope)
        if assign is None:
            return None
        node = assign
    if not isinstance(node, ast.Subscript) or _routed(node):
        return None
    sl = node.slice
    if not isinstance(sl, ast.Slice):
        return None
    for bound in (sl.lower, sl.upper):
        if bound is None or isinstance(bound, ast.Constant):
            continue
        if _routed(bound):
            continue
        if isinstance(bound, ast.Name):
            # a bound assigned from pad_to_bucket(...) IS bucket-routed
            b_assign = program._find_assignment(model, bound.id, scope)
            if b_assign is not None and _routed(b_assign):
                continue
        return bound
    return None


def _is_jitted_callee(program, model, path: str, call: ast.Call) -> bool:
    callee = dotted_name(call.func)
    if callee is None:
        return False
    if callee in model.jit_aliases:
        return True
    if "." in callee:
        return False
    got = program.resolve_fn(path, callee, call)
    if got is None:
        return False
    t_model = program.modules.get(got[0])
    return t_model is not None and got[1] in t_model.traced


def _bucket_fix(model, call: ast.Call) -> Optional[Fix]:
    """Single-line, single-positional-argument calls get the mechanical
    bucket routing; anything wider is reported for a hand fix."""
    if len(call.args) != 1 or call.keywords \
            or not isinstance(call.args[0], ast.Name):
        return None
    if call.lineno != getattr(call, "end_lineno", call.lineno):
        return None
    old = ast.get_source_segment(model.source, call)
    callee_src = ast.get_source_segment(model.source, call.func)
    arg = call.args[0].id
    if not old or not callee_src or old not in model.lines[call.lineno - 1]:
        return None
    new = f"{callee_src}(bucket_rows({arg}))[:{arg}.shape[0]]"
    return Fix(edits=(Edit(call.lineno, old, new),),
               add_import=("hivemall_tpu.core.batch", "bucket_rows"))


def check_program(program: ProgramModel, scanned: Set[str]) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()

    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None or not in_traceflow_scope(path, model):
            continue
        for fn in model.functions:
            if model.is_traced(fn):
                continue  # shapes inside a trace are already fixed
            for call in walk_scope(fn):
                if not isinstance(call, ast.Call) \
                        or not _is_jitted_callee(program, model, path, call):
                    continue
                for arg in call.args:
                    if isinstance(arg, ast.Starred):
                        break
                    bound = _dynamic_bound(program, model, path, arg, fn)
                    if bound is None:
                        continue
                    if (path, call.lineno) in seen:
                        break
                    seen.add((path, call.lineno))
                    callee = dotted_name(call.func)
                    bound_src = ast.get_source_segment(model.source,
                                                       bound) or "?"
                    findings.append(Finding(
                        path, call.lineno, RULE_ID, Severity.ERROR,
                        f"jitted `{callee}` fed a slice with data-dependent "
                        f"bound `{bound_src}` — one fresh compile per novel "
                        f"length, bypassing the warmup matrix; route the "
                        f"batch through the bucket ladder (bucket_rows / "
                        f"pad_to_bucket) first",
                        model.snippet(call.lineno),
                        fix=_bucket_fix(model, call)))
                    break
    return findings
