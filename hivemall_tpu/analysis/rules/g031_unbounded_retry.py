"""G031 unbounded-retry: a retry loop with no attempt cap or no backoff.

The bench.py TPU-probe pathology, generalized: a ``while True:`` loop
whose except handler neither raises, breaks, nor returns retries
*forever* — a persistent failure (bad artifact, dead endpoint) becomes
a 100%-CPU busy spin that also hammers the failing dependency. And a
retry that IS bounded but sleeps nowhere between attempts burns its
whole budget in microseconds, so the bound might as well not exist.

Flagged, in the failure-path scope:

- **no cap**: ``while True`` (or ``while 1``) containing a handler with
  no ``raise``/``break``/``return`` anywhere in its body — nothing ever
  stops the loop on persistent failure;
- **no backoff**: a retry loop (``while True`` with an escaping
  handler, or ``for _ in range(n)`` with a continuing handler) where
  neither the handler nor the loop body sleeps or waits
  (``config.BACKOFF_CALL_TAILS``) before the next attempt.

``cv.wait(timeout)`` counts as backoff — blocking on a condition
variable IS the well-behaved form of waiting. No machine fix: the right
cap and delay are policy, not syntax.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .. import config
from ..exceptionflow import classify_handler, get_model, in_exception_scope
from ..findings import Finding, Severity
from ..modmodel import dotted_name, walk_scope
from ..program import ProgramModel

RULE_ID = "G031"


def _is_while_true(node: ast.While) -> bool:
    return isinstance(node.test, ast.Constant) and bool(node.test.value)


def _is_range_for(node: ast.For) -> bool:
    if not isinstance(node.iter, ast.Call):
        return False
    return (dotted_name(node.iter.func) or "").rsplit(".", 1)[-1] == "range"


def _has_exit(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Break, ast.Return)):
            return True
    return False


def _can_fall_through(handler: ast.ExceptHandler) -> bool:
    """The handler can reach the next loop iteration: an explicit
    ``continue``, or a body that does not end in raise/return/break."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Continue):
            return True
    last = handler.body[-1]
    return not isinstance(last, (ast.Raise, ast.Return, ast.Break))


def _has_backoff(ef, path: str, root: ast.AST) -> bool:
    """A sleep/wait lexically in the loop, or one call deep: a server
    loop whose take-next-item helper blocks on a CV (the batcher shape)
    is paced by that wait even though the wait is not in the loop body."""
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        d = dotted_name(node.func)
        if d is None:
            continue
        if d.rsplit(".", 1)[-1] in config.BACKOFF_CALL_TAILS:
            return True
        got = ef.resolve_callee(path, node, d)
        if got is not None:
            t_model = ef.program.modules.get(got[0])
            if t_model is not None:
                for sub in walk_scope(got[1]):
                    if isinstance(sub, ast.Call):
                        sd = dotted_name(sub.func)
                        if sd is not None and sd.rsplit(".", 1)[-1] in \
                                config.BACKOFF_CALL_TAILS:
                            return True
    return False


def _retry_handlers(loop: ast.AST) -> List[ast.ExceptHandler]:
    """Handlers of Trys directly inside the loop (not nested loops)."""
    out: List[ast.ExceptHandler] = []
    stack = list(loop.body)
    while stack:
        stmt = stack.pop()
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            continue  # inner loop: its own retry structure
        if isinstance(stmt, ast.Try):
            out.extend(stmt.handlers)
            stack.extend(stmt.body + stmt.orelse + stmt.finalbody)
            continue
        for attr in ("body", "orelse"):
            suite = getattr(stmt, attr, None)
            if isinstance(suite, list):
                stack.extend(s for s in suite if isinstance(s, ast.stmt))
    return out


def check_program(program: ProgramModel, scanned: Set[str]
                  ) -> List[Finding]:
    findings: List[Finding] = []
    ef = get_model(program)
    for path in sorted(scanned):
        model = program.modules.get(path)
        if model is None or not in_exception_scope(path, model):
            continue
        for fn in model.functions:
            for node in walk_scope(fn):
                is_spin = isinstance(node, ast.While) \
                    and _is_while_true(node)
                is_capped_for = isinstance(node, ast.For) \
                    and _is_range_for(node)
                if not (is_spin or is_capped_for):
                    continue
                retrying = [h for h in _retry_handlers(node)
                            if _can_fall_through(h)]
                if not retrying:
                    continue  # every handler escapes: not a retry loop
                h = min(retrying, key=lambda h: h.lineno)
                # a handler that DELIVERS the failure (set_exception on a
                # Future, a loud surface) is a serve loop handling per-item
                # errors, not a silent spin — only the backoff arm applies
                uncapped = [r for r in retrying if not _has_exit(r)
                            and not (classify_handler(r).loud
                                     or classify_handler(r).resolves_future)]
                if isinstance(node, ast.While) and uncapped:
                    h = min(uncapped, key=lambda h: h.lineno)
                    findings.append(Finding(
                        path, h.lineno, RULE_ID, Severity.WARNING,
                        "unbounded retry: this handler swallows the "
                        "failure and `while True` re-enters the attempt "
                        "with no cap — a persistent failure retries "
                        "forever; count attempts and raise past a limit",
                        model.snippet(h.lineno)))
                elif not _has_backoff(ef, path, node):
                    findings.append(Finding(
                        path, h.lineno, RULE_ID, Severity.WARNING,
                        "retry without backoff: the loop re-attempts "
                        "immediately after a failure — add a sleep/wait "
                        "between attempts so a failing dependency is not "
                        "hammered at CPU speed",
                        model.snippet(h.lineno)))
    return findings
