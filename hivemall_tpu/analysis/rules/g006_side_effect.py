"""G006 untraced-side-effect: host effects baked into traced functions.

A jitted function's Python body runs ONCE, at trace time. ``print``,
metrics-counter increments, ``time.*`` reads, ``np.random`` draws, and
mutation of free (closure) Python state inside a traced function execute
once per *compile*, not once per *step* — the counter silently stops
counting, the print lies, the mutation races the trace cache. Use
``jax.debug.print`` / ``jax.debug.callback`` for real per-step effects, or
hoist the effect to the host loop.

Flagged inside traced functions:
- calls to ``print`` / ``time.*`` / ``logging.*`` / ``np.random.*`` /
  known metrics methods (``.increment()`` / ``.set_gauge()`` /
  ``.record()``);
- assignment to subscripts/attributes of free variables and
  ``.append``/``.update``/``.add`` on free variables (closure mutation);
- ``global`` / ``nonlocal`` declarations.

``jax.debug.*`` is the sanctioned escape hatch and is never flagged.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .. import config
from ..findings import Finding, Severity
from ..modmodel import ModuleModel, dotted_name, walk_scope

RULE_ID = "G006"

_MUTATING_METHODS = ("append", "update", "add", "extend", "insert", "pop",
                     "setdefault", "write")


def _local_names(fn: ast.AST) -> Set[str]:
    names: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    for node in walk_scope(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, (ast.For,)) and isinstance(node.target,
                                                         ast.Name):
            names.add(node.target.id)
        elif isinstance(node, ast.comprehension):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    names.add(n.id)
    return names


def check(model: ModuleModel) -> List[Finding]:
    findings: List[Finding] = []

    def emit(node: ast.AST, msg: str, sev: str = Severity.ERROR) -> None:
        findings.append(Finding(model.rel_path, node.lineno, RULE_ID, sev,
                                msg, model.snippet(node.lineno)))

    for fn in model.functions:
        if not model.is_traced(fn):
            continue
        locals_ = _local_names(fn)
        for node in walk_scope(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                emit(node, f"`{'global' if isinstance(node, ast.Global) else 'nonlocal'}` "
                           f"mutation inside jitted `{fn.name}` runs once "
                           f"per compile, not per step")
            elif isinstance(node, ast.Call):
                callee = dotted_name(node.func) or ""
                root = callee.split(".", 1)[0]
                tail = callee.rsplit(".", 1)[-1]
                if callee.startswith("jax.debug."):
                    continue  # the sanctioned per-step effect
                if callee in config.SIDE_EFFECT_CALLS:
                    emit(node, f"`{callee}` inside jitted `{fn.name}` fires "
                               f"at trace time only — use jax.debug.print "
                               f"for per-step output")
                elif root in config.SIDE_EFFECT_ATTR_ROOTS:
                    emit(node, f"`{callee}` inside jitted `{fn.name}` reads "
                               f"host state at trace time only")
                elif callee.startswith(("np.random.", "numpy.random.")):
                    emit(node, f"`{callee}` inside jitted `{fn.name}` draws "
                               f"ONCE at trace time — every step replays the "
                               f"same numbers; thread a jax.random key")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in config.SIDE_EFFECT_METHODS:
                    emit(node, f"metrics call `.{node.func.attr}()` inside "
                               f"jitted `{fn.name}` counts compiles, not "
                               f"steps — increment in the host loop")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATING_METHODS \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id not in locals_ \
                        and isinstance(getattr(node, "graftcheck_parent",
                                               None), ast.Expr):
                    emit(node, f"mutation of free variable "
                               f"`{node.func.value.id}.{node.func.attr}(...)`"
                               f" inside jitted `{fn.name}` happens at trace "
                               f"time only")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    base = tgt
                    while isinstance(base, (ast.Subscript, ast.Attribute)):
                        base = base.value
                    if isinstance(base, ast.Name) and base.id not in locals_ \
                            and not isinstance(tgt, ast.Name):
                        emit(tgt, f"write into free variable `{base.id}` "
                                  f"inside jitted `{fn.name}` mutates host "
                                  f"state at trace time only")
    return findings
