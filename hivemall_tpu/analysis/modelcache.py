"""Shared ModuleModel construction cache: memory layer + on-disk pickle.

Every graftcheck scan needs a ModuleModel per file — and before v6 each
``analyze_paths`` call re-parsed every scanned file even when the same
file had just been parsed as package context, so a full-tree scan paid
the ~1.5 s parse+build cost twice and the test suite's ~40 in-process
scans paid it over and over. This module makes model construction a
single cached path with two layers:

- **memory** (always on): ``{abspath: (mtime, size, rel_path, model)}``
  — the old ``program._PKG_CACHE`` semantics, now shared by package
  context AND scanned files. Because the SAME ModuleModel object is
  returned while the file is unchanged, per-module analysis products
  attached as ``_graftcheck_*`` attributes (concurrency class tables,
  FFI decls, raised-exception summaries) survive across scans — that
  cache-attachment contract is what keeps repeated scans cheap.
- **disk** (``.graftcheck_cache/models-pyXY.pkl`` at the repo root):
  pickled models keyed on each file's **sha256**, so invalidation is
  per file and a fresh process (each ``scripts/lint.sh`` run, each
  pytest worker) skips parsing files it has seen before. The file name
  carries the Python minor version — AST pickles are not portable
  across versions — and the payload carries a schema number. Only
  files inside the ``hivemall_tpu`` package persist: test fixtures and
  tmpdir scratch files would churn the store every run for no reuse.

``_graftcheck_*`` memo attributes are STRIPPED (from shallow copies —
the live models keep their caches) before pickling: several are keyed
by ``id()`` of AST nodes, and object ids do not survive a pickle
round-trip, so persisting them would resurrect tables whose keys can
collide with unrelated nodes in the new process.

Set ``GRAFTCHECK_CACHE=0`` to disable the disk layer (the memory layer
has no staleness modes beyond mtime/size and stays on).
"""

from __future__ import annotations

import ast
import copy
import hashlib
import os
import pickle
import sys
import tempfile
from typing import Dict, Optional, Tuple

from .modmodel import ModuleModel

SCHEMA_VERSION = 1
_MAGIC = "graftcheck-model-cache"

_MEM: Dict[str, Tuple[float, int, str, Optional[ModuleModel]]] = {}
# abspath -> (sha256 hex, rel_path, model); None until loaded
_DISK: Optional[Dict[str, Tuple[str, str, ModuleModel]]] = None
_DIRTY = False


def _enabled() -> bool:
    return os.environ.get("GRAFTCHECK_CACHE", "1") != "0"


def cache_dir() -> str:
    from .program import package_root
    return os.path.join(os.path.dirname(package_root()),
                        ".graftcheck_cache")


def cache_file() -> str:
    return os.path.join(
        cache_dir(), "models-py%d%d.pkl" % sys.version_info[:2])


def _persistable(abspath: str) -> bool:
    from .program import package_root
    return abspath.startswith(package_root() + os.sep)


def _load_disk() -> Dict[str, Tuple[str, str, ModuleModel]]:
    global _DISK
    if _DISK is not None:
        return _DISK
    _DISK = {}
    if not _enabled():
        return _DISK
    try:
        with open(cache_file(), "rb") as fh:
            payload = pickle.load(fh)
        if isinstance(payload, dict) \
                and payload.get("magic") == _MAGIC \
                and payload.get("schema") == SCHEMA_VERSION:
            _DISK = payload["models"]
    except Exception:  # corrupt/absent/foreign cache: rebuild from source
        _DISK = {}
    return _DISK


def cached_model(fs_path: str, rel_path: str) -> Optional[ModuleModel]:
    """The ModuleModel for a file, or None when it is unreadable or does
    not parse (callers that need the precise error re-read the file —
    failures are rare, so the double read costs nothing in practice)."""
    global _DIRTY
    ap = os.path.abspath(fs_path)
    try:
        st = os.stat(ap)
    except OSError:
        return None
    hit = _MEM.get(ap)
    if hit is not None and hit[0] == st.st_mtime and hit[1] == st.st_size \
            and hit[2] == rel_path:
        return hit[3]
    try:
        with open(ap, "rb") as fh:
            raw = fh.read()
    except OSError:
        return None
    model: Optional[ModuleModel] = None
    persist = _persistable(ap) and _enabled()
    disk = _load_disk() if persist else {}
    digest = hashlib.sha256(raw).hexdigest()
    entry = disk.get(ap)
    if entry is not None and entry[0] == digest and entry[1] == rel_path:
        model = entry[2]
    else:
        try:
            source = raw.decode("utf-8")
            model = ModuleModel(rel_path, source,
                                ast.parse(source, filename=rel_path))
        except (SyntaxError, ValueError, UnicodeDecodeError):
            model = None
        if persist:
            if model is not None:
                disk[ap] = (digest, rel_path, model)
            else:
                disk.pop(ap, None)
            _DIRTY = True
    _MEM[ap] = (st.st_mtime, st.st_size, rel_path, model)
    return model


def _stripped(model: ModuleModel) -> ModuleModel:
    clean = copy.copy(model)
    for attr in [a for a in vars(clean) if a.startswith("_graftcheck_")]:
        delattr(clean, attr)
    return clean


def save() -> None:
    """Atomically write the disk layer when it changed this process."""
    global _DIRTY
    if not _DIRTY or not _enabled() or _DISK is None:
        return
    _DIRTY = False
    payload = {
        "magic": _MAGIC, "schema": SCHEMA_VERSION,
        "models": {ap: (digest, rel, _stripped(model))
                   for ap, (digest, rel, model) in _DISK.items()},
    }
    tmp = None
    try:
        os.makedirs(cache_dir(), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=cache_dir(), suffix=".tmp")
        with os.fdopen(fd, "wb") as fh:
            pickle.dump(payload, fh, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, cache_file())
    except OSError:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def clear() -> None:
    """Drop both layers (tests use this to exercise cold paths)."""
    global _DISK, _DIRTY
    _MEM.clear()
    _DISK = None
    _DIRTY = False
    try:
        os.unlink(cache_file())
    except OSError:
        pass
