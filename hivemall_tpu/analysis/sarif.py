"""SARIF 2.1.0 output: graftcheck findings as CI annotations.

SARIF (Static Analysis Results Interchange Format) is the log format
CI systems ingest to render findings as inline review annotations.
``python -m hivemall_tpu.analysis --format sarif`` emits one run whose
``results`` are the findings the baseline gate would report (all of
them under ``--no-baseline``) — the same set that drives the exit code,
so the annotations and the gate never disagree.

Shape notes (the parts consumers actually key on):

- ``tool.driver.rules`` carries every registered rule with its one-line
  doc; ``results[].ruleIndex`` points back into that array;
- levels map severity directly (``error`` / ``warning``);
- ``partialFingerprints`` uses the baseline identity ``(rule, path,
  snippet)`` — stable across unrelated edits, exactly like
  ``analysis/baseline.py`` — so CI dedupes findings the same way the
  baseline does.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Sequence

from .findings import Finding, Severity

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://docs.oasis-open.org/sarif/sarif/v2.1.0/errata01/"
                "os/schemas/sarif-schema-2.1.0.json")
TOOL_VERSION = "7.0"
INFO_URI = "https://github.com/hivemall-tpu/hivemall-tpu" \
           "/blob/main/docs/static_analysis.md"

_LEVELS = {Severity.ERROR: "error", Severity.WARNING: "warning"}


def _fingerprint(f: Finding) -> str:
    key = f"{f.rule}\x1f{f.path}\x1f{f.snippet}"
    return hashlib.sha256(key.encode("utf-8")).hexdigest()[:32]


def _location(path: str, line: int, snippet: str) -> dict:
    return {
        "physicalLocation": {
            "artifactLocation": {
                "uri": path,
                "uriBaseId": "SRCROOT",
            },
            "region": {
                "startLine": max(1, line),
                "snippet": {"text": snippet},
            },
        },
    }


def render_sarif(findings: Sequence[Finding]) -> dict:
    from .rules import RULE_DOCS

    rule_ids = sorted(set(RULE_DOCS) | {f.rule for f in findings})
    rule_index: Dict[str, int] = {rid: i for i, rid in enumerate(rule_ids)}
    rules: List[dict] = []
    for rid in rule_ids:
        doc = RULE_DOCS.get(
            rid, "parse failure" if rid == "G000" else rid)
        rules.append({
            "id": rid,
            "name": doc.split(":", 1)[0].strip(),
            "shortDescription": {"text": doc},
            "helpUri": INFO_URI,
            "defaultConfiguration": {"level": "error"},
        })
    results: List[dict] = []
    for f in findings:
        # primary location first; `related` carries the extra ends of a
        # cross-file finding (G025: the C declaration the Python binding
        # drifted from) as further physicalLocations in the same list
        locations = [_location(f.path, f.line, f.snippet)]
        for r_path, r_line, r_snippet in f.related:
            locations.append(_location(r_path, r_line, r_snippet))
        results.append({
            "ruleId": f.rule,
            "ruleIndex": rule_index[f.rule],
            "level": _LEVELS.get(f.severity, "error"),
            "message": {"text": f.message},
            "locations": locations,
            "partialFingerprints": {
                "graftcheckKey/v1": _fingerprint(f),
            },
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "graftcheck",
                    "version": TOOL_VERSION,
                    "informationUri": INFO_URI,
                    "rules": rules,
                },
            },
            "originalUriBaseIds": {
                "SRCROOT": {"description": {
                    "text": "repository root (paths are repo-relative, "
                            "forward slashes)"}},
            },
            "results": results,
        }],
    }
