"""Finding record + inline-suppression parsing.

A finding's identity for baseline purposes is ``(rule, path, snippet)`` —
the stripped source line, not the line number — so unrelated edits above a
known finding don't invalidate the baseline, while any edit to the flagged
line itself surfaces it again.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple


class Severity:
    ERROR = "error"
    WARNING = "warning"

    ORDER = {ERROR: 0, WARNING: 1}


@dataclass(frozen=True)
class Edit:
    """One within-line text replacement: on `line`, the first occurrence of
    `old` becomes `new`. Within-line edits never shift other findings' line
    numbers, so every fix collected in one scan applies in one pass."""
    line: int  # 1-based
    old: str
    new: str


@dataclass(frozen=True)
class WrapFinally:
    """A multi-line repair for G030: indent lines `start`..`release_line-1`
    one level under an inserted ``try:`` and turn the release statement at
    `release_line` into ``finally:`` + the indented release. `release_text`
    is the stripped source of the release line at plan time — the fixer
    re-validates it so a stale plan never rewrites changed code."""
    start: int  # 1-based first line of the wrapped region
    release_line: int  # 1-based line of the X.release() statement
    release_text: str


@dataclass(frozen=True)
class Fix:
    """A machine-applicable repair attached to a finding. `add_import` is
    (module, name) — the fixer merges all requested names per module into
    one import statement and inserts/extends it idempotently. `wrap` is a
    try/finally wrap; wraps shift line numbers, so the fixer applies them
    after every within-line edit, bottom-up."""
    edits: Tuple[Edit, ...] = ()
    add_import: Optional[Tuple[str, str]] = None
    wrap: Optional[WrapFinally] = None


@dataclass(frozen=True)
class Finding:
    path: str  # repo-relative, forward slashes
    line: int  # 1-based
    rule: str  # "G001".."G011" ("G000" = parse failure)
    severity: str  # Severity.*
    message: str
    snippet: str  # stripped source of the flagged line (baseline key)
    # optional autofix; not part of identity/baseline and not serialized
    fix: Optional[Fix] = field(default=None, compare=False)
    # additional (path, line, snippet) locations — G025 points into the C++
    # source alongside the Python declaration; SARIF renders them as extra
    # physicalLocations. Not part of identity/baseline and not serialized.
    related: Tuple[Tuple[str, int, str], ...] = field(default=(),
                                                     compare=False)

    @property
    def key(self):
        return (self.rule, self.path, self.snippet)

    def format(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.severity}] {self.message}")

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "severity": self.severity,
            "message": self.message,
            "snippet": self.snippet,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(path=d["path"], line=int(d.get("line", 0)),
                   rule=d["rule"], severity=d.get("severity", Severity.ERROR),
                   message=d.get("message", ""), snippet=d.get("snippet", ""))


_DISABLE_RE = re.compile(
    r"#\s*graftcheck:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\s]+)")


def parse_suppressions(source: str):
    """Return (per_line, whole_file): per_line maps 1-based line number to the
    set of rule ids disabled on that line; whole_file is the set disabled for
    the entire module (``# graftcheck: disable-file=G00X`` anywhere).
    ``all`` disables every rule."""
    per_line: Dict[int, Set[str]] = {}
    whole_file: Set[str] = set()
    for i, text in enumerate(source.splitlines(), start=1):
        m = _DISABLE_RE.search(text)
        if not m:
            continue
        rules = {r.strip().upper() for r in m.group("rules").split(",")
                 if r.strip()}
        if m.group("file"):
            whole_file |= rules
        else:
            per_line[i] = per_line.get(i, set()) | rules
    return per_line, whole_file


def apply_suppressions(findings: List[Finding], per_line, whole_file
                       ) -> List[Finding]:
    out = []
    for f in findings:
        disabled = whole_file | per_line.get(f.line, set())
        if "ALL" in disabled or f.rule in disabled:
            continue
        out.append(f)
    return out


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message))
