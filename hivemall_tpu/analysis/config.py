"""Codebase-specific knobs for the graftcheck rules.

graftcheck is purpose-built for this repo's JAX idioms: the hot-path module
list, the mesh axis registry, and the jitted-factory naming convention live
here rather than being rediscovered per rule.
"""

from __future__ import annotations

import re

# --- G002: modules whose loops are per-step hot paths -----------------------
# The per-step loops of these modules drive every benchmark; an implicit
# device->host sync there serializes dispatch (BENCH_r01-r05 regressions).
HOT_LOOP_MODULES = (
    "hivemall_tpu/core/engine.py",
    "hivemall_tpu/parallel/sharded_train.py",
    "hivemall_tpu/parallel/mix.py",
    "hivemall_tpu/models/trees/grow.py",
    # the epoch/convergence driver that loops the engine's jitted steps
    "hivemall_tpu/models/base.py",
)

# Methods with these names receive device state / blocks by contract, so
# their parameters are treated as device values even outside a loop.
HOT_FN_RE = re.compile(r"^(step|_step|train_step|epoch)$")

# Calls that force an implicit device->host transfer when applied to a
# device value. jax.device_get is handled separately (it is the explicit,
# batched boundary idiom — flagged only when used per-element).
SYNC_CALLS = ("float", "int", "bool")
SYNC_NP_CALLS = ("asarray", "array")
SYNC_METHODS = ("item", "tolist")

# --- taint: factories returning jitted callables ----------------------------
# `step = make_train_step(...)` / `predict = make_predict(...)`: calling the
# result yields device arrays. Matched against the callee name.
JITTED_FACTORY_RE = re.compile(
    r"^make_\w*(step|epoch|predict|train_fn|mix|fn)\w*$")

# Attribute callees whose results are device values (trainer convention).
JITTED_ATTR_CALLEES = ("_step", "step")

# Transforms whose function argument is traced when called.
TRACING_TRANSFORMS = (
    "jit", "vmap", "pmap", "shard_map", "scan", "cond", "while_loop",
    "fori_loop", "checkpoint", "remat", "grad", "value_and_grad", "custom_vjp",
)

# Calls whose RESULT is host data even when arguments are device values.
UNTAINT_CALLS = ("device_get", "shape", "len", "range", "eval_shape",
                 "tree_structure")

# --- G003: dtype-sensitive scopes ------------------------------------------
# Modules whose math feeds weight updates: bare literals / float64 here can
# silently upcast the bf16-above-2^24 storage policy (models/base.py).
DTYPE_MODULE_PREFIXES = (
    "hivemall_tpu/ops/",
    "hivemall_tpu/core/",
    "hivemall_tpu/models/",
    "hivemall_tpu/kernels/",
)
# Update-math modules where even host-side helper functions are checked for
# unpinned float literals (their outputs flow straight into rule updates).
DTYPE_MATH_MODULES = (
    "hivemall_tpu/ops/eta.py",
    "hivemall_tpu/ops/losses.py",
)

# --- G004: mesh axis registry ----------------------------------------------
# Fallback when parallel/mesh.py is outside the scanned path set. When it IS
# scanned, its module-level string constants and Mesh(...) literals extend
# this set.
MESH_FILE = "hivemall_tpu/parallel/mesh.py"
DEFAULT_AXIS_NAMES = frozenset({"workers", "shards"})
COLLECTIVE_CALLS = ("psum", "pmean", "pmax", "pmin", "all_gather",
                    "axis_index", "ppermute", "psum_scatter", "pcast")

# --- G005: donation --------------------------------------------------------
# jit-wrapped functions whose name looks step-shaped should donate their
# model-state argument; otherwise every hot-loop step copies the tables.
STEP_NAME_RE = re.compile(r"(step|epoch|train)", re.IGNORECASE)

# --- G006: host side effects -----------------------------------------------
SIDE_EFFECT_CALLS = ("print",)
SIDE_EFFECT_ATTR_ROOTS = ("time", "logging")
SIDE_EFFECT_METHODS = ("increment", "set_gauge", "record")
SIDE_EFFECT_NP_RANDOM = ("random",)
