"""Codebase-specific knobs for the graftcheck rules.

graftcheck is purpose-built for this repo's JAX idioms: the hot-path module
list, the mesh axis registry, and the jitted-factory naming convention live
here rather than being rediscovered per rule.
"""

from __future__ import annotations

import re

# --- G002: modules whose loops are per-step hot paths -----------------------
# The per-step loops of these modules drive every benchmark; an implicit
# device->host sync there serializes dispatch (BENCH_r01-r05 regressions).
HOT_LOOP_MODULES = (
    "hivemall_tpu/core/engine.py",
    "hivemall_tpu/parallel/sharded_train.py",
    "hivemall_tpu/parallel/mix.py",
    "hivemall_tpu/models/trees/grow.py",
    # the epoch/convergence driver that loops the engine's jitted steps
    "hivemall_tpu/models/base.py",
)

# Methods with these names receive device state / blocks by contract, so
# their parameters are treated as device values even outside a loop.
HOT_FN_RE = re.compile(r"^(step|_step|train_step|epoch)$")

# Calls that force an implicit device->host transfer when applied to a
# device value. jax.device_get is handled separately (it is the explicit,
# batched boundary idiom — flagged only when used per-element).
SYNC_CALLS = ("float", "int", "bool")
SYNC_NP_CALLS = ("asarray", "array")
SYNC_METHODS = ("item", "tolist")

# --- taint: factories returning jitted callables ----------------------------
# `step = make_train_step(...)` / `predict = make_predict(...)`: calling the
# result yields device arrays. Matched against the callee name.
JITTED_FACTORY_RE = re.compile(
    r"^make_\w*(step|epoch|predict|train_fn|mix|fn)\w*$")

# Attribute callees whose results are device values (trainer convention).
JITTED_ATTR_CALLEES = ("_step", "step")

# Transforms whose function argument is traced when called.
TRACING_TRANSFORMS = (
    "jit", "vmap", "pmap", "shard_map", "scan", "cond", "while_loop",
    "fori_loop", "checkpoint", "remat", "grad", "value_and_grad", "custom_vjp",
)

# Calls whose RESULT is host data even when arguments are device values.
UNTAINT_CALLS = ("device_get", "shape", "len", "range", "eval_shape",
                 "tree_structure")

# --- G003: dtype-sensitive scopes ------------------------------------------
# Modules whose math feeds weight updates: bare literals / float64 here can
# silently upcast the bf16-above-2^24 storage policy (models/base.py).
DTYPE_MODULE_PREFIXES = (
    "hivemall_tpu/ops/",
    "hivemall_tpu/core/",
    "hivemall_tpu/models/",
    "hivemall_tpu/kernels/",
)
# Update-math modules where even host-side helper functions are checked for
# unpinned float literals (their outputs flow straight into rule updates).
DTYPE_MATH_MODULES = (
    "hivemall_tpu/ops/eta.py",
    "hivemall_tpu/ops/losses.py",
)

# --- G004: mesh axis registry ----------------------------------------------
# Fallback when parallel/mesh.py is outside the scanned path set. When it IS
# scanned, its module-level string constants and Mesh(...) literals extend
# this set.
MESH_FILE = "hivemall_tpu/parallel/mesh.py"
DEFAULT_AXIS_NAMES = frozenset({"workers", "shards"})
COLLECTIVE_CALLS = ("psum", "pmean", "pmax", "pmin", "all_gather",
                    "axis_index", "ppermute", "psum_scatter", "pcast")

# --- G012-G016: concurrency / serving safety --------------------------------
# Constructors whose result is a lock object; the kind decides reentrancy
# (plain Lock is non-reentrant; Condition() wraps an RLock by default).
LOCK_CONSTRUCTOR_KINDS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Semaphore": "lock",
    "BoundedSemaphore": "lock",
}

# Method calls on a field that mutate the underlying container — counted as
# WRITES by the guarded-by inference (`self._q.append(x)` races exactly like
# `self._q = ...`).
MUTATOR_METHODS = ("append", "appendleft", "extend", "insert", "add",
                   "discard", "remove", "clear", "update", "setdefault",
                   "pop", "popleft", "popitem", "sort")

# G013 scope: the serving hot path — a blocking call under a lock here stalls
# every in-flight request at once (the hot-swap-stall failure mode). Modules
# outside the list opt in with the marker comment. The continuous-training
# pipeline is in scope by prefix: its worker thread shares the registry with
# request handlers, so a freeze/gate/deploy under its lock would stall
# every concurrent status()/lineage read exactly when a swap is in flight.
# The observability stack (metrics registry + endpoint, time-series
# sampler, SLO engine) is hot the same way: the sampler thread, ring
# listeners and HTTP scrape handlers all take its locks concurrently with
# request handlers, so a registry snapshot or listener callback under a
# ring/engine lock stalls both the sampler AND every /metrics scrape.
CONCURRENCY_HOT_PREFIXES = ("hivemall_tpu/serving/",
                            "hivemall_tpu/pipeline/",
                            "hivemall_tpu/runtime/metrics",
                            "hivemall_tpu/runtime/timeseries",
                            "hivemall_tpu/runtime/slo")
CONCURRENCY_MARKER = "# graftcheck: serving-module"

# Blocking-call classification for G013 (tails of dotted callees).
BLOCKING_DEVICE_TAILS = ("device_get", "block_until_ready")
BLOCKING_IO_TAILS = ("sleep", "urlopen", "connect", "accept", "recv",
                     "sendall", "getaddrinfo", "fsync")
# Future/thread rendezvous: .result() blocks on completion; set_result /
# set_exception run done-callbacks synchronously on the calling thread.
BLOCKING_FUTURE_TAILS = ("result", "set_result", "set_exception", "join",
                         "wait")
# jit dispatch / compile triggers: a cold bucket compiles under the lock.
BLOCKING_JIT_TAILS = ("warmup", "predict", "predict_fn")
# Roots whose methods share tails with the blocking list but never block
# (os.path.join, np ops, json/re parsing).
BLOCKING_SAFE_ROOTS = ("os", "np", "numpy", "json", "re", "posixpath",
                       "ntpath", "shutil", "sys", "math")

# --- G017-G021: dtype / precision flow (v4) ---------------------------------
# Hot-path scopes for the dtype-flow rules: a silent widening here doubles
# HBM traffic on every step/request (the dequant-free serving contract the
# quantized-artifact work depends on). The kernel/op packages and the
# serving score path are always hot; elsewhere in the dtype-sensitive
# packages only traced / step-shaped functions are (dtypeflow.in_hot_scope).
DTYPEFLOW_HOT_PREFIXES = (
    "hivemall_tpu/ops/",
    "hivemall_tpu/kernels/",
)
# serving/engine.py carries the dequant-free score path (the _q8_* scorers
# and every gathered-window cast); io/checkpoint.py carries the shared
# quantization pack/unpack helpers (quantize_int8 / bf16_pack_raw) — both
# are always hot for G017/G019 so a widened full-table copy or a silent
# promotion in the quant plumbing fails tier-1 (scripts/lint.sh) before a
# benchmark ever runs.
DTYPEFLOW_HOT_MODULES = ("hivemall_tpu/serving/engine.py",
                         # the hot-row score cache (the serving L0 fast
                         # path): cached values ARE the engine's computed
                         # predictions — a silent widening or f64 leak in
                         # the cache plumbing would break the cached ==
                         # computed bit-parity gate the skew bench pins.
                         # (G012-G016 concurrency scope is the serving/
                         # prefix — CONCURRENCY_HOT_PREFIXES above — so
                         # cache.py's lock discipline is gated the same
                         # way as batcher.py's.)
                         "hivemall_tpu/serving/cache.py",
                         # the sharded score path: per-window widens only
                         # (G019) and f32 accumulation (G021), same
                         # contract as the single-device _q8_* scorers
                         "hivemall_tpu/serving/sharded.py",
                         # the top-K retrieval path: the blocked catalog
                         # scorers carry the same dequant-free contract
                         # (int8 window widen + scale fold, f32
                         # accumulation) at catalog scale — a full-table
                         # dequant here costs N_items, not a window
                         "hivemall_tpu/serving/retrieval.py",
                         "hivemall_tpu/io/checkpoint.py",
                         # the segment-sum batched trainer: the CPU hot
                         # path — gathered [U]-window widens only, f32
                         # delta accumulation, one cast at each table
                         # write; a full-table promotion here would hand
                         # back the bandwidth the compact plan bought
                         "hivemall_tpu/core/batch_update.py",
                         # the native-apply staging layer (-native_apply):
                         # host f32 tables + plan marshalling feeding the
                         # ctypes ABI — a silent widening or float64
                         # temporary here doubles the very traffic the
                         # native pass exists to cut, and an unpinned
                         # dtype would cross the ABI as garbage
                         "hivemall_tpu/core/native_batch.py")
HOT_MARKER = "# graftcheck: hot-module"

# G018 scope: the serving/request path plus checkpoint IO — np.float64 (or a
# float64-by-default numpy constructor) here silently doubles payload and
# table bandwidth. Modules outside opt in with the serving-module marker
# (shared with G013 — both guard the same request path).
DTYPEFLOW_SERVING_PREFIXES = (
    "hivemall_tpu/serving/",
    "hivemall_tpu/io/",
)

# G020 scope: artifact/checkpoint save->load modules whose reloads must pin
# the manifest dtype (a bf16 table widened to f32 at rest must narrow back
# on load, not silently serve wide).
ARTIFACT_IO_MODULES = (
    "hivemall_tpu/io/checkpoint.py",
    "hivemall_tpu/serving/artifact.py",
    "hivemall_tpu/serving/engine.py",
    # the sharded load path re-places reloaded tables; its dtype pins live
    # in host_score_tables but an unpinned asarray HERE would undo them
    "hivemall_tpu/serving/sharded.py",
)
ARTIFACT_MARKER = "# graftcheck: artifact-io"

# --- G022-G026: FFI boundary (v5) ------------------------------------------
# Exported symbols of the native library (native/hivemall_native.cpp) all
# share this prefix; any dotted call whose tail matches is a foreign call.
FFI_SYMBOL_PREFIXES = ("hm_",)
# Callees whose results are sanctioned pointer sources: they raise on any
# dtype/rank/contiguity violation, so arrays unpacked from them are
# ABI-proven (ops/scatter.py plan_abi_arrays — the frozen plan ABI's gate).
FFI_SANCTIONING_VALIDATORS = ("plan_abi_arrays",)
# numpy constructors whose result is always freshly allocated C-contiguous;
# with an explicit dtype they fully validate a pointer source.
FFI_FRESH_CTORS = ("empty", "zeros", "ones", "full", "frombuffer")
# The Python-side plan ABI version constant (ops/scatter.py) checked by
# G025 against the C side's HM_PLAN_ABI_VERSION literal.
FFI_ABI_VERSION_CONSTANT = "PLAN_ABI_VERSION"
# C source of the native library for the G025 cross-language check; the
# env var overrides the default repo-root-relative location (tests seed
# deliberate drift through a tempdir copy).
FFI_NATIVE_CPP_ENV = "GRAFTCHECK_NATIVE_CPP"
FFI_NATIVE_CPP_DEFAULT = "native/hivemall_native.cpp"

# --- G027-G031: exception flow / failure paths (v6) --------------------------
# Failure-path scope: the serving request path, the continuous-training
# pipeline, and the whole runtime package (recovery driver, fault injector,
# tracing, metrics, cluster shims) — the code whose exception paths the
# reliability fronts depend on. A Future leaked on an unwind here hangs a
# client forever; a silent fallback hides a degradation until a bench
# regresses. Modules outside the prefixes opt in with the marker comment.
EXCEPTION_HOT_PREFIXES = (
    "hivemall_tpu/serving/",
    "hivemall_tpu/pipeline/",
    "hivemall_tpu/runtime/",
)
EXCEPTION_MARKER = "# graftcheck: failure-path-module"

# Handler calls that count as a LOUD surface for G028: the fallback names
# its reason somewhere an operator can see (warnings / logging / the trace
# ring / the metrics registry).
LOUD_CALL_TAILS = ("warn", "warning", "warn_explicit", "error", "exception",
                   "critical", "fatal", "instant", "increment")
LOUD_CALL_ROOTS = ("warnings", "logging")

# Handler types whose silent fallback is the sanctioned API-probing idiom
# (compat shims, optional native libraries) — a handler catching ONLY these
# is never a G028 degraded path.
PROBE_EXCEPTION_TYPES = frozenset({
    "ImportError", "ModuleNotFoundError", "AttributeError",
})

# Retry backoff classification for G031 (tails of dotted callees).
# cv.wait(timeout) counts: blocking on a condition variable IS the
# well-behaved form of waiting between attempts.
BACKOFF_CALL_TAILS = ("sleep", "wait")

# --- G005: donation --------------------------------------------------------
# jit-wrapped functions whose name looks step-shaped should donate their
# model-state argument; otherwise every hot-loop step copies the tables.
STEP_NAME_RE = re.compile(r"(step|epoch|train)", re.IGNORECASE)

# --- G006: host side effects -----------------------------------------------
SIDE_EFFECT_CALLS = ("print",)
SIDE_EFFECT_ATTR_ROOTS = ("time", "logging")
SIDE_EFFECT_METHODS = ("increment", "set_gauge", "record")
SIDE_EFFECT_NP_RANDOM = ("random",)

# --- v7 traceflow (G032-G036) ----------------------------------------------
# Modules whose jit call graphs the trace-time rules sweep by default: the
# serving dispatch stack and the kernel/op layers every jitted scorer and
# step funnels through. The zero-recompile contract is a property of these
# modules first; anything else opts in with the marker comment.
TRACEFLOW_HOT_PREFIXES = (
    "hivemall_tpu/ops/",
    "hivemall_tpu/kernels/",
)
TRACEFLOW_HOT_MODULES = (
    "hivemall_tpu/serving/engine.py",
    "hivemall_tpu/serving/retrieval.py",
    "hivemall_tpu/serving/sharded.py",
)
TRACEFLOW_MARKER = "# graftcheck: jit-hot-module"

# Module-level dicts recognized as sanctioned jit memos (the _SHARDED_JIT /
# _RETRIEVAL_JIT / _QUANT_JIT get-or-build idiom): a function that both
# reads and writes one of these is a memo helper, and jit wrappers built
# under it are constructed once per key, not once per call.
TRACEFLOW_MEMO_NAME_RE = re.compile(r"^_[A-Z0-9_]*JIT[A-Z0-9_]*$")

# Function names sanctioned to construct jit wrappers per CALL of the
# factory: builders invoked once at setup (make_*/build_*) and __init__.
# Calling one of these per hot-loop iteration is still churn (G032c).
TRACEFLOW_FACTORY_RE = re.compile(r"^_?(make|build)_\w+")

# Calls that canonicalize an array's shape onto the bucket ladder before it
# reaches a jitted callable (G034). pad_to_bucket is the width calculator
# (slicing/padding to its result IS bucket routing); bucket_rows and
# pad_rows_to_multiple are the array-level canonicalizers; a bare pad is
# trusted as deliberate shape control.
SHAPE_CANONICALIZERS = ("pad_to_bucket", "bucket_rows", "pad_rows_to_multiple",
                        "pad")

# Callee names that declare themselves host-sync boundaries (G036): a
# helper named like one of these performs its device_get on purpose, as the
# loop's sanctioned whole-value boundary read.
TRACEFLOW_SYNC_NAME_RE = re.compile(
    r"(sync|block_until|device_get|to_host|fetch|drain|gather_host)",
    re.IGNORECASE)

# Call tails inside a callee body that constitute an unconditional device
# sync for the G036 summary walk (taint-free: these block by name).
TRACEFLOW_SYNC_CALL_TAILS = ("device_get", "block_until_ready")
