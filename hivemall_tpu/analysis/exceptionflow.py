"""Exception-flow model: raise summaries, handler coverage, Future lifecycle (v6).

The reliability fronts (fleet-scale serving with replica death, elastic
process loss) are all failure-path code, and the repo's failure-path
invariants — Futures always resolved, fallbacks always LOUD with a named
reason, locks released on every unwind, retries bounded and backed off —
lived only in convention and point tests. This module gives the G027-G031
rules something to *prove* them against, stdlib-only and jax-free, on top
of the whole-program layer (program.py):

- per-function **raised-exception summaries** (``raises``): the exception
  type names a function can provably raise — explicit ``raise X`` (bare
  re-raises resolve to the enclosing handler's caught types, ``with``
  suites propagate, handlers that catch a type subtract it via the
  builtin + local class hierarchy), plus known-raising callees resolved
  through the import map with a depth-bounded walk;
- **try/except coverage**: every handler classified by what it does —
  re-raise / convert, surface the reason LOUDLY (``warnings.warn``,
  logging, a trace instant, a counter), resolve a Future
  (``set_exception``), swallow (pass/continue only), or silently fall
  back to degraded work (``classify_handler``);
- a **Future lifecycle lattice** (created → escaped → resolved): direct
  ``Future()`` locals tracked through their owner function in source
  order, so G027 can prove "this future was handed to a queue/caller and
  a later statement can unwind past its resolution".

Resolution is deliberately conservative, exactly like the SPMD and
concurrency layers: rules flag only what the model can prove (a raise
statement reached through resolvable call edges); dynamic callees and
unresolvable exception expressions are trusted.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from . import config
from .modmodel import _FN_TYPES, ModuleModel, dotted_name, walk_scope
from .program import ProgramModel

MAX_RAISE_DEPTH = 6

# Enough of the builtin exception hierarchy for catch matching: child ->
# parent. Everything here eventually reaches Exception/BaseException.
_BUILTIN_PARENT = {
    "ArithmeticError": "Exception",
    "AssertionError": "Exception",
    "AttributeError": "Exception",
    "BlockingIOError": "OSError",
    "BrokenPipeError": "ConnectionError",
    "BufferError": "Exception",
    "CancelledError": "Exception",
    "ConnectionError": "OSError",
    "ConnectionResetError": "ConnectionError",
    "EOFError": "Exception",
    "Exception": "BaseException",
    "FileExistsError": "OSError",
    "FileNotFoundError": "OSError",
    "FloatingPointError": "ArithmeticError",
    "GeneratorExit": "BaseException",
    "IOError": "OSError",
    "ImportError": "Exception",
    "IndexError": "LookupError",
    "InterruptedError": "OSError",
    "IsADirectoryError": "OSError",
    "KeyError": "LookupError",
    "KeyboardInterrupt": "BaseException",
    "LookupError": "Exception",
    "MemoryError": "Exception",
    "ModuleNotFoundError": "ImportError",
    "NotADirectoryError": "OSError",
    "NotImplementedError": "RuntimeError",
    "OSError": "Exception",
    "OverflowError": "ArithmeticError",
    "PermissionError": "OSError",
    "RecursionError": "RuntimeError",
    "RuntimeError": "Exception",
    "StopAsyncIteration": "Exception",
    "StopIteration": "Exception",
    "SystemExit": "BaseException",
    "TimeoutError": "OSError",
    "TypeError": "Exception",
    "UnicodeDecodeError": "UnicodeError",
    "UnicodeEncodeError": "UnicodeError",
    "UnicodeError": "ValueError",
    "ValueError": "Exception",
    "ZeroDivisionError": "ArithmeticError",
}

_IN_PROGRESS = frozenset({"\x00in-progress"})


def handler_names(handler: ast.ExceptHandler) -> Optional[Tuple[str, ...]]:
    """Caught type name tails of one handler; None = bare ``except:``."""
    t = handler.type
    if t is None:
        return None
    exprs = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
    out = []
    for e in exprs:
        d = dotted_name(e)
        out.append(d.rsplit(".", 1)[-1] if d else "?")
    return tuple(out)


def is_broad(names: Optional[Tuple[str, ...]]) -> bool:
    return names is None or any(n in ("Exception", "BaseException")
                                for n in names)


class HandlerInfo:
    """What one except clause does with what it catches."""

    __slots__ = ("node", "names", "bare", "broad", "exc_var", "uses_exc",
                 "reraises", "loud", "resolves_future", "swallow_only",
                 "has_work")

    def __init__(self, node: ast.ExceptHandler):
        self.node = node
        self.names = handler_names(node)
        self.bare = node.type is None
        self.broad = is_broad(self.names)
        self.exc_var = node.name
        self.uses_exc = False
        self.reraises = False          # any `raise` in the handler body
        self.loud = False              # warn/log/trace/counter surface
        self.resolves_future = False   # set_exception / set_result
        self.swallow_only = True       # body is only pass/continue/...
        self.has_work = False          # body does something real


def classify_handler(node: ast.ExceptHandler) -> HandlerInfo:
    info = HandlerInfo(node)
    for stmt in node.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # docstring / ellipsis placeholder
        info.swallow_only = False
        info.has_work = True
    for sub in walk_scope(node):
        if isinstance(sub, ast.Raise):
            info.reraises = True
        if isinstance(sub, ast.Name) and info.exc_var is not None \
                and sub.id == info.exc_var:
            info.uses_exc = True
        if isinstance(sub, ast.Call):
            d = dotted_name(sub.func)
            if d is None:
                continue
            tail = d.rsplit(".", 1)[-1]
            root = d.split(".", 1)[0]
            if tail in ("set_exception", "set_result"):
                info.resolves_future = True
            if tail in config.LOUD_CALL_TAILS \
                    or root in config.LOUD_CALL_ROOTS \
                    or root in ("log", "logger"):
                info.loud = True
    return info


class ExceptionModel:
    """Interprocedural exception propagation over one ProgramModel.

    Raise summaries are memoized on the owning ModuleModel objects (the
    package-tree models are shared across scans via modelcache's mtime layer),
    so repeated in-process scans — the test suite's _cli runs, the
    --fix re-scan — pay the summary walk once per module version."""

    def __init__(self, program: ProgramModel):
        self.program = program

    # -- catch matching ----------------------------------------------------

    def catches(self, path: str, guard: Optional[Tuple[str, ...]],
                exc: str) -> bool:
        """Does a handler catching ``guard`` types catch exception type
        ``exc``? Bare handlers and Exception/BaseException catch
        everything; otherwise match the name or its base chain (builtin
        hierarchy + local ``class X(Y)`` defs)."""
        if guard is None:
            return True
        chain = self._base_chain(path, exc)
        return any(g in chain for g in guard)

    def _base_chain(self, path: str, exc: str) -> FrozenSet[str]:
        out = {exc, "Exception", "BaseException"} \
            if exc not in _BUILTIN_PARENT else {exc}
        cur: Optional[str] = exc
        depth = 0
        while cur is not None and depth < 8:
            depth += 1
            parent = _BUILTIN_PARENT.get(cur)
            if parent is None:
                parent = self._local_base(path, cur)
            if parent is None or parent in out:
                break
            out.add(parent)
            cur = parent
        return frozenset(out)

    def _local_base(self, path: str, name: str) -> Optional[str]:
        """First base-class name of a ``class <name>(Base)`` def in the
        module (or its import source)."""
        model = self.program.modules.get(path)
        if model is None:
            return None
        for node in ast.walk(model.tree):
            if isinstance(node, ast.ClassDef) and node.name == name:
                for b in node.bases:
                    d = dotted_name(b)
                    if d is not None:
                        return d.rsplit(".", 1)[-1]
                return None
        imp = self.program.imports(path).get(name)
        if imp is not None and imp[0] is not None:
            return self._local_base(imp[0], imp[1])
        return None

    # -- callee resolution -------------------------------------------------

    def resolve_callee(self, path: str, call: ast.Call, dotted: str
                       ) -> Optional[Tuple[str, ast.AST]]:
        """(module, def) for a call the raise walk can follow: bare names
        (lexical + imports), ``self.helper`` methods of the enclosing
        class, and ``mod.helper`` through a plain module import."""
        parts = dotted.split(".")
        if len(parts) == 1:
            return self.program.resolve_fn(path, dotted, call)
        if len(parts) == 2 and parts[0] == "self":
            cls = getattr(call, "graftcheck_parent", None)
            while cls is not None and not isinstance(cls, ast.ClassDef):
                cls = getattr(cls, "graftcheck_parent", None)
            if cls is not None:
                for m in cls.body:
                    if isinstance(m, _FN_TYPES) and m.name == parts[1]:
                        return path, m
            return None
        if len(parts) == 2:
            imp = self.program.imports(path).get(parts[0])
            if imp is not None and imp[0] is not None:
                got = self.program.top_level_def(imp[0], parts[1])
                if got is not None:
                    return imp[0], got
        return None

    # -- raise summaries ---------------------------------------------------

    def raises(self, path: str, fn: ast.AST, depth: int = 0
               ) -> FrozenSet[str]:
        """Exception type names ``fn`` can provably raise to its caller."""
        model = self.program.modules.get(path)
        if model is None or depth > MAX_RAISE_DEPTH:
            return frozenset()
        memo: Dict[int, FrozenSet[str]] = getattr(
            model, "_graftcheck_raises", None)
        if memo is None:
            memo = {}
            model._graftcheck_raises = memo  # type: ignore[attr-defined]
        cached = memo.get(id(fn))
        if cached is not None:
            return frozenset() if cached is _IN_PROGRESS else cached
        memo[id(fn)] = _IN_PROGRESS  # cycle guard
        out: Set[str] = set()
        for exc, _node in self.escaping_raises(path, fn, depth):
            out.add(exc)
        result = frozenset(out)
        memo[id(fn)] = result
        return result

    def escaping_raises(self, path: str, fn: ast.AST, depth: int = 0
                        ) -> Iterator[Tuple[str, ast.AST]]:
        """(exception name, statement/call node) pairs for every raise
        that escapes ``fn`` — explicit raises plus resolvable raising
        callees, each filtered through the enclosing handlers."""

        def visit(stmts, guards: Tuple[Tuple[Optional[Tuple[str, ...]],
                                             ...], ...],
                  handler_ctx: Optional[Tuple[str, ...]]
                  ) -> Iterator[Tuple[str, ast.AST]]:
            for stmt in stmts:
                if isinstance(stmt, _FN_TYPES + (ast.ClassDef,)):
                    continue
                if isinstance(stmt, ast.Raise):
                    for exc in self._raise_names(path, stmt, handler_ctx):
                        if not self._guarded(path, guards, exc):
                            yield exc, stmt
                    continue
                yield from self._call_raises(path, stmt, guards, depth)
                if isinstance(stmt, ast.Try):
                    body_guards = guards + (tuple(
                        handler_names(h) for h in stmt.handlers),)
                    yield from visit(stmt.body, body_guards, handler_ctx)
                    for h in stmt.handlers:
                        ctx = handler_names(h)
                        yield from visit(h.body, guards,
                                         ctx if ctx is not None
                                         else ("Exception",))
                    # the else clause is NOT protected by this try's
                    # handlers (Python semantics), nor is the finally
                    yield from visit(stmt.orelse, guards, handler_ctx)
                    yield from visit(stmt.finalbody, guards, handler_ctx)
                elif isinstance(stmt, (ast.If, ast.While)):
                    yield from visit(stmt.body, guards, handler_ctx)
                    yield from visit(stmt.orelse, guards, handler_ctx)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    yield from visit(stmt.body, guards, handler_ctx)
                    yield from visit(stmt.orelse, guards, handler_ctx)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    # a raise inside the suite propagates out of the with
                    yield from visit(stmt.body, guards, handler_ctx)

        yield from visit(fn.body, (), None)

    def _raise_names(self, path: str, stmt: ast.Raise,
                     handler_ctx: Optional[Tuple[str, ...]]) -> List[str]:
        if stmt.exc is None:
            # bare re-raise: the enclosing handler's caught types
            return list(handler_ctx or ("Exception",))
        exc = stmt.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        d = dotted_name(exc)
        if d is None:
            return []
        name = d.rsplit(".", 1)[-1]
        # `raise self.RECOVERABLE`-style dynamic tuples stay trusted
        return [name] if name[:1].isupper() else []

    def _guarded(self, path: str, guards, exc: str) -> bool:
        return any(self.catches(path, g, exc)
                   for layer in guards for g in layer)

    def _call_raises(self, path: str, stmt: ast.stmt, guards, depth: int
                     ) -> Iterator[Tuple[str, ast.AST]]:
        """Escaping raises contributed by resolvable calls in the
        statement's own expressions (compound bodies are visited by the
        caller with their own guard stacks)."""
        for call, dotted in self._stmt_calls(stmt):
            got = self.resolve_callee(path, call, dotted)
            if got is None:
                continue
            t_path, t_fn = got
            for exc in self.raises(t_path, t_fn, depth + 1):
                if not self._guarded(path, guards, exc):
                    yield exc, call

    def _stmt_calls(self, stmt: ast.stmt
                    ) -> Iterator[Tuple[ast.Call, str]]:
        """Calls in the statement's header/leaf expressions, not in
        nested statement bodies or nested defs."""
        exprs: List[Optional[ast.expr]] = []
        if isinstance(stmt, ast.Try):
            return
        if isinstance(stmt, (ast.If, ast.While)):
            exprs = [stmt.test]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            exprs = [stmt.iter]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            exprs = [item.context_expr for item in stmt.items]
        elif isinstance(stmt, ast.Return):
            exprs = [stmt.value]
        elif isinstance(stmt, ast.Expr):
            exprs = [stmt.value]
        elif isinstance(stmt, ast.Assign):
            exprs = [stmt.value]
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            exprs = [stmt.value]
        elif isinstance(stmt, ast.Assert):
            exprs = [stmt.test, stmt.msg]
        for root in exprs:
            if root is None:
                continue
            stack: List[ast.AST] = [root]
            while stack:
                node = stack.pop()
                if isinstance(node, _FN_TYPES + (ast.Lambda,)):
                    continue
                if isinstance(node, ast.Call):
                    d = dotted_name(node.func)
                    if d is not None:
                        yield node, d
                stack.extend(ast.iter_child_nodes(node))

    # -- Future lifecycle --------------------------------------------------

    def future_locals(self, fn: ast.AST) -> Dict[str, ast.stmt]:
        """{name: creating assignment} for direct ``x = Future()`` /
        ``x: Future = Future()`` locals of ``fn`` (its own scope only)."""
        out: Dict[str, ast.stmt] = {}
        for node in walk_scope(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, value = node.target, node.value
            else:
                continue
            if isinstance(tgt, ast.Name) and isinstance(value, ast.Call):
                d = dotted_name(value.func) or ""
                if d.rsplit(".", 1)[-1] == "Future":
                    out[tgt.id] = node
        return out


def get_model(program: ProgramModel) -> ExceptionModel:
    """One ExceptionModel per ProgramModel (all five G027-G031 rules
    share it; summaries additionally persist on the module models)."""
    model = getattr(program, "_graftcheck_exceptions", None)
    if model is None:
        model = ExceptionModel(program)
        program._graftcheck_exceptions = model  # type: ignore[attr-defined]
    return model


def in_exception_scope(path: str, model: Optional[ModuleModel]) -> bool:
    """G027-G031 run on the failure-path scope (serving / pipeline /
    runtime) plus modules opting in with the failure-path marker."""
    if path.startswith(config.EXCEPTION_HOT_PREFIXES):
        return True
    return model is not None and config.EXCEPTION_MARKER in model.source
