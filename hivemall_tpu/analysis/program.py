"""Whole-program model: the interprocedural layer over ModuleModel.

PR 1's rules are per-module pattern matchers. The SPMD-safety classes
(G007/G008/G010) need whole-program sharding semantics instead: a psum in
``core/engine.py`` is only correct relative to the mesh axes bound by the
``shard_map`` call site in ``parallel/sharded_train.py`` that (transitively)
calls it. This module provides, stdlib-only and jax-free:

- a cross-module **import map** (``from ..core.engine import make_train_fn``
  resolves to the def node in its home module, through relative levels and
  ``as`` aliases, including function-local imports);
- a **constant registry** (module-level string constants, resolved through
  import chains — ``WORKER_AXIS`` used in ``parallel/mix.py`` resolves to
  ``"workers"`` declared in ``parallel/mesh.py``);
- per-function **summaries**: collectives used with their axis expression
  (literal / parameter / named constant) and outgoing calls;
- **shard_map call sites** with best-effort resolution of the mesh
  expression to its axis-name set (through ``make_mesh``/``make_mesh_2d``
  defaults, ``Mesh(...)`` literals, ``self.mesh = ...`` assignments and
  conditional fallbacks) and of the body expression to a function def
  (through factory calls that return a nested def);
- an interprocedural **walk** that propagates string-resolvable arguments
  (axis names) and function-valued arguments through call edges with a
  depth bound, so a collective four helpers below a shard_map site is
  checked against that site's mesh.

Resolution is deliberately conservative: every rule built on this model
flags only what it can *prove* (both ends resolved to literals); anything
dynamic is trusted, exactly like G004 trusts variable axis names.

The model is always built with the full ``hivemall_tpu`` package tree as
context (parsed once per process and mtime-cached), so single-file and
changed-files scans see the same call graph as a full scan.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from . import config
from .modmodel import ModuleModel, _FN_TYPES, dotted_name, walk_scope

MAX_CALL_DEPTH = 8

# env values: ("str", value) for resolved axis-name strings,
#             ("fn", module_path, fn_node, closure_env) for function values
StrVal = Tuple[str, str]


# --------------------------------------------------------------------------
# package-tree context cache
# --------------------------------------------------------------------------


def package_root() -> str:
    """Filesystem path of the hivemall_tpu package this analyzer lives in."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_package_models() -> Dict[str, ModuleModel]:
    """Every module of the package, through the shared model cache
    (modelcache.py: in-process mtime layer + on-disk sha256 layer).
    Returns {normalized rel_path: ModuleModel}; unparsable files are
    skipped here — the runner reports them when they are in the scanned
    set."""
    from . import modelcache
    root = package_root()
    out: Dict[str, ModuleModel] = {}
    prefix = os.path.basename(root)  # "hivemall_tpu"
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            ap = os.path.join(dirpath, name)
            rel = prefix + "/" + os.path.relpath(ap, root).replace(
                os.sep, "/")
            model = modelcache.cached_model(ap, rel)
            if model is not None:
                out[rel] = model
    modelcache.save()
    return out


# --------------------------------------------------------------------------
# shard_map call sites
# --------------------------------------------------------------------------

class ShardMapSite:
    """One shard_map(...) call: the body/mesh/specs expressions plus the
    module and enclosing function they appear in."""

    __slots__ = ("module", "call", "fn_expr", "mesh_expr", "in_specs_expr",
                 "out_specs_expr")

    def __init__(self, module: str, call: ast.Call):
        self.module = module
        self.call = call
        args = list(call.args)
        self.fn_expr = args[0] if args else None
        kw = {k.arg: k.value for k in call.keywords}
        self.mesh_expr = kw.get("mesh", args[1] if len(args) > 1 else None)
        self.in_specs_expr = kw.get("in_specs",
                                    args[2] if len(args) > 2 else None)
        self.out_specs_expr = kw.get("out_specs",
                                     args[3] if len(args) > 3 else None)


class FnSummary:
    """What one function does that sharding rules care about."""

    __slots__ = ("collectives", "calls", "param_defaults")

    def __init__(self):
        # (call node, collective tail, axis_kind, axis_value)
        #   axis_kind: "str" (resolved literal), "name" (identifier to
        #   resolve through params/constants), None (dynamic)
        self.collectives: List[Tuple[ast.Call, str, Optional[str],
                                     Optional[str]]] = []
        self.calls: List[Tuple[ast.Call, str]] = []  # (node, dotted callee)
        self.param_defaults: Dict[str, ast.expr] = {}


def collective_axis_expr(call: ast.Call, tail: str) -> Optional[ast.expr]:
    """The axis-name expression of a collective call, mirroring G004:
    ``axis_index(axis)`` takes it first, ``psum(x, axis)`` second,
    ``axis_name=``/``axis=`` kwargs win."""
    cand = None
    if tail == "axis_index":
        cand = call.args[0] if call.args else None
    elif len(call.args) >= 2:
        cand = call.args[1]
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis"):
            cand = kw.value
    return cand


class ProgramModel:
    def __init__(self, scanned: Dict[str, ModuleModel],
                 with_package_context: bool = True):
        self.modules: Dict[str, ModuleModel] = {}
        if with_package_context:
            self.modules.update(_load_package_models())
        self.modules.update(scanned)  # scanned content wins over disk
        self.scanned: Set[str] = set(scanned)
        self._imports: Dict[str, Dict[str, Tuple[Optional[str], str]]] = {}
        self._constants: Dict[str, Dict[str, str]] = {}
        self._summaries: Dict[Tuple[str, int], FnSummary] = {}
        self._sites: Optional[List[ShardMapSite]] = None

    # -- imports / constants ----------------------------------------------

    def imports(self, path: str) -> Dict[str, Tuple[Optional[str], str]]:
        """{local name: (target module rel_path or None, remote name)} from
        every ImportFrom in the module (function-local imports included)."""
        if path in self._imports:
            return self._imports[path]
        out: Dict[str, Tuple[Optional[str], str]] = {}
        model = self.modules.get(path)
        if model is not None:
            pkg_parts = path.split("/")[:-1]  # directory of the module
            for node in ast.walk(model.tree):
                if isinstance(node, ast.Import):
                    # plain `import pkg.mod [as m]`: the bound name is a
                    # MODULE — remote name "" so def/constant lookups fail
                    # cleanly, but rules still see the name as imported
                    # (G010 must treat `m.helper(...)` as opaque, not as a
                    # benign method call on a local value)
                    for alias in node.names:
                        local = (alias.asname or
                                 alias.name.split(".", 1)[0])
                        dotted = alias.name if alias.asname \
                            else alias.name.split(".", 1)[0]
                        target = None
                        if dotted.startswith("hivemall_tpu"):
                            parts = dotted.split(".")
                            for cand in ("/".join(parts) + ".py",
                                         "/".join(parts) + "/__init__.py"):
                                if cand in self.modules:
                                    target = cand
                                    break
                        out[local] = (target, "")
                    continue
                if not isinstance(node, ast.ImportFrom):
                    continue
                target = self._resolve_import_module(node, pkg_parts)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    out[local] = (target, alias.name)
        self._imports[path] = out
        return out

    def _resolve_import_module(self, node: ast.ImportFrom,
                               pkg_parts: List[str]) -> Optional[str]:
        """Rel_path of the module an ImportFrom pulls from, when it lives
        in the analyzed program; None for external modules (jax, numpy)."""
        if node.level and not pkg_parts:
            return None
        if node.level:
            base = pkg_parts[:len(pkg_parts) - (node.level - 1)] \
                if node.level > 1 else list(pkg_parts)
            if node.level > 1 and len(pkg_parts) < node.level - 1:
                return None
            parts = base + (node.module.split(".") if node.module else [])
        else:
            if not node.module or not node.module.startswith(
                    "hivemall_tpu"):
                return None
            parts = node.module.split(".")
        for cand in ("/".join(parts) + ".py",
                     "/".join(parts) + "/__init__.py"):
            if cand in self.modules:
                return cand
        return None

    def constants(self, path: str) -> Dict[str, str]:
        """Module-level ``NAME = "literal"`` string constants."""
        if path in self._constants:
            return self._constants[path]
        out: Dict[str, str] = {}
        model = self.modules.get(path)
        if model is not None:
            for node in model.tree.body:
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            out[tgt.id] = node.value.value
        self._constants[path] = out
        return out

    def resolve_str(self, path: str, name: str,
                    _seen: Optional[Set[Tuple[str, str]]] = None
                    ) -> Optional[str]:
        """Resolve an identifier to a string literal through module
        constants and import chains."""
        if _seen is None:
            _seen = set()
        if (path, name) in _seen:
            return None
        _seen.add((path, name))
        val = self.constants(path).get(name)
        if val is not None:
            return val
        imp = self.imports(path).get(name)
        if imp is not None and imp[0] is not None:
            return self.resolve_str(imp[0], imp[1], _seen)
        return None

    # -- def resolution ----------------------------------------------------

    def top_level_def(self, path: str, name: str) -> Optional[ast.AST]:
        model = self.modules.get(path)
        if model is None:
            return None
        for node in model.tree.body:
            if isinstance(node, _FN_TYPES) and node.name == name:
                return node
        return None

    def resolve_fn(self, path: str, name: str,
                   from_node: Optional[ast.AST] = None
                   ) -> Optional[Tuple[str, ast.AST]]:
        """(module, def node) for a bare function name: lexical scope in
        the home module first, then the import map."""
        model = self.modules.get(path)
        if model is not None and from_node is not None:
            fn = model.resolve_def(name, from_node)
            if fn is not None:
                return path, fn
        fn = self.top_level_def(path, name)
        if fn is not None:
            return path, fn
        imp = self.imports(path).get(name)
        if imp is not None and imp[0] is not None:
            target = self.top_level_def(imp[0], imp[1])
            if target is not None:
                return imp[0], target
        return None

    # -- summaries ---------------------------------------------------------

    def summary(self, path: str, fn: ast.AST) -> FnSummary:
        key = (path, id(fn))
        cached = self._summaries.get(key)
        if cached is not None:
            return cached
        s = FnSummary()
        args = fn.args
        pos = args.posonlyargs + args.args
        for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            s.param_defaults[a.arg] = d
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None:
                s.param_defaults[a.arg] = d
        for node in walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None:
                continue
            s.calls.append((node, callee))
            tail = callee.rsplit(".", 1)[-1]
            if tail in config.COLLECTIVE_CALLS:
                cand = collective_axis_expr(node, tail)
                if isinstance(cand, ast.Constant) \
                        and isinstance(cand.value, str):
                    s.collectives.append((node, tail, "str", cand.value))
                elif isinstance(cand, ast.Name):
                    s.collectives.append((node, tail, "name", cand.id))
                else:
                    s.collectives.append((node, tail, None, None))
        self._summaries[key] = s
        return s

    def param_names(self, fn: ast.AST) -> List[str]:
        a = fn.args
        return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]

    # -- shard_map sites ---------------------------------------------------

    def shard_map_sites(self) -> List[ShardMapSite]:
        if self._sites is None:
            self._sites = []
            for path, model in self.modules.items():
                if "shard_map" not in model.source:  # cheap pre-filter
                    continue
                for node in ast.walk(model.tree):
                    if isinstance(node, ast.Call):
                        callee = dotted_name(node.func) or ""
                        if callee.rsplit(".", 1)[-1] == "shard_map":
                            self._sites.append(ShardMapSite(path, node))
        return self._sites

    # -- mesh-axes resolution ---------------------------------------------

    def mesh_axes(self, path: str, expr: Optional[ast.expr],
                  scope: Optional[ast.AST], depth: int = 0
                  ) -> Optional[Set[str]]:
        """Best-effort axis-name set of a mesh expression; None = unknown."""
        if expr is None or depth > 6:
            return None
        model = self.modules.get(path)
        if isinstance(expr, ast.Call):
            return self._mesh_axes_of_call(path, expr, scope, depth)
        if isinstance(expr, ast.IfExp):
            a = self.mesh_axes(path, expr.body, scope, depth + 1)
            b = self.mesh_axes(path, expr.orelse, scope, depth + 1)
            return (a | b) if a is not None and b is not None else None
        if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.Or):
            vals = [self.mesh_axes(path, v, scope, depth + 1)
                    for v in expr.values]
            if all(v is not None for v in vals):
                out: Set[str] = set()
                for v in vals:
                    out |= v  # type: ignore[arg-type]
                return out
            return None
        if isinstance(expr, ast.Name) and model is not None:
            assign = self._find_assignment(model, expr.id, scope)
            if assign is not None:
                return self.mesh_axes(path, assign, scope, depth + 1)
            return None
        if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                          ast.Name) \
                and expr.value.id == "self" and model is not None:
            assign, owner = self._find_self_assignment(model, scope,
                                                       expr.attr)
            if assign is not None:
                return self.mesh_axes(path, assign, owner, depth + 1)
        return None

    def _axis_arg(self, path: str, call: ast.Call, kwarg: str,
                  target: Optional[Tuple[str, ast.AST]],
                  scope: Optional[ast.AST]) -> Tuple[bool, Optional[str]]:
        """Resolve an axis-name argument of a mesh-constructor call:
        explicit kwarg, explicit positional (matched against the
        constructor def's signature), else the def's default. Returns
        (explicitly_passed, value) — an explicit argument that does NOT
        resolve must make the whole mesh unknown, never fall back to the
        default."""
        for kw in call.keywords:
            if kw.arg == kwarg:
                return True, self._str_of(path, kw.value, scope)
        if target is not None:
            t_path, t_fn = target
            params = [a.arg for a in
                      t_fn.args.posonlyargs + t_fn.args.args]
            if kwarg in params:
                i = params.index(kwarg)
                if i < len(call.args) and not any(
                        isinstance(a, ast.Starred) for a in call.args):
                    return True, self._str_of(path, call.args[i], scope)
            default = self.summary(t_path, t_fn).param_defaults.get(kwarg)
            if default is not None:
                return False, self._str_of(t_path, default, None)
        return False, None

    def _mesh_axes_of_call(self, path: str, call: ast.Call,
                           scope: Optional[ast.AST], depth: int
                           ) -> Optional[Set[str]]:
        callee = dotted_name(call.func) or ""
        tail = callee.rsplit(".", 1)[-1]
        if tail == "Mesh":
            names = None
            if len(call.args) >= 2:
                names = call.args[1]
            for kw in call.keywords:
                if kw.arg == "axis_names":
                    names = kw.value
            if names is None:
                return None
            return self._axis_name_set(path, names, scope)
        # the registry-default fallbacks below are the REPO's make_mesh /
        # make_mesh_2d conventions: they apply only to the exact bare
        # names (a dotted jax.make_mesh or a make_meshgrid must stay
        # unknown, not default to 'workers')
        if tail == "make_mesh_2d" and "." not in callee:
            target = self.resolve_fn(path, callee, call)
            rep_given, rep = self._axis_arg(path, call, "replica_axis",
                                            target, scope)
            shd_given, shd = self._axis_arg(path, call, "shard_axis",
                                            target, scope)
            if (rep_given and rep is None) or (shd_given and shd is None):
                return None  # explicitly passed but unresolvable: unknown
            rep = rep or "workers"
            shd = shd or "shards"
            return {rep, shd}
        if tail == "make_mesh" and "." not in callee:
            target = self.resolve_fn(path, callee, call)
            given, axis = self._axis_arg(path, call, "axis_name", target,
                                         scope)
            if given and axis is None:
                return None  # explicitly passed but unresolvable: unknown
            if axis is None:
                axis = "workers"  # the stock make_mesh default
            return {axis}
        if tail == "named_mesh" and ("." not in callee
                                     or "jax_compat" in callee):
            # the serving-mesh helper (runtime/jax_compat.named_mesh):
            # axis_names is the 2nd positional or keyword; its signature
            # default is the serving convention ("batch", "model") — this
            # is what lets G008 validate PartitionSpecs over the sharded
            # SERVING load path (serving/placement.py, serving/sharded.py)
            names = call.args[1] if len(call.args) >= 2 else None
            for kw in call.keywords:
                if kw.arg == "axis_names":
                    names = kw.value
            if names is None:
                return {"batch", "model"}
            return self._axis_name_set(path, names, scope)
        return None

    def _axis_name_set(self, path: str, names: ast.expr,
                       scope: Optional[ast.AST]) -> Optional[Set[str]]:
        """Axis-name set of an explicit axis_names expression (tuple/list
        of resolvable strings, or a single name); None = unresolvable —
        an explicitly-passed-but-unknown spelling must make the whole
        mesh unknown, never fall back to a default."""
        if isinstance(names, (ast.Tuple, ast.List)):
            out: Set[str] = set()
            for elt in names.elts:
                s = self._str_of(path, elt, scope)
                if s is None:
                    return None
                out.add(s)
            return out
        s = self._str_of(path, names, scope)
        return {s} if s is not None else None

    def _find_assignment(self, model: ModuleModel, name: str,
                         scope: Optional[ast.AST]) -> Optional[ast.expr]:
        """Last single-target assignment (or param default) giving `name` a
        value, searched in the enclosing function chain then the module
        body."""
        cur = scope
        while cur is not None:
            found = None
            for node in walk_scope(cur):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == name:
                    found = node.value
            if found is not None:
                return found
            if isinstance(cur, _FN_TYPES):
                default = self.summary(model.rel_path, cur) \
                    .param_defaults.get(name)
                if default is not None and name in self.param_names(cur):
                    return default
            cur = model.enclosing_function(cur)
        for node in model.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == name:
                return node.value
        return None

    def _find_self_assignment(self, model: ModuleModel,
                              scope: Optional[ast.AST], attr: str
                              ) -> Tuple[Optional[ast.expr],
                                         Optional[ast.AST]]:
        """rhs of ``self.<attr> = ...`` anywhere in the enclosing class
        (searching __init__ first), plus the method it was found in."""
        cls = scope
        while cls is not None and not isinstance(cls, ast.ClassDef):
            cls = getattr(cls, "graftcheck_parent", None)
        if cls is None:
            return None, None
        methods = [n for n in cls.body if isinstance(n, _FN_TYPES)]
        methods.sort(key=lambda m: m.name != "__init__")
        for m in methods:
            for node in walk_scope(m):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self" and tgt.attr == attr:
                        return node.value, m
        return None, None

    def _str_of(self, path: str, expr: ast.expr,
                scope: Optional[ast.AST]) -> Optional[str]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Name):
            return self.resolve_str(path, expr.id)
        return None

    # -- body resolution ---------------------------------------------------

    def resolve_callable(self, path: str, expr: Optional[ast.expr],
                         env: Optional[Dict[str, tuple]] = None,
                         depth: int = 0
                         ) -> Optional[Tuple[str, ast.AST, Dict[str, tuple]]]:
        """Resolve a callable expression to (module, def, closure_env).

        Handles: bare names; ``partial(f, ...)``; factory calls whose def
        ``return``s a nested def (the ``stripe_score(axis, shard)`` idiom)
        — the factory's resolvable string arguments become the closure env
        of the returned def, so axis names survive one factory hop."""
        env = env or {}
        if expr is None or depth > 4:
            return None
        if isinstance(expr, ast.Name):
            bound = env.get(expr.id)
            if bound is not None and bound[0] == "fn":
                return bound[1], bound[2], bound[3]
            got = self.resolve_fn(path, expr.id, expr)
            if got is not None:
                return got[0], got[1], {}
            return None
        if not isinstance(expr, ast.Call):
            return None
        callee = dotted_name(expr.func)
        if callee in ("partial", "functools.partial") and expr.args:
            return self.resolve_callable(path, expr.args[0], env, depth + 1)
        if callee is None or "." in callee:
            return None
        got = self.resolve_fn(path, callee, expr)
        if got is None:
            return None
        f_path, f_def = got
        f_env = self.call_env(path, expr, f_path, f_def, env)
        # factory: find `return <name>` where <name> is a def nested in it
        f_model = self.modules.get(f_path)
        if f_model is None:
            return None
        for node in walk_scope(f_def):
            if isinstance(node, ast.Return) and node.value is not None:
                inner = self.resolve_callable(f_path, node.value, f_env,
                                              depth + 1)
                if inner is not None:
                    return inner
                if isinstance(node.value, ast.Name):
                    nested = f_model.resolve_def(node.value.id, node)
                    if nested is not None:
                        return f_path, nested, f_env
        return None

    # -- call-edge environments -------------------------------------------

    def call_env(self, caller_path: str, call: ast.Call, callee_path: str,
                 callee: ast.AST, caller_env: Dict[str, tuple]
                 ) -> Dict[str, tuple]:
        """Bind the callee's parameters to resolvable caller arguments:
        string literals / constants propagate as ("str", v); names that
        resolve to defs propagate as ("fn", module, def, env). Unresolvable
        arguments stay unbound; callee defaults fill the rest."""
        params = [a.arg for a in callee.args.posonlyargs + callee.args.args]
        env: Dict[str, tuple] = {}
        summ = self.summary(callee_path, callee)
        for p, d in summ.param_defaults.items():
            v = self._value_of(callee_path, d, {})
            if v is not None:
                env[p] = v
        offset = 1 if params[:1] == ["self"] else 0

        def bind(name: str, arg: ast.expr) -> None:
            v = self._value_of(caller_path, arg, caller_env)
            if v is not None:
                env[name] = v

        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            j = i + offset
            if j < len(params):
                bind(params[j], arg)
        for kw in call.keywords:
            if kw.arg is not None:
                bind(kw.arg, kw.value)
        return env

    def _value_of(self, path: str, expr: ast.expr,
                  env: Dict[str, tuple]) -> Optional[tuple]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return ("str", expr.value)
        if isinstance(expr, ast.Name):
            if expr.id in env:
                return env[expr.id]
            s = self.resolve_str(path, expr.id)
            if s is not None:
                return ("str", s)
            got = self.resolve_fn(path, expr.id, expr)
            if got is not None:
                return ("fn", got[0], got[1], {})
        return None

    # -- interprocedural walk ---------------------------------------------

    def walk_calls(self, path: str, fn: ast.AST, env: Dict[str, tuple],
                   depth: int = 0,
                   _visited: Optional[Set[Tuple[str, int]]] = None
                   ) -> Iterator[Tuple[str, ast.AST, FnSummary,
                                       Dict[str, tuple]]]:
        """Yield (module, def, summary, env) for `fn` and every function
        transitively reachable from it through resolvable call edges,
        depth-bounded and cycle-safe."""
        if _visited is None:
            _visited = set()
        key = (path, id(fn))
        if key in _visited or depth > MAX_CALL_DEPTH:
            return
        _visited.add(key)
        summ = self.summary(path, fn)
        yield path, fn, summ, env
        # defs nested in fn run as part of the same traced computation
        # (scan bodies, vmapped closures); their free variables see fn's
        # bindings, so they inherit the env
        model = self.modules.get(path)
        if model is not None:
            for nested in model.functions:
                if model.enclosing_function(nested) is fn:
                    yield from self.walk_calls(path, nested, dict(env),
                                               depth + 1, _visited)
        for call, callee in summ.calls:
            target: Optional[Tuple[str, ast.AST, Dict[str, tuple]]] = None
            if "." not in callee:
                bound = env.get(callee)
                if bound is not None and bound[0] == "fn":
                    target = (bound[1], bound[2], dict(bound[3]))
                else:
                    got = self.resolve_fn(path, callee, call)
                    if got is not None:
                        target = (got[0], got[1], {})
            if target is None:
                continue
            t_path, t_fn, t_closure = target
            t_env = self.call_env(path, call, t_path, t_fn, env)
            merged = dict(t_closure)
            merged.update(t_env)
            yield from self.walk_calls(t_path, t_fn, merged, depth + 1,
                                       _visited)

    def resolve_axis(self, path: str, fn: ast.AST, kind: Optional[str],
                     value: Optional[str], env: Dict[str, tuple]
                     ) -> Optional[str]:
        """Axis string of a summarized collective, given the walk env."""
        if kind == "str":
            return value
        if kind != "name" or value is None:
            return None
        bound = env.get(value)
        if bound is not None:
            return bound[1] if bound[0] == "str" else None
        if value in self.param_names(fn):
            default = self.summary(path, fn).param_defaults.get(value)
            if default is not None:
                v = self._value_of(path, default, {})
                if v is not None and v[0] == "str":
                    return v[1]
            return None  # unbound dynamic parameter: trusted
        return self.resolve_str(path, value)

    # -- import graph (for --with-callers) --------------------------------

    def importers_of(self, targets: Set[str]) -> Set[str]:
        """Transitive closure of modules importing any of `targets`."""
        out: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for path in self.modules:
                if path in out or path in targets:
                    continue
                deps = {t for t, _ in self.imports(path).values()
                        if t is not None}
                if deps & (targets | out):
                    out.add(path)
                    changed = True
        return out
