"""Checkpoint / warm start.

The reference's persistence model is model-as-table: trainers dump
(feature, weight[, covar]) rows at close(), and warm start reloads such a
table via `-loadmodel <file>` from the Hive distributed cache
(ref: LearnerBaseUDTF.java:215-333; SURVEY.md §5 "Checkpoint / resume").

Two tiers here:
- `save_model_rows` / `load_model_rows` — the interchange format: a
  key-value table (npz), optionally compressed with the sparse codec
  (utils/codec.encode_sparse_model — the FFM/tree blob recipe).
- `save_linear_state` / `load_linear_state` — full training-state checkpoint
  (all slots + step counter), enabling MID-TRAINING resume, which the
  reference cannot do (its replay files are deleteOnExit temp files,
  FactorizationMachineUDTF.java:301-302).
"""

from __future__ import annotations

import hashlib
import json
import os
import warnings
from typing import Dict, Mapping, Optional, Tuple

import jax
import numpy as np

from ..core.state import LinearState, init_linear_state
from ..utils.codec import decode_sparse_model, encode_sparse_model


def save_model_rows(path: str, feats: np.ndarray, weights: np.ndarray,
                    covars: Optional[np.ndarray] = None,
                    compressed: bool = False) -> None:
    if compressed:
        with open(path, "wb") as f:
            f.write(encode_sparse_model(feats, weights))
        return
    data = {"feature": np.asarray(feats), "weight": np.asarray(weights)}
    if covars is not None:
        data["covar"] = np.asarray(covars)
    np.savez_compressed(path, **data)


def load_model_rows(path: str) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    if path.endswith(".npz"):
        # context-manage the NpzFile: np.load keeps the zip member open
        # until closed, and a long-lived scorer reloading models would
        # otherwise leak one fd per reload
        with np.load(path) as z:
            return (z["feature"], z["weight"],
                    z["covar"] if "covar" in z.files else None)
    if path.endswith((".tsv", ".csv", ".txt")):
        return _load_text_model_rows(path)
    with open(path, "rb") as f:
        feats, weights = decode_sparse_model(f.read())
    return feats, weights, None


def _load_text_model_rows(path: str):
    """Interchange with the reference: a Hive-exported model table
    `feature<TAB>weight[<TAB>covar]` (or comma-separated) — the exact file the
    reference's -loadmodel consumed from the distributed cache
    (ref: LearnerBaseUDTF.loadPredictionModel:215-333)."""
    sep = "," if path.endswith(".csv") else "\t"
    feats, weights, covars = [], [], []
    has_covar = False
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(sep)
            feats.append(int(parts[0]))
            weights.append(float(parts[1]))
            if len(parts) > 2:
                covars.append(float(parts[2]))
                has_covar = True
    return (np.asarray(feats, np.int64), np.asarray(weights, np.float32),
            np.asarray(covars, np.float32) if has_covar else None)


def dense_from_rows(dims: int, feats: np.ndarray, weights: np.ndarray,
                    covars: Optional[np.ndarray] = None):
    """Model rows -> dense warm-start arrays (the loadPredictionModel path)."""
    w = np.zeros(dims, np.float32)
    w[np.asarray(feats, np.int64) % dims] = weights
    c = None
    if covars is not None:
        c = np.ones(dims, np.float32)
        c[np.asarray(feats, np.int64) % dims] = covars
    return w, c


def np_saveable(x: np.ndarray) -> np.ndarray:
    """npz-stable host array: bf16 (which np.savez cannot round-trip
    reliably) widens to f32 — value-exact; the recorded ``weights_dtype``
    entry narrows it back at load (the graftcheck G020 contract). The
    widen half of the at-rest protocol, shared with serving/artifact."""
    a = np.asarray(x)
    if a.dtype.name == "bfloat16":
        return a.astype(np.float32)
    return a


def dtype_from_name(name):
    """The narrow half of the at-rest protocol: a recorded dtype NAME back
    to the dtype device tables must reload at. bf16 needs the ml_dtypes
    object (the string means nothing to jnp.asarray); every other name —
    or None for pre-protocol checkpoints — passes through as-is."""
    if name == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return name


# --- quantized at-rest protocol (serving/artifact freeze(quantize=...)) -----
# Two schemes share this module with np_saveable/dtype_from_name because
# they are the same contract extended downward: the pack stores the
# REDUCED representation losslessly, the manifest records how to read it,
# and nothing between freeze and the score path ever materializes a
# widened copy of a full table (graftcheck G019/G020).
#
# - bf16: raw uint16 bit patterns (np.savez cannot round-trip ml_dtypes,
#   but a view can — exact bytes, half the widened-f32 pack);
# - int8_absmax: per-block symmetric int8 with one f32 scale per block of
#   `block_rows` (power of two) rows along the quantized axis, computed by
#   absmax: scale = max(|block|) / 127, q = rint(x / scale). An all-zero
#   block records scale 1.0 so dequantization is exactly zero; a tail
#   block shorter than block_rows is padded with zeros for the reshape
#   only (the pad never changes absmax and is sliced off the q output).

QUANT_SCHEME_BF16 = "bf16"
QUANT_SCHEME_INT8 = "int8_absmax"
QUANT_BLOCK_ROWS = 64  # default scale-block granularity (power of two)
SCALE_SUFFIX = "__scale"  # pack name of a quantized table's scale array


def bf16_pack_raw(x) -> np.ndarray:
    """bf16 table -> raw uint16 bit patterns, npz-stable without widening
    (the quantized-artifact counterpart of np_saveable). A non-bf16 input
    is rounded to bf16 first — that rounding IS the quantization."""
    import jax.numpy as jnp

    a = np.asarray(x)
    if a.dtype.name != "bfloat16":
        a = a.astype(jnp.bfloat16)
    return a.view(np.uint16)


def bf16_unpack_raw(u: np.ndarray) -> np.ndarray:
    """Raw uint16 bit patterns back to a host bf16 array (a view, not a
    cast — jnp.asarray of the result reloads at bf16 with zero copies of
    anything widened)."""
    import jax.numpy as jnp

    return np.ascontiguousarray(np.asarray(u, np.uint16)).view(jnp.bfloat16)


def quantize_int8(table, block_rows: int = QUANT_BLOCK_ROWS, axis: int = 0):
    """Symmetric per-block int8 quantization along ``axis``.

    Returns ``(q, scales)``: ``q`` is int8 with ``table``'s shape; ``scales``
    is f32 with the same shape except the quantized axis collapses to
    ``ceil(rows / block_rows)`` blocks. Row r of the table dequantizes as
    ``q[r] * scales[r // block_rows]`` (axis-relative), which is exactly how
    the serving scorers fold the scale into the gathered window — the full
    table is never widened (graftcheck G019).
    """
    if block_rows <= 0 or block_rows & (block_rows - 1):
        raise ValueError(f"block_rows must be a power of two: {block_rows}")
    a = np.asarray(np_saveable(table), np.float32)
    a = np.moveaxis(a, axis, 0)
    rows = a.shape[0]
    n_blocks = max(1, -(-rows // block_rows))
    pad = n_blocks * block_rows - rows
    if pad:  # tail block: zero-pad for the reshape only (absmax unchanged)
        a = np.concatenate(
            [a, np.zeros((pad,) + a.shape[1:], np.float32)])
    blocks = a.reshape((n_blocks, block_rows) + a.shape[1:])
    absmax = np.max(np.abs(blocks), axis=1)  # [n_blocks, *rest]
    # all-zero block: scale 1.0 keeps q == 0 dequantizing to exact zero
    scales = np.where(absmax > 0.0, absmax / np.float32(127.0),
                      np.float32(1.0)).astype(np.float32)
    q = np.clip(np.rint(blocks / scales[:, None]), -127, 127).astype(np.int8)
    q = q.reshape((n_blocks * block_rows,) + a.shape[1:])[:rows]
    return np.moveaxis(q, 0, axis), np.moveaxis(scales, 0, axis)


def dequantize_int8(q, scales, block_rows: int = QUANT_BLOCK_ROWS,
                    axis: int = 0) -> np.ndarray:
    """Host-side reference dequantization (tests / offline analysis; the
    serving path never calls this on a full table — it folds the scale
    into the gathered window instead)."""
    qq = np.moveaxis(np.asarray(q), axis, 0)
    ss = np.moveaxis(np.asarray(scales, np.float32), axis, 0)
    per_row = np.repeat(ss, block_rows, axis=0)[: qq.shape[0]]
    return np.moveaxis(qq.astype(np.float32) * per_row, 0, axis)


def pack_linear_state(state: LinearState) -> Dict[str, np.ndarray]:
    """LinearState -> the npz array payload (one copy of the layout, shared
    by save_linear_state and the elastic-checkpoint writer)."""
    host = jax.device_get(state)
    arrays = {
        "weights": np_saveable(host.weights),
        "touched": np.asarray(host.touched),
        "step": np.asarray(host.step),
        # the dtype the state TRAINED with — resume must re-narrow a bf16
        # table rather than silently continue in f32
        "weights_dtype": np.asarray(np.asarray(host.weights).dtype.name),
    }
    if host.covars is not None:
        arrays["covars"] = np_saveable(host.covars)
    for k, v in host.slots.items():
        arrays[f"slot__{k}"] = np.asarray(v)
    for k, v in host.globals.items():
        arrays[f"global__{k}"] = np.asarray(v)
    return arrays


def unpack_linear_state(arrays: Mapping[str, np.ndarray]) -> LinearState:
    """The load half of pack_linear_state, over any name->array mapping
    (an open NpzFile or the dict load_elastic returns)."""
    import jax.numpy as jnp

    # dtype pins (graftcheck G020): weights/covars re-narrow to their
    # recorded training dtype; slots/globals/touched/step are f32 /
    # int8 / int32 by construction (core/state.init_linear_state)
    wdt = str(arrays["weights_dtype"][()]) if "weights_dtype" in arrays \
        else None
    table_dt = dtype_from_name(wdt)
    slots = {k[len("slot__"):]: jnp.asarray(arrays[k], jnp.float32)
             for k in arrays if k.startswith("slot__")}
    globals_ = {k[len("global__"):]: jnp.asarray(arrays[k], jnp.float32)
                for k in arrays if k.startswith("global__")}
    return LinearState(
        weights=jnp.asarray(arrays["weights"], table_dt),
        covars=jnp.asarray(arrays["covars"], table_dt)
        if "covars" in arrays else None,
        slots=slots,
        touched=jnp.asarray(arrays["touched"], jnp.int8),
        step=jnp.asarray(arrays["step"], jnp.int32),
        globals=globals_,
    )


def save_linear_state(path: str, state: LinearState) -> None:
    np.savez_compressed(path, **pack_linear_state(state))


def load_linear_state(path: str) -> LinearState:
    # all arrays materialize inside the with: NpzFile reads lazily from the
    # underlying zip and must be closed (fd leak otherwise)
    with np.load(path) as z:
        return unpack_linear_state({k: z[k] for k in z.files})


# --- elastic checkpoints (runtime/recovery checkpoint()/elastic_resume()) ---
# One self-contained npz per checkpoint: the COLLAPSED, stripe-free payload
# arrays plus an embedded JSON manifest recording striping metadata (dims,
# dims_padded, n_shards, stripe, rule/hyper, step) and a sha256 digest over
# the payload bytes. Self-contained means single-file atomic: write tmp,
# rotate the previous checkpoint to `path.prev`, rename tmp into place — a
# crash at ANY point leaves at least one valid checkpoint on disk, and the
# loader verifies the digest and falls back (loudly) to `.prev` when the
# newest file is truncated or corrupt.

ELASTIC_FORMAT_VERSION = 1
MANIFEST_KEY = "__manifest__"
PREV_SUFFIX = ".prev"


class CheckpointCorrupt(RuntimeError):
    """The checkpoint file exists but cannot be trusted: unreadable zip
    (truncation), missing manifest, or payload digest mismatch."""


class NotElasticCheckpoint(CheckpointCorrupt):
    """A readable npz with no embedded manifest — a legacy
    save_linear_state checkpoint, not a rotted elastic one. The resume
    path treats it as the pre-manifest format instead of falling back."""


def elastic_digest(arrays: Mapping[str, np.ndarray]) -> str:
    """sha256 over the payload: sorted (name, dtype, shape, raw bytes).
    The manifest carries this digest, so it cannot cover itself — the
    loader recomputes over the arrays and compares."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        if name == MANIFEST_KEY:
            continue
        a = np.ascontiguousarray(np.asarray(arrays[name]))
        h.update(name.encode())
        h.update(str(a.dtype.str).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def crash_point(tag: str, path: str) -> None:
    """No-op hook on the checkpoint write path — the monkeypatch target the
    fault harness (runtime/faults.py) uses to simulate a crash between the
    payload write and the atomic rename. Tags: ``elastic.after_write`` (tmp
    exists, nothing rotated), ``elastic.before_rename`` (previous checkpoint
    already rotated to .prev, new one not yet in place)."""


def checkpoint_written(path: str) -> None:
    """No-op hook fired after a successful write+rename — the fault
    harness's seat for post-hoc truncation/corruption injection."""


def save_elastic(path: str, arrays: Dict[str, np.ndarray],
                 manifest: dict) -> dict:
    """Atomically persist an elastic checkpoint: payload ``arrays`` plus
    ``manifest`` (digest and format_version are stamped here). On success
    the previous checkpoint survives as ``path + '.prev'`` — the loader's
    fallback when a later write is interrupted or the newest file rots.
    Returns the stamped manifest."""
    manifest = dict(manifest)
    manifest["format_version"] = ELASTIC_FORMAT_VERSION
    manifest["digest"] = elastic_digest(arrays)
    # .npz suffix keeps np.savez from renaming the temp file under us
    tmp = path + ".tmp.npz"
    np.savez_compressed(
        tmp, **arrays,
        **{MANIFEST_KEY: np.asarray(json.dumps(manifest))})
    crash_point("elastic.after_write", path)
    if os.path.exists(path):
        os.replace(path, path + PREV_SUFFIX)
    crash_point("elastic.before_rename", path)
    os.replace(tmp, path)
    checkpoint_written(path)
    return manifest


def _load_elastic_one(path: str):
    """Read + verify ONE checkpoint file. Raises CheckpointCorrupt on any
    integrity failure (truncated zip, missing/unparsable manifest, digest
    mismatch) and FileNotFoundError when absent."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    try:
        with np.load(path, allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
    except FileNotFoundError:
        raise
    except Exception as e:  # zipfile.BadZipFile, zlib.error, ValueError ...
        raise CheckpointCorrupt(f"{path}: unreadable npz ({e})") from e
    if MANIFEST_KEY not in arrays:
        raise NotElasticCheckpoint(
            f"{path}: no {MANIFEST_KEY} entry — not an elastic checkpoint")
    try:
        manifest = json.loads(str(arrays.pop(MANIFEST_KEY)[()]))
    except Exception as e:
        raise CheckpointCorrupt(f"{path}: unparsable manifest ({e})") from e
    digest = elastic_digest(arrays)
    if digest != manifest.get("digest"):
        raise CheckpointCorrupt(
            f"{path}: payload digest {digest[:12]}… does not match the "
            f"manifest's {str(manifest.get('digest'))[:12]}…")
    return arrays, manifest


def load_elastic(path: str, fallback: bool = True):
    """Load + verify the newest valid checkpoint at ``path``. When the
    newest file is missing or corrupt and ``fallback`` is on, fall back —
    loudly, with a warning naming the reason — to ``path + '.prev'`` (the
    last successfully-rotated checkpoint) instead of crashing the resume.
    Returns ``(arrays, manifest)``."""
    try:
        return _load_elastic_one(path)
    except (FileNotFoundError, CheckpointCorrupt) as e:
        if not fallback or isinstance(e, NotElasticCheckpoint):
            # a legacy (pre-manifest) checkpoint is a format, not a rot —
            # the caller decides how to read it
            raise
        prev = path + PREV_SUFFIX
        if not os.path.exists(prev):
            raise
        warnings.warn(
            f"elastic checkpoint {path} is unusable ({e}); falling back to "
            f"the previous checkpoint {prev} — work since that checkpoint "
            "will be replayed", RuntimeWarning, stacklevel=2)
        return _load_elastic_one(prev)
