"""Sharded binary record files — the input-pipeline / epoch-replay subsystem.

The reference replays epochs by spilling every training row to a NIO
positioned temp file and re-reading it in close()
(ref: utils/io/NioStatefullSegment.java:29-68, fm/FactorizationMachineUDTF.java:291-332,
mf/OnlineMatrixFactorizationUDTF.java:92-203). TPU-first this becomes a
proper record-shard pipeline (SURVEY.md §2.17 io note): rows are written once
to N binary shards; epochs iterate shards with shard-order + in-shard
shuffling and yield fixed-shape FeatureBlocks, optionally prefetched to
device ahead of the consumer.

Record format (little-endian), per row:
    u8  nnz | varint delta-coded feature ids | f32[nnz] values | f32 label
Shard file: magic "HMTR1" + u64 row count + rows.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core.batch import FeatureBlock, iter_blocks, pad_to_bucket
from ..utils.codec import leb128_decode, leb128_encode

MAGIC = b"HMTR1"


def write_records(prefix: str, idx_rows: Sequence[np.ndarray],
                  val_rows: Sequence[np.ndarray], labels: Sequence[float],
                  num_shards: int = 1) -> List[str]:
    """Round-robin rows into `num_shards` files `prefix-{i:05d}.hmtr`."""
    from .. import native

    paths = [f"{prefix}-{i:05d}.hmtr" for i in range(num_shards)]
    shard_rows: List[List[int]] = [list(range(s, len(idx_rows), num_shards))
                                   for s in range(num_shards)]
    for p, rows in zip(paths, shard_rows):
        body = None
        if native.available():
            body = native.encode_records(
                [idx_rows[r] for r in rows], [val_rows[r] for r in rows],
                np.asarray([labels[r] for r in rows], np.float32))
        if body is None:
            out = bytearray()
            for r in rows:
                idx = np.asarray(idx_rows[r], np.int64)
                # stable: equal-id entries keep input order (matches the
                # native encoder's stable_sort byte-for-byte)
                order = np.argsort(idx, kind="stable")
                idx = idx[order]
                val = np.asarray(val_rows[r], np.float32)[order]
                if len(idx) > 255:
                    raise ValueError("row nnz > 255 unsupported by record format")
                out.append(len(idx))
                prev = 0
                for i in idx:
                    leb128_encode(int(i) - prev, out)
                    prev = int(i)
                out.extend(val.tobytes())
                out.extend(struct.pack("<f", float(labels[r])))
            body = bytes(out)
        with open(p, "wb") as f:
            f.write(MAGIC)
            f.write(struct.pack("<Q", len(rows)))
            f.write(body)
    return paths


def read_shard(path: str) -> Tuple[List[np.ndarray], List[np.ndarray], np.ndarray]:
    with open(path, "rb") as f:
        data = f.read()
    if data[:5] != MAGIC:
        raise ValueError(f"{path}: bad magic")
    (n,) = struct.unpack_from("<Q", data, 5)
    from .. import native

    decoded = native.decode_records(data[13:], n)
    if decoded is not None:
        offsets, indices, values, labels = decoded
        idx_rows = [indices[offsets[r]:offsets[r + 1]] for r in range(n)]
        val_rows = [values[offsets[r]:offsets[r + 1]] for r in range(n)]
        return idx_rows, val_rows, labels
    pos = 13
    idx_rows: List[np.ndarray] = []
    val_rows: List[np.ndarray] = []
    labels = np.empty(n, np.float32)
    for r in range(n):
        nnz = data[pos]
        pos += 1
        idx = np.empty(nnz, np.int64)
        prev = 0
        for k in range(nnz):
            d, pos = leb128_decode(data, pos)
            prev += d
            idx[k] = prev
        val = np.frombuffer(data, np.float32, count=nnz, offset=pos).copy()
        pos += 4 * nnz
        (labels[r],) = struct.unpack_from("<f", data, pos)
        pos += 4
        idx_rows.append(idx)
        val_rows.append(val)
    return idx_rows, val_rows, labels


class RecordDataset:
    """Epoch iterator over record shards with shuffling + fixed-shape blocks.

    `device_prefetch` stages the next block's arrays on device while the
    current one computes (the double-buffering the reference's synchronous
    disk replay lacked)."""

    def __init__(self, paths: Sequence[str], dims: int, batch_size: int,
                 width: Optional[int] = None, shuffle: bool = True,
                 seed: int = 31, device_prefetch: bool = True):
        self.paths = list(paths)
        self.dims = dims
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.device_prefetch = device_prefetch
        self._width = width
        self._epoch = 0

    def _resolve_width(self, idx_rows) -> int:
        if self._width is None:
            self._width = pad_to_bucket(max((len(r) for r in idx_rows), default=1))
        return self._width

    def blocks(self) -> Iterator[FeatureBlock]:
        rng = np.random.RandomState(self.seed + self._epoch)
        self._epoch += 1
        order = rng.permutation(len(self.paths)) if self.shuffle else \
            np.arange(len(self.paths))

        def host_blocks():
            for s in order:
                idx_rows, val_rows, labels = read_shard(self.paths[s])
                if self.shuffle:
                    perm = rng.permutation(len(idx_rows))
                    idx_rows = [idx_rows[i] for i in perm]
                    val_rows = [val_rows[i] for i in perm]
                    labels = labels[perm]
                width = self._resolve_width(idx_rows)
                yield from iter_blocks(idx_rows, val_rows, labels, self.dims,
                                       self.batch_size, width)

        if not self.device_prefetch:
            yield from host_blocks()
            return
        import jax

        pending = None
        for blk in host_blocks():
            staged = FeatureBlock(*(jax.device_put(a) for a in blk))
            if pending is not None:
                yield pending
            pending = staged
        if pending is not None:
            yield pending
