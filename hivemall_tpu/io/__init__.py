from .checkpoint import (  # noqa: F401
    load_linear_state,
    load_model_rows,
    save_linear_state,
    save_model_rows,
)
