"""hivemall-tpu: a TPU-native (JAX/XLA/Pallas/pjit) machine-learning framework
with the capabilities of Apache Hivemall.

Reference behavior blueprint: /root/reference (L3Sota/hivemall v0.4.2-rc.1).
See SURVEY.md for the layer map this package mirrors:

- ``hivemall_tpu.utils``    -> utility substrate (hashing, parsing, options)  [ref L0]
- ``hivemall_tpu.core``     -> model state pytrees + batched update engine    [ref L1]
- ``hivemall_tpu.parallel`` -> collective model mixing (MIX replacement)      [ref L2/L2']
- ``hivemall_tpu.models``   -> learners (linear, multiclass, FM/FFM, MF, trees) [ref L3]
- ``hivemall_tpu.ftvec``, ``knn``, ``evaluation``, ``ensemble``, ``tools``,
  ``dataset``               -> feature engineering & query-utility functions  [ref L4]
- ``hivemall_tpu.sql``      -> the SQL-name function registry (define-all.hive parity) [ref L5]
"""

VERSION = "0.4.2-rc.1+tpu0"


def version() -> str:
    """Mirrors hivemall_version() (ref: core/.../HivemallVersionUDF.java)."""
    return VERSION
