from .registry import REGISTRY, get_function, list_functions, macros  # noqa: F401
