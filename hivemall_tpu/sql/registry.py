"""The SQL-name function registry — L5 parity surface.

Mirrors `resources/ddl/define-all.hive` (607 lines, ~150 CREATE TEMPORARY
FUNCTION statements): every SQL name the reference registers resolves here to
the equivalent Python callable, so a Hivemall user can look up any function
they know by its SQL name (`get_function("train_arow")`). Aliases (to_dense /
to_dense_features, logress / train_logistic_regr, concat_array / array_concat,
train_randomforest_regressor / _regr) are kept.

The reference's SQL *macros* (define-all.hive:582-607) are plain functions
here: max2, min2, idf, tfidf, rand_gid, rand_gid2.
"""

from __future__ import annotations

import math
import random
from typing import Callable, Dict, List

from .. import version as _version
from ..dataset import lr_datagen
from ..ensemble import (argmin_kld, max_label, maxrow, rf_ensemble, voted_avg,
                        weight_voted_avg)
from ..evaluation import f1score, logloss, mae, mse, ndcg, r2, rmse
from ..ftvec import (add_bias, amplify, binarize_label, bpr_sampling,
                     categorical_features, conv2dense, extract_feature,
                     extract_weight, feature, feature_hashing, feature_index,
                     ffm_features, indexed_features, item_pairs_sampling,
                     l2_normalize, polynomial_features, populate_not_in,
                     powered_features, quantified_features, quantify,
                     quantitative_features, rand_amplify, rescale,
                     sort_by_feature, tf, to_dense_features,
                     to_sparse_features, vectorize_features, zscore)
from ..knn import (angular_distance, angular_similarity, bbit_minhash,
                   cosine_distance, cosine_similarity, distance2similarity,
                   euclid_distance, euclid_similarity, hamming_distance,
                   jaccard_distance, jaccard_similarity, kld,
                   manhattan_distance, minhash, minhashes, minkowski_distance,
                   popcnt)
from ..models import classifier as _cls
from ..models import multiclass as _mc
from ..models import regression as _regr
from ..models.ffm import ffm_predict, train_ffm
from ..models.fm import fm_predict, train_fm
from ..models.mf import (bprmf_predict, mf_predict, train_bprmf,
                         train_mf_adagrad, train_mf_sgd)
from ..models.trees import (guess_attrs, train_gradient_tree_boosting_classifier,
                            train_randomforest_classifier,
                            train_randomforest_regr, tree_predict)
from ..tools import (array_avg, array_concat, array_intersect, array_remove,
                     array_sum, base91, bits_collect, bits_or, collect_all,
                     convert_label, deflate, distcache_gets, each_top_k,
                     float_array, generate_series, inflate, is_stopword,
                     jobconf_gets, jobid, map_get_sum, map_tail_n,
                     normalize_unicode, rowid, sigmoid, sort_and_uniq_array,
                     split_words, subarray, subarray_endwith,
                     subarray_startwith, taskid, to_bits, to_map,
                     to_ordered_map, to_string_array, tokenize, unbase91,
                     unbits, x_rank)
from ..utils.hashing import array_hash_values, mhash, sha1_hash


def _add_feature_index(features):
    """`add_feature_index(array<double>)` -> ["1:v1", ...]
    (ref: ftvec/AddFeatureIndexUDF.java)."""
    return [f"{i + 1}:{float(v)}" for i, v in enumerate(features)]


def prefixed_hash_values(values, prefix, num_features=None):
    from ..utils.hashing import DEFAULT_NUM_FEATURES
    from ..utils.hashing import array_hash_values as ahv

    return ahv(values, prefix, num_features or DEFAULT_NUM_FEATURES)


# ---- macros (ref: define-all.hive:582-607) ----

def max2(x, y):
    return x if x > y else y


def min2(x, y):
    return x if x < y else y


def java_min(x, y):
    return min(x, y)


def rand_gid(k: int) -> int:
    return int(random.random() * k)


def rand_gid2(k: int, seed: int) -> int:
    return int(random.Random(seed).random() * k)


def idf(df_t: float, n_docs: float) -> float:
    return math.log10(n_docs / max2(1.0, df_t)) + 1.0


def tfidf(tf_value: float, df_t: float, n_docs: float) -> float:
    return tf_value * idf(df_t, n_docs)


REGISTRY: Dict[str, Callable] = {
    "hivemall_version": _version,
    # binary classifiers (§2.3)
    "train_perceptron": _cls.train_perceptron,
    "train_pa": _cls.train_pa,
    "train_pa1": _cls.train_pa1,
    "train_pa2": _cls.train_pa2,
    "train_cw": _cls.train_cw,
    "train_arow": _cls.train_arow,
    "train_arowh": _cls.train_arowh,
    "train_scw": _cls.train_scw,
    "train_scw2": _cls.train_scw2,
    "train_adagrad_rda": _cls.train_adagrad_rda,
    # multiclass (§2.4)
    "train_multiclass_perceptron": _mc.train_multiclass_perceptron,
    "train_multiclass_pa": _mc.train_multiclass_pa,
    "train_multiclass_pa1": _mc.train_multiclass_pa1,
    "train_multiclass_pa2": _mc.train_multiclass_pa2,
    "train_multiclass_cw": _mc.train_multiclass_cw,
    "train_multiclass_arow": _mc.train_multiclass_arow,
    "train_multiclass_arowh": _mc.train_multiclass_arowh,
    "train_multiclass_scw": _mc.train_multiclass_scw,
    "train_multiclass_scw2": _mc.train_multiclass_scw2,
    # similarity / distance / LSH (§2.10)
    "cosine_similarity": cosine_similarity,
    "jaccard_similarity": jaccard_similarity,
    "angular_similarity": angular_similarity,
    "euclid_similarity": euclid_similarity,
    "distance2similarity": distance2similarity,
    "popcnt": popcnt,
    "kld": kld,
    "hamming_distance": hamming_distance,
    "euclid_distance": euclid_distance,
    "cosine_distance": cosine_distance,
    "angular_distance": angular_distance,
    "jaccard_distance": jaccard_distance,
    "manhattan_distance": manhattan_distance,
    "minkowski_distance": minkowski_distance,
    "minhashes": minhashes,
    "minhash": minhash,
    "bbit_minhash": bbit_minhash,
    # ensemble (§2.12)
    "voted_avg": voted_avg,
    "weight_voted_avg": weight_voted_avg,
    "max_label": max_label,
    "maxrow": maxrow,
    "argmin_kld": argmin_kld,
    "rf_ensemble": rf_ensemble,
    # hashing (§2.9)
    "mhash": mhash,
    "sha1": sha1_hash,
    "array_hash_values": array_hash_values,
    "prefixed_hash_values": prefixed_hash_values,
    "feature_hashing": feature_hashing,
    # pairing / scaling
    "polynomial_features": polynomial_features,
    "powered_features": powered_features,
    "rescale": rescale,
    "zscore": zscore,
    "l2_normalize": l2_normalize,
    # amplify
    "amplify": amplify,
    "rand_amplify": rand_amplify,
    # ftvec top-level
    "add_bias": add_bias,
    "sort_by_feature": sort_by_feature,
    "extract_feature": extract_feature,
    "extract_weight": extract_weight,
    "add_feature_index": _add_feature_index,
    "feature": feature,
    "feature_index": feature_index,
    # conv
    "conv2dense": conv2dense,
    "to_dense_features": to_dense_features,
    "to_dense": to_dense_features,
    "to_sparse_features": to_sparse_features,
    "to_sparse": to_sparse_features,
    "quantify": quantify,
    # trans
    "vectorize_features": vectorize_features,
    "categorical_features": categorical_features,
    "ffm_features": ffm_features,
    "indexed_features": indexed_features,
    "quantified_features": quantified_features,
    "quantitative_features": quantitative_features,
    "binarize_label": binarize_label,
    # ranking
    "bpr_sampling": bpr_sampling,
    "item_pairs_sampling": item_pairs_sampling,
    "populate_not_in": populate_not_in,
    # text ftvec
    "tf": tf,
    # regression (§2.5)
    "logress": _regr.train_logistic_regr,
    "train_logistic_regr": _regr.train_logistic_regr,
    "train_pa1_regr": _regr.train_pa1_regr,
    "train_pa1a_regr": _regr.train_pa1a_regr,
    "train_pa2_regr": _regr.train_pa2_regr,
    "train_pa2a_regr": _regr.train_pa2a_regr,
    "train_arow_regr": _regr.train_arow_regr,
    "train_arowe_regr": _regr.train_arowe_regr,
    "train_arowe2_regr": _regr.train_arowe2_regr,
    "train_adagrad_regr": _regr.train_adagrad_regr,
    "train_adadelta_regr": _regr.train_adadelta_regr,
    # tools: array
    "float_array": float_array,
    "array_remove": array_remove,
    "sort_and_uniq_array": sort_and_uniq_array,
    "subarray_endwith": subarray_endwith,
    "subarray_startwith": subarray_startwith,
    "array_concat": array_concat,
    "concat_array": array_concat,
    "subarray": subarray,
    "array_avg": array_avg,
    "array_sum": array_sum,
    "to_string_array": to_string_array,
    "array_intersect": array_intersect,
    "collect_all": collect_all,
    # tools: bits
    "bits_collect": bits_collect,
    "to_bits": to_bits,
    "unbits": unbits,
    "bits_or": bits_or,
    # tools: compress
    "inflate": inflate,
    "deflate": deflate,
    # tools: map
    "map_get_sum": map_get_sum,
    "map_tail_n": map_tail_n,
    "to_map": to_map,
    "to_ordered_map": to_ordered_map,
    # tools: math / mapred / misc / text
    "sigmoid": sigmoid,
    "taskid": taskid,
    "jobid": jobid,
    "rowid": rowid,
    "distcache_gets": distcache_gets,
    "jobconf_gets": jobconf_gets,
    "generate_series": generate_series,
    "convert_label": convert_label,
    "x_rank": x_rank,
    "each_top_k": each_top_k,
    "tokenize": tokenize,
    "is_stopword": is_stopword,
    "split_words": split_words,
    "normalize_unicode": normalize_unicode,
    "base91": base91,
    "unbase91": unbase91,
    # dataset
    "lr_datagen": lr_datagen,
    # evaluation (§2.11)
    "f1score": f1score,
    "mae": mae,
    "mse": mse,
    "rmse": rmse,
    "r2": r2,
    "ndcg": ndcg,
    "logloss": logloss,
    # MF (§2.7)
    "mf_predict": mf_predict,
    "train_mf_sgd": train_mf_sgd,
    "train_mf_adagrad": train_mf_adagrad,
    "train_bprmf": train_bprmf,
    "bprmf_predict": bprmf_predict,
    # FM / FFM (§2.6)
    "fm_predict": fm_predict,
    "train_fm": train_fm,
    "train_ffm": train_ffm,
    "ffm_predict": ffm_predict,
    # trees (§2.8)
    # nlp (ref: resources/ddl/define-additional.hive:9-10)
    "tokenize_ja": __import__("hivemall_tpu.nlp", fromlist=["tokenize_ja"]).tokenize_ja,
    # trees (§2.8)
    "train_randomforest_classifier": train_randomforest_classifier,
    "train_randomforest_regressor": train_randomforest_regr,
    "train_randomforest_regr": train_randomforest_regr,
    "train_gradient_tree_boosting_classifier": train_gradient_tree_boosting_classifier,
    "tree_predict": tree_predict,
    "guess_attribute_types": guess_attrs,
}

MACROS: Dict[str, Callable] = {
    "java_min": java_min,
    "max2": max2,
    "min2": min2,
    "rand_gid": rand_gid,
    "rand_gid2": rand_gid2,
    "idf": idf,
    "tfidf": tfidf,
}


def get_function(name: str) -> Callable:
    fn = REGISTRY.get(name) or MACROS.get(name)
    if fn is None:
        raise KeyError(f"unknown function {name!r}; see list_functions()")
    return fn


def list_functions() -> List[str]:
    return sorted(REGISTRY) + sorted(MACROS)


def macros() -> Dict[str, Callable]:
    return dict(MACROS)
