from .tokenizer import tokenize_ja  # noqa: F401
