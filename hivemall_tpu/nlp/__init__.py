from .tokenizer import tokenize_ja, tokenize_ja_bulk  # noqa: F401
