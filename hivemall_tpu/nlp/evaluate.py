"""Segmentation accuracy scoring for tokenize_ja against a gold standard.

The standard word-segmentation metric: tokens become character spans
(cumulative offsets over the concatenated token text), and precision /
recall / F1 are micro-averaged over exact span matches — the same scheme
used to score Japanese/Chinese segmenters against corpora. The bundled
gold fixture (tests/data/tokenize_ja_gold.tsv: 100+ hand-verified everyday
sentences at IPADic granularity) gates the built-in lattice analyzer's
quality as a NUMBER rather than a structural claim (reference behavior
bar: KuromojiUDF NORMAL mode over IPADic,
nlp/src/main/java/hivemall/nlp/tokenizer/KuromojiUDF.java:55-86)."""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple


def token_spans(tokens: Sequence[str]) -> List[Tuple[int, int]]:
    """Tokens -> (start, end) character spans over their concatenation."""
    spans = []
    pos = 0
    for t in tokens:
        spans.append((pos, pos + len(t)))
        pos += len(t)
    return spans


def segmentation_prf(
        pairs: Sequence[Tuple[Sequence[str], Sequence[str]]]) -> Dict:
    """Micro-averaged span precision/recall/F1 over (gold, predicted)
    token-list pairs. Both sides must cover the same character stream
    (punctuation excluded on both, as the analyzer drops it); a coverage
    mismatch shows up as span misses, i.e. a lower score, never a crash."""
    tp = fp = fn = 0
    for gold, pred in pairs:
        g = set(token_spans(gold))
        p = set(token_spans(pred))
        tp += len(g & p)
        fp += len(p - g)
        fn += len(g - p)
    precision = tp / (tp + fp) if tp + fp else 0.0
    recall = tp / (tp + fn) if tp + fn else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall else 0.0)
    return {"precision": precision, "recall": recall, "f1": f1,
            "gold_tokens": tp + fn, "predicted_tokens": tp + fp}


def load_gold(path: str) -> List[Tuple[str, List[str]]]:
    """Read a `sentence<TAB>tok1 tok2 ...` fixture."""
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.rstrip("\n")
            if not line or line.startswith("#"):
                continue
            sent, toks = line.split("\t")
            out.append((sent, toks.split(" ")))
    return out
