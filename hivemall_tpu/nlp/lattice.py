"""Lattice (Viterbi) Japanese morphological segmenter.

The same algorithm Kuromoji runs over IPADic (build a word lattice over the
sentence from dictionary hits + unknown-word candidates, pick the min-cost
path with Viterbi; ref: KuromojiUDF's Lucene JapaneseTokenizer,
nlp/src/main/java/hivemall/nlp/tokenizer/KuromojiUDF.java:55-86), scaled to
the bundled lexicon (nlp/lexicon_ja.py):

- dictionary nodes: every lexicon surface matching at each position;
- unknown-word nodes: maximal same-character-class runs (kanji runs also at
  lengths 1..4 so lexicalized splits can win), priced above lexicon entries
  per MeCab's unknown-word model;
- path cost = word costs + POS-bigram connection costs (a small hand-tuned
  matrix standing in for IPADic's full 1316^2 connection table).

Pure host-side code, like the reference's JVM analyzer — tokenization feeds
the feature pipeline (tf/feature_hashing) and never touches the device.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .lexicon_ja import AUX, ADJ, N, P, PRE, V, build_lexicon

# class ids shared with the native kernel (hm_lattice_tokenize_bulk)
_CLASS_IDS = {"hira": 0, "kata": 1, "kanji": 2, "num": 3, "latin": 4,
              "space": 5, "punct": 6}

_UNK_KANJI = "名詞"      # unknown kanji run -> noun (IPADic unk model)
_UNK_KATA = "名詞"       # katakana run -> noun (loanword)
_UNK_HIRA = "動詞"       # unknown hiragana run -> most often a verb chunk
_UNK_LATIN = "名詞"
_UNK_NUM = "名詞"

# connection costs: (left_pos, right_pos) -> cost. Negative = favored.
_CONN: Dict[Tuple[str, str], int] = {
    (N, P): -150,        # noun + particle: the backbone of Japanese syntax
    (V, AUX): -250,      # verb stem + auxiliary (食べ+た, 書き+ます)
    (ADJ, AUX): -150,    # 高かっ+た
    (AUX, AUX): -100,    # まし+た, なかっ+た
    (P, V): -50,         # particle then verb
    (P, N): -50,
    (PRE, N): -150,      # この+人
    ("接頭詞", N): -200,  # お+風呂 (prefix binds to the following noun)
    (N, AUX): -50,       # noun + copula です/だ
    (N, N): 150,         # discourage spurious noun-noun splits vs compounds
    (P, P): 100,         # two particles in a row happens (には) but rarer
    (AUX, N): 100,
    (V, V): 200,
}

_BOS = "BOS"


def _char_class(ch: str) -> str:
    o = ord(ch)
    if 0x3040 <= o <= 0x309F:
        return "hira"
    if 0x30A0 <= o <= 0x30FF or o == 0x30FC:
        return "kata"
    if 0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF or o == 0x3005:  # 々
        return "kanji"
    if ch.isdigit():
        return "num"
    if ch.isalnum():
        return "latin"
    if ch.isspace():
        return "space"
    return "punct"


# unknown-word pricing: (base, per_char). Above lexicon costs so dictionary
# analyses win; hiragana steepest (function words must come from the lexicon).
_UNK_COST = {
    # kanji retuned round 5 (blind4 post-record): at (900,900) a fresh
    # 2-kanji compound with ONE lexicalized kanji shredded — lexical-1
    # (~430) + unknown-1 (1800) = ~2380 beat the 2-run price 2700 (10 of
    # blind4's 14 first-pass misses: 雪/崩, 法/案, 巨/額...). (1100, 500)
    # prices runs 1600/2100/2600/3100 so the 2-run beats lexical-1 +
    # unknown-1 (~2180+conn) while single-kanji unknowns stay above every
    # lexicon tier and suffix splits on LEXICAL hosts still win
    "kanji": (1100, 500),
    "kata": (700, 250),
    "hira": (1200, 1800),
    "latin": (600, 100),
    "num": (600, 100),
}

_UNK_POS = {"kanji": _UNK_KANJI, "kata": _UNK_KATA, "hira": _UNK_HIRA,
            "latin": _UNK_LATIN, "num": _UNK_NUM}


def _class_array(cps: np.ndarray, texts: List[str]) -> np.ndarray:
    """Per-codepoint class ids for the native kernel. The common ranges
    resolve vectorized; anything else falls back to _char_class per char so
    Python's unicode isspace/isdigit/isalnum semantics remain authoritative
    (full-width digits, exotic scripts, odd whitespace)."""
    cls = np.full(cps.shape, 255, np.uint8)
    cp = cps.astype(np.uint32)
    cls[(cp >= 0x3040) & (cp <= 0x309F)] = 0  # hira
    cls[(cp >= 0x30A0) & (cp <= 0x30FF)] = 1  # kata (incl. 30FC)
    cls[((cp >= 0x4E00) & (cp <= 0x9FFF)) |
        ((cp >= 0x3400) & (cp <= 0x4DBF))] = 2  # kanji
    # ASCII
    cls[(cp >= 0x30) & (cp <= 0x39)] = 3
    cls[((cp >= 0x41) & (cp <= 0x5A)) | ((cp >= 0x61) & (cp <= 0x7A))] = 4
    cls[((cp >= 0x09) & (cp <= 0x0D)) | ((cp >= 0x1C) & (cp <= 0x1F)) |
        (cp == 0x20)] = 5
    ascii_rest = (cp < 0x80) & (cls == 255)
    cls[ascii_rest] = 6
    # the common CJK marks only — parts of the 0x3000 block are alnum in
    # Python (〇 numeric letter, 〆), so anything else resolves below
    cls[(cp == 0x3001) | (cp == 0x3002) |  # 、 。
        ((cp >= 0x3008) & (cp <= 0x3011)) |  # 〈〉《》「」『』【】
        (cp == 0x3014) | (cp == 0x3015)] = 6
    cls[cp == 0x3000] = 5  # ideographic space
    cls[cp == 0x3005] = 2  # 々
    # everything else: exact Python classification, char by char (rare)
    unresolved = np.nonzero(cls == 255)[0]
    if len(unresolved):
        flat = "".join(texts)
        for i in unresolved:
            cls[i] = _CLASS_IDS[_char_class(flat[i])]
    return cls


class LatticeTokenizer:
    """Viterbi over dictionary + unknown-word lattice. Returns
    (surface, pos) pairs; punctuation/space are path breaks (the Lucene
    analyzer likewise drops punctuation)."""

    def __init__(self, lexicon: Optional[Dict[str, List[Tuple[str, int]]]] = None):
        self.lexicon = lexicon if lexicon is not None else build_lexicon()
        self.max_word = max(len(s) for s in self.lexicon)
        self._native_tables = None  # built lazily by tokenize_bulk

    def _build_native_tables(self):
        """Marshal the lexicon / connection costs / unknown model into the
        flat arrays hm_lattice_tokenize_bulk consumes (codepoint surfaces,
        per-surface entry ranges in INSERTION order so candidate iteration —
        and therefore Viterbi tie-breaking — matches _viterbi exactly)."""
        pos_set = {p for entries in self.lexicon.values() for p, _ in entries}
        pos_set |= set(_UNK_POS.values())
        pos_list = sorted(pos_set)
        pos_id = {p: i for i, p in enumerate(pos_list)}

        surf_cps: List[np.ndarray] = []
        surf_offsets = [0]
        entry_offsets = [0]
        e_pos: List[int] = []
        e_cost: List[int] = []
        for surf, entries in self.lexicon.items():
            if not entries:
                # a surface with no entries yields no dictionary candidate,
                # so it must not suppress unknown candidates in the C kernel
                # (which keys suppression on map membership)
                continue
            cp = np.frombuffer(surf.encode("utf-32-le"), dtype=np.uint32)
            surf_cps.append(cp)
            surf_offsets.append(surf_offsets[-1] + len(cp))
            for p, c in entries:
                e_pos.append(pos_id[p])
                e_cost.append(int(c))
            entry_offsets.append(len(e_pos))

        n_pos = len(pos_list)
        conn = np.zeros((n_pos, n_pos), np.int32)
        for (a, b), c in _CONN.items():
            if a in pos_id and b in pos_id:
                conn[pos_id[a], pos_id[b]] = c
        unk_base = np.zeros(5, np.int32)
        unk_per = np.zeros(5, np.int32)
        unk_pos = np.zeros(5, np.int16)
        for name, cid in _CLASS_IDS.items():
            if cid >= 5:
                continue
            b, p = _UNK_COST[name]
            unk_base[cid], unk_per[cid] = b, p
            unk_pos[cid] = pos_id[_UNK_POS[name]]
        self._native_tables = {
            "pos_list": pos_list,
            "surf_buf": np.ascontiguousarray(
                np.concatenate(surf_cps) if surf_cps else
                np.zeros(0, np.uint32)),
            "surf_offsets": np.asarray(surf_offsets, np.int64),
            "entry_offsets": np.asarray(entry_offsets, np.int64),
            "entry_pos": np.asarray(e_pos, np.int16),
            "entry_cost": np.asarray(e_cost, np.int32),
            "conn": conn, "unk_base": unk_base, "unk_per": unk_per,
            "unk_pos": unk_pos,
        }
        return self._native_tables

    def tokenize_bulk(self, texts: List[str]) -> List[List[Tuple[str, str]]]:
        """Tokenize many texts; uses the native Viterbi when the library is
        built (parity-tested against tokenize(), which stays the semantic
        authority), else loops the Python path."""
        from .. import native

        out = None
        if texts and native.available():
            out = self._tokenize_bulk_native(texts)
        if out is None:
            return [self.tokenize(t) for t in texts]
        return out

    def _tokenize_bulk_native(self, texts: List[str]):
        from .. import native

        tabs = self._native_tables or self._build_native_tables()
        cps_list = [np.frombuffer(t.encode("utf-32-le"), dtype=np.uint32)
                    for t in texts]
        text_offsets = np.zeros(len(texts) + 1, np.int64)
        for i, c in enumerate(cps_list):
            text_offsets[i + 1] = text_offsets[i] + len(c)
        cps = np.ascontiguousarray(
            np.concatenate(cps_list) if cps_list else np.zeros(0, np.uint32))
        classes = _class_array(cps, texts)
        res = native.lattice_tokenize_bulk(
            cps, classes, text_offsets, tabs["surf_buf"],
            tabs["surf_offsets"], tabs["entry_offsets"], tabs["entry_pos"],
            tabs["entry_cost"], self.max_word, tabs["conn"],
            tabs["unk_base"], tabs["unk_per"], tabs["unk_pos"])
        if res is None:
            return None
        starts, lens, pos_ids, counts = res
        pos_list = tabs["pos_list"]
        out: List[List[Tuple[str, str]]] = []
        k = 0
        for i, text in enumerate(texts):
            n = int(counts[i])
            toks = [(text[starts[j]:starts[j] + lens[j]],
                     pos_list[pos_ids[j]]) for j in range(k, k + n)]
            out.append(toks)
            k += n
        return out

    def tokenize(self, text: str) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        # segment at space/punct boundaries; lattice runs per segment
        seg = ""
        for ch in text:
            if _char_class(ch) in ("space", "punct"):
                if seg:
                    out.extend(self._viterbi(seg))
                    seg = ""
            else:
                seg += ch
        if seg:
            out.extend(self._viterbi(seg))
        return out

    def decompound(self, token: str) -> List[str]:
        """SEARCH-mode splitting of a long compound (>= 4 chars): re-run the
        lattice over the token with whole-token candidates suppressed, so
        the best dictionary-backed split wins (機械学習 -> 機械/学習) and
        unknown compounds fall to their 2-char unknown pieces — the analog
        of Kuromoji SEARCH mode's long-kanji-node penalty. Returns [] when
        the token should stay whole (shorter than 4, or no split parses)."""
        if len(token) < 4:
            return []
        parts = [s for s, _ in self._viterbi(token, suppress_whole=True)]
        # only trust DICTIONARY-BACKED splits: at least half the characters
        # must sit in lexicon entries of length >= 2, else (all-unknown
        # compound) the lattice split is arbitrary — Kuromoji likewise only
        # decompounds via dictionary entries; the caller falls back to
        # recall-oriented 2-grams
        covered = sum(len(s) for s in parts
                      if len(s) >= 2 and s in self.lexicon)
        if len(parts) > 1 and 2 * covered >= len(token):
            return parts
        return []

    def _candidates(self, s: str, i: int,
                    suppress_whole: bool = False) -> List[Tuple[str, str, int]]:
        """(surface, pos, word_cost) candidates starting at position i.
        `suppress_whole` drops any candidate spanning all of `s` (the
        decompound path must produce >= 2 parts)."""
        cands: List[Tuple[str, str, int]] = []
        # dictionary hits
        for L in range(1, min(self.max_word, len(s) - i) + 1):
            if suppress_whole and i == 0 and L == len(s):
                continue
            surf = s[i : i + L]
            for pos, cost in self.lexicon.get(surf, ()):
                cands.append((surf, pos, cost))
        # unknown-word candidates over the same-class run
        cls = _char_class(s[i])
        run = 1
        while i + run < len(s) and _char_class(s[i + run]) == cls:
            run += 1
        base, per = _UNK_COST[cls]
        pos = _UNK_POS[cls]
        if cls in ("kata", "latin", "num"):
            lengths = [run]  # whole run: loanwords/numbers don't split
        elif cls == "kanji":
            lengths = list(range(1, min(run, 4) + 1))
            if run > 4:
                lengths.append(run)
        else:  # hira
            lengths = list(range(1, min(run, 3) + 1))
        for L in lengths:
            if suppress_whole and i == 0 and L == len(s):
                continue
            surf = s[i : i + L]
            if any(c[0] == surf for c in cands):
                continue  # lexicon entry already covers this surface
            cands.append((surf, pos, base + per * L))
        return cands

    def _viterbi(self, s: str,
                 suppress_whole: bool = False) -> List[Tuple[str, str]]:
        n = len(s)
        # best[i][pos] = (cost, prev_index, prev_pos, surface): the cheapest
        # path reaching position i whose LAST token has that pos. Keeping a
        # state per (position, pos) — not one per position — is what makes
        # the POS-bigram connection model actually first-order: a dearer
        # prefix whose final pos connects better downstream (生まれ+た at
        # V,AUX -250) must survive the cheaper 生ま+れ AUX state at the same
        # boundary (the round-5 blind3 fixture caught the collapsed version
        # shredding exactly that class of parse).
        best: List[Dict[str, Tuple[int, int, str, str]]] = \
            [dict() for _ in range(n + 1)]
        best[0][_BOS] = (0, -1, "", "")
        for i in range(n):
            if not best[i]:
                continue
            cands = self._candidates(s, i, suppress_whole)
            # states iterate in sorted-pos order, BOS last — the SAME order
            # the native kernel scans its state rows (st = 0..n_pos with
            # BOS at n_pos), so strict-< tie-breaking picks identical paths
            # on both (test_bulk_path_scores_identically depends on it)
            for pos_i in sorted(p for p in best[i] if p != _BOS) + \
                    ([_BOS] if _BOS in best[i] else []):
                cost_i = best[i][pos_i][0]
                for surf, pos, wcost in cands:
                    j = i + len(surf)
                    conn = 0 if pos_i == _BOS else _CONN.get((pos_i, pos), 0)
                    total = cost_i + wcost + conn
                    cur = best[j].get(pos)
                    if cur is None or total < cur[0]:
                        best[j][pos] = (total, i, pos_i, surf)
        if not best[n]:
            # unreachable (shouldn't happen: 1-char unknowns always exist)
            return [(s, _UNK_POS.get(_char_class(s[0]), N))]
        # backtrack from the cheapest end state (sorted scan + strict < ==
        # the native kernel's ascending-id end scan)
        pos = min(sorted(best[n]), key=lambda p: best[n][p][0])
        toks: List[Tuple[str, str]] = []
        i = n
        while i > 0:
            _, prev, prev_pos, surf = best[i][pos]
            toks.append((surf, pos))
            i, pos = prev, prev_pos
        toks.reverse()
        return toks
