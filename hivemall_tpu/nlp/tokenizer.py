"""`tokenize_ja` — Japanese tokenization.

Mirrors KuromojiUDF (ref: nlp/src/main/java/hivemall/nlp/tokenizer/KuromojiUDF.java:55-120):
`tokenize_ja(text [, mode [, stopwords [, stoptags]]])` with mode
NORMAL/SEARCH/EXTENDED, a stopword list, and POS stoptag filtering.

Backend resolution: an external morphological analyzer (fugashi/MeCab or
janome) is used when installed; otherwise the BUILT-IN lattice analyzer
(nlp/lattice.py — Viterbi over the bundled lexicon + unknown-word models,
the same algorithm Kuromoji runs over IPADic) is the default, so the
in-image behavior is always morphological, with POS tags for stoptag
filtering. The character-class segmenter (_charclass_tokenize) remains as a
library function for callers that want raw script-run splitting.
"""

from __future__ import annotations

import unicodedata
from typing import List, Optional, Sequence

from .lattice import _char_class

_BACKEND = None
_BACKEND_NAME = "charclass"


def _resolve_backend():
    global _BACKEND, _BACKEND_NAME
    if _BACKEND is not None:
        return _BACKEND
    try:
        import fugashi  # type: ignore

        _BACKEND = fugashi.Tagger()
        _BACKEND_NAME = "fugashi"
        return _BACKEND
    except ImportError:
        pass
    try:
        from janome.tokenizer import Tokenizer  # type: ignore

        _BACKEND = Tokenizer()
        _BACKEND_NAME = "janome"
        return _BACKEND
    except ImportError:
        pass
    from .lattice import LatticeTokenizer

    _BACKEND = LatticeTokenizer()
    _BACKEND_NAME = "lattice"
    return _BACKEND


def _charclass_tokenize(text: str) -> List[str]:
    tokens: List[str] = []
    cur = ""
    cur_cls = None
    for ch in text:
        # digits group with latin here (historical raw-run behavior:
        # "JAX2026" stays one token), unlike the lattice's own unknown-word
        # model which prices digit runs separately
        cls = _char_class(ch)
        if cls == "num":
            cls = "latin"
        if cls in ("space", "punct"):
            if cur:
                tokens.append(cur)
                cur, cur_cls = "", None
            continue
        if cls != cur_cls and cur:
            tokens.append(cur)
            cur = ""
        cur += ch
        cur_cls = cls
    if cur:
        tokens.append(cur)
    return tokens


def backend_name() -> str:
    _resolve_backend()
    return _BACKEND_NAME


def tokenize_ja_bulk(texts: Sequence[str], mode: str = "normal",
                     stopwords: Optional[Sequence[str]] = None,
                     stoptags: Optional[Sequence[str]] = None
                     ) -> List[List[str]]:
    """Corpus-shaped tokenize_ja: one call over many documents. With the
    built-in lattice backend and NORMAL mode, segmentation runs through the
    native bulk Viterbi (nlp/lattice.py::tokenize_bulk — parity-tested
    against the per-text path); SEARCH/EXTENDED and external backends fall
    back to per-text tokenize_ja. Feeds tf/feature_hashing pipelines
    (the KuromojiUDF-over-a-corpus usage)."""
    mode_l = (mode or "normal").lower()
    backend = _resolve_backend()
    if _BACKEND_NAME != "lattice" or mode_l != "normal":
        return [tokenize_ja(t, mode, stopwords, stoptags) for t in texts]
    normalized = [unicodedata.normalize("NFKC", t or "") for t in texts]
    stop_top = {t for t in (stoptags or ()) if "-" not in t}
    stop = set(stopwords or ())
    out: List[List[str]] = []
    for pairs in backend.tokenize_bulk(normalized):
        toks = [s for s, pos in pairs if pos not in stop_top]
        if stop:
            toks = [t for t in toks if t not in stop]
        out.append(toks)
    return out


def tokenize_ja(text: str, mode: str = "normal",
                stopwords: Optional[Sequence[str]] = None,
                stoptags: Optional[Sequence[str]] = None) -> List[str]:
    if text is None:
        return []
    mode = (mode or "normal").lower()
    if mode not in ("normal", "search", "extended"):
        raise ValueError(f"unsupported mode {mode!r} (normal/search/extended)")
    text = unicodedata.normalize("NFKC", text)
    backend = _resolve_backend()
    tokens: List[str] = []
    if _BACKEND_NAME == "lattice":
        # Kuromoji stoptags are hierarchical ("助詞-格助詞"); the built-in
        # lattice carries top-level POS only, so a top-level stoptag filters
        # that whole class, while a narrower hierarchical tag matches
        # nothing here (never over-filter an entire class because the user
        # asked to drop one subtype)
        stop_top = {t for t in (stoptags or ()) if "-" not in t}
        for surface, pos in backend.tokenize(text):
            if pos in stop_top:
                continue
            tokens.append(surface)
    elif _BACKEND_NAME == "fugashi":
        stop_pos = set(stoptags or [])
        for word in backend(text):
            pos = word.feature.pos1 if hasattr(word.feature, "pos1") else ""
            if stop_pos and pos in stop_pos:
                continue
            tokens.append(word.surface)
    elif _BACKEND_NAME == "janome":
        stop_pos = set(stoptags or [])
        for tok in backend.tokenize(text):
            pos = tok.part_of_speech.split(",")[0]
            if stop_pos and pos in stop_pos:
                continue
            tokens.append(tok.surface)
    if mode in ("search", "extended"):
        # SEARCH mode additionally decompounds long tokens (Kuromoji keeps
        # the compound AND emits its parts). The lattice backend re-segments
        # the compound with whole-token candidates suppressed (dictionary-
        # backed split); other backends fall back to kanji 2-grams.
        extra: List[str] = []
        decompounded = set()
        for t in tokens:
            # Kuromoji SEARCH penalizes long kanji (>=4 here) and long
            # other-script runs (>=7) so lexicalized splits win; katakana
            # compounds only decompound dictionary-backed (no 2-gram
            # fallback — kana 2-grams are noise)
            is_kanji = len(t) >= 4 and all(_char_class(c) == "kanji" for c in t)
            is_long_kata = len(t) >= 7 and all(_char_class(c) == "kata" for c in t)
            if is_kanji or is_long_kata:
                parts: List[str] = []
                if _BACKEND_NAME == "lattice":
                    parts = backend.decompound(t)
                if parts:
                    decompounded.add(t)
                elif mode == "search" and is_kanji:
                    # recall-oriented 2-gram fallback for OOV compounds;
                    # EXTENDED skips it — its own unigram stage below covers
                    # OOV (emitting both would duplicate every character)
                    parts = [t[i : i + 2] for i in range(len(t) - 1)]
                extra.extend(parts)
        tokens = tokens + extra
    if mode == "extended":
        # EXTENDED additionally replaces UNKNOWN words with their character
        # 1-grams (Kuromoji Mode.EXTENDED: unknown terms are n-grammed so
        # OOV text still matches at search time; known terms pass through).
        # "Unknown" = not a dictionary word for the lattice backend; other
        # backends have no cheap membership test, so only multi-char
        # katakana/latin loanword runs — the dominant OOV class — n-gram.
        def _is_unknown(t: str) -> bool:
            if _BACKEND_NAME == "lattice":
                return t not in backend.lexicon
            cls = {_char_class(c) for c in t}
            return len(t) >= 2 and (cls == {"kata"} or cls == {"latin"})

        expanded: List[str] = []
        for t in tokens:
            # a compound whose dictionary-backed split was already emitted
            # stays whole; unigramming it too would double-count every char
            if len(t) >= 2 and _is_unknown(t) and t not in decompounded:
                expanded.extend(t)
            else:
                expanded.append(t)
        tokens = expanded
    if stopwords:
        stop = set(stopwords)
        tokens = [t for t in tokens if t not in stop]
    return tokens
