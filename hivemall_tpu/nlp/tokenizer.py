"""`tokenize_ja` — Japanese tokenization.

Mirrors KuromojiUDF (ref: nlp/src/main/java/hivemall/nlp/tokenizer/KuromojiUDF.java:55-120):
`tokenize_ja(text [, mode [, stopwords [, stoptags]]])` with mode
NORMAL/SEARCH/EXTENDED, a stopword list, and POS stoptag filtering.

Backend resolution: a real morphological analyzer (fugashi/MeCab, janome, or
SudachiPy) is used when installed; otherwise a character-class segmenter
(kanji/kana/latin run boundaries — the standard analyzer-free fallback)
stands in so the function is always callable. POS stoptags only apply when a
morphological backend provides POS tags.
"""

from __future__ import annotations

import re
import unicodedata
from typing import List, Optional, Sequence

_BACKEND = None
_BACKEND_NAME = "charclass"


def _resolve_backend():
    global _BACKEND, _BACKEND_NAME
    if _BACKEND is not None:
        return _BACKEND
    try:
        import fugashi  # type: ignore

        _BACKEND = fugashi.Tagger()
        _BACKEND_NAME = "fugashi"
        return _BACKEND
    except ImportError:
        pass
    try:
        from janome.tokenizer import Tokenizer  # type: ignore

        _BACKEND = Tokenizer()
        _BACKEND_NAME = "janome"
        return _BACKEND
    except ImportError:
        pass
    _BACKEND = False
    return _BACKEND


def _char_class(ch: str) -> str:
    o = ord(ch)
    if 0x3040 <= o <= 0x309F:
        return "hira"
    if 0x30A0 <= o <= 0x30FF or o == 0x30FC:
        return "kata"
    if 0x4E00 <= o <= 0x9FFF or 0x3400 <= o <= 0x4DBF:
        return "kanji"
    if ch.isalnum():
        return "latin"
    if ch.isspace():
        return "space"
    return "punct"


def _charclass_tokenize(text: str) -> List[str]:
    tokens: List[str] = []
    cur = ""
    cur_cls = None
    for ch in text:
        cls = _char_class(ch)
        if cls in ("space", "punct"):
            if cur:
                tokens.append(cur)
                cur, cur_cls = "", None
            continue
        if cls != cur_cls and cur:
            tokens.append(cur)
            cur = ""
        cur += ch
        cur_cls = cls
    if cur:
        tokens.append(cur)
    return tokens


def backend_name() -> str:
    _resolve_backend()
    return _BACKEND_NAME


def tokenize_ja(text: str, mode: str = "normal",
                stopwords: Optional[Sequence[str]] = None,
                stoptags: Optional[Sequence[str]] = None) -> List[str]:
    if text is None:
        return []
    mode = (mode or "normal").lower()
    if mode not in ("normal", "search", "extended"):
        raise ValueError(f"unsupported mode {mode!r} (normal/search/extended)")
    text = unicodedata.normalize("NFKC", text)
    backend = _resolve_backend()
    tokens: List[str] = []
    if backend is False:
        tokens = _charclass_tokenize(text)
    elif _BACKEND_NAME == "fugashi":
        stop_pos = set(stoptags or [])
        for word in backend(text):
            pos = word.feature.pos1 if hasattr(word.feature, "pos1") else ""
            if stop_pos and pos in stop_pos:
                continue
            tokens.append(word.surface)
    elif _BACKEND_NAME == "janome":
        stop_pos = set(stoptags or [])
        for tok in backend.tokenize(text):
            pos = tok.part_of_speech.split(",")[0]
            if stop_pos and pos in stop_pos:
                continue
            tokens.append(tok.surface)
    if mode in ("search", "extended"):
        # SEARCH mode additionally decompounds long tokens; the fallback
        # approximates by also emitting 2-grams of long kanji runs
        extra: List[str] = []
        for t in tokens:
            if len(t) >= 4 and all(_char_class(c) == "kanji" for c in t):
                extra.extend(t[i : i + 2] for i in range(len(t) - 1))
        tokens = tokens + extra
    if stopwords:
        stop = set(stopwords)
        tokens = [t for t in tokens if t not in stop]
    return tokens
