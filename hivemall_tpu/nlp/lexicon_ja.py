"""Built-in Japanese lexicon for the lattice tokenizer (nlp/lattice.py).

A compact IPADic-style morpheme inventory — function words enumerated, verb
and adjective inflections GENERATED from stems by conjugation class — so the
in-image `tokenize_ja` default is a real morphological analyzer rather than
a character-class splitter (parity target: KuromojiUDF NORMAL mode,
ref: nlp/src/main/java/hivemall/nlp/tokenizer/KuromojiUDF.java:55-86, whose
Lucene JapaneseTokenizer consults the bundled IPADic the same way).

Granularity matches IPADic: inflected predicates split stem + auxiliaries
(食べました -> 食べ/まし/た), particles are single morphemes, compounds stay
whole when lexicalized. Costs are hand-scaled integers: lower = preferred;
the unknown-word models in lattice.py are priced above lexicon entries so
known analyses win.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

# POS tags (IPADic top-level)
N = "名詞"          # noun
P = "助詞"          # particle
AUX = "助動詞"      # auxiliary verb
V = "動詞"          # verb
ADJ = "形容詞"      # i-adjective
ADV = "副詞"        # adverb
CONJ = "接続詞"     # conjunction
PRE = "連体詞"      # prenominal
PRON = "名詞"       # pronouns filed as nouns, like IPADic 名詞-代名詞
SYM = "記号"        # symbol

_PARTICLES = [
    # 格助詞 / 係助詞 / 接続助詞 / 終助詞 / 副助詞
    "が", "を", "に", "で", "と", "へ", "から", "まで", "より", "の",
    "は", "も", "こそ", "さえ", "しか", "だけ", "ほど", "くらい", "ぐらい",
    "など", "なら", "ば", "ながら", "つつ", "ので", "のに", "けど", "けれど",
    "けれども", "か", "ね", "よ", "な", "わ", "ぞ", "や", "とか", "って",
    # IPADic 連語 compounds (one token each, like 格助詞,連語)
    "について", "による", "によって", "に対して", "として", "とともに",
    "にとって", "に関して", "をめぐって",
]

_AUXILIARIES = [
    # copulas + inflecting auxiliaries, IPADic-style split units: です
    # conjugates でし+た / でしょ+う, だ conjugates だっ+た / だろ+う,
    # ます conjugates まし+た / ましょ+う (the fused surfaces でした etc.
    # are NOT entries, exactly like IPADic)
    "です", "でし", "でしょ", "だ", "だっ", "だろ", "である",
    "ます", "まし", "ませ", "ましょ", "た", "て", "で",
    "ない", "なかっ", "なく", "ぬ", "ん", "う", "よう", "たら", "だら",
    "れる", "られる", "れ", "られ", "せる", "させる", "せ", "させ",
    "たい", "たかっ", "そう", "らしい", "みたい", "べき", "ちゃ", "じゃ",
]

_NOUNS = [
    # pronouns / demonstratives
    "私", "僕", "俺", "彼", "彼女", "誰", "何", "これ", "それ", "あれ",
    "どれ", "ここ", "そこ", "あそこ", "どこ", "こちら", "そちら",
    # time
    "今日", "明日", "昨日", "今", "今年", "去年", "来年", "毎日", "朝",
    "昼", "夜", "時間", "時", "年", "月", "日", "週", "分", "秒", "午前",
    "午後",
    # common concrete/abstract
    "人", "人間", "子供", "男", "女", "友達", "家族", "先生", "学生",
    "日本", "日本語", "英語", "東京", "京都", "世界", "国", "町", "村",
    "学校", "大学", "会社", "仕事", "電話", "映画", "音楽", "写真",
    "本", "新聞", "手紙", "名前", "言葉", "話", "意味", "問題", "質問",
    "答え", "勉強", "研究", "旅行", "買い物", "料理", "食事", "朝食",
    "昼食", "夕食", "水", "お茶", "御飯", "ご飯", "肉", "魚", "野菜",
    "寿司", "犬", "猫", "鳥", "花", "木", "山", "川", "海", "空", "雨",
    "雪", "風", "天気", "車", "電車", "自転車", "飛行機", "駅", "道",
    "家", "部屋", "店", "お金", "金", "手", "足", "目", "耳", "口",
    "頭", "体", "心", "気", "声", "色", "形", "数", "前", "後", "上",
    "下", "中", "外", "間", "こと", "もの", "ところ", "とき", "ため",
    "ほう", "方", "的", "さん", "君", "様", "機械", "学習", "計算",
    "情報", "技術", "言語", "処理", "自然", "国際", "空港", "科学",
    "関西", "関東", "経済", "政治", "社会", "文化", "歴史", "教育",
    "環境", "開発", "分析", "予測", "回帰", "分類", "学会", "論文",
    # round-4 growth toward the gold-set gate (everyday vocabulary)
    "椅子", "興味", "窓", "予定", "来週", "来月", "毎朝", "紅茶",
    "どちら", "妹", "弟", "兄", "姉", "母", "父", "医者", "荷物",
    "夏休み", "春", "夏", "秋", "冬", "気持ち", "銀行", "番号", "地図",
    "病院", "薬", "約束", "漢字", "宿題", "歌", "みんな", "景色",
    "台所", "公園", "散歩", "会議", "資料", "電気", "風呂", "男の子",
    "女の子", "場所", "道具", "人口", "結果", "準備", "原因", "注目",
    "確認", "発表", "精度", "基本", "本当", "掃除", "図書館", "たち",
    # post-held-out growth (everyday nouns/compounds; the held-out
    # fixture's blind first-pass number was recorded BEFORE this batch)
    "駅前", "今朝", "今夜", "夜空", "歌手", "誕生日", "週末", "牛乳",
    "靴", "庭", "星", "隣", "自分", "意見", "橋", "昔", "山頂", "空気",
    "通り", "角", "信号", "交差点", "地下鉄", "切符", "財布", "鍵",
    "眼鏡", "帽子", "服", "洗濯", "冷蔵庫", "電子", "機器", "画面",
    "携帯", "番組", "広告", "記事", "作品", "小説", "詩", "絵", "曲",
    "声優", "俳優", "選手", "監督", "観客", "客", "店員", "社員",
    "社長", "部長", "課長", "同僚", "上司", "隣人", "親", "祖父",
    "祖母", "孫", "夫", "妻", "息子", "娘", "赤ちゃん", "大人",
    "老人", "若者", "皆", "全員", "相手", "他人", "知り合い",
    # 形容動詞語幹 (na-adjective stems), IPADic files them 名詞
    "好き", "嫌い", "きれい", "静か", "有名", "大切", "便利", "元気",
    "大変", "簡単", "上手", "下手", "得意", "親切", "特別", "必要",
    "安全", "危険", "自由", "平等", "正直", "素直", "真面目", "複雑",
    "単純", "豊か", "確か", "十分", "無理", "無駄", "邪魔", "丁寧",
    "適当", "楽", "暇", "重要", "貴重", "新鮮", "当然", "完全",
    "熱心", "活発", "立派", "綺麗", "苦手", "残念", "不思議",
    # numerals + common counters (IPADic 名詞,数 / 名詞,接尾,助数詞)
    "一", "二", "三", "四", "五", "六", "七", "八", "九", "十",
    "百", "千", "万", "円", "度", "回", "個", "冊", "枚", "匹",
    "一つ", "二つ", "三つ", "四つ", "五つ",
    # round-4b growth batch 1: news / public life
    "政府", "首相", "大統領", "選挙", "議員", "国会", "警察", "事故",
    "事件", "被害", "災害", "地震", "台風", "津波", "火事", "戦争",
    "平和", "法律", "裁判", "契約", "権利", "義務", "制度", "政策",
    "価格", "値段", "商品", "製品", "工場", "農業", "産業", "企業",
    "市場", "株", "税金", "収入", "給料", "貯金", "保険", "年金",
    "貿易", "輸出", "輸入", "消費", "生産", "需要", "供給", "景気",
    # round-4b growth batch 2: health / body
    "医療", "健康", "病気", "風邪", "熱", "怪我", "手術", "検査",
    "体温", "血", "骨", "肌", "髪", "顔", "鼻", "歯", "首", "肩",
    "背中", "腕", "指", "膝", "腰", "胃", "心臓", "脳",
    # round-4b growth batch 3: mind / communication / abstraction
    "記憶", "夢", "希望", "不安", "心配", "安心", "喜び", "怒り",
    "悲しみ", "驚き", "感動", "感謝", "尊敬", "努力", "成功", "失敗",
    "経験", "知識", "能力", "才能", "性格", "習慣", "文章", "単語",
    "文字", "発音", "文法", "辞書", "翻訳", "会話", "挨拶", "説明",
    "紹介", "案内", "連絡", "報告", "相談", "提案", "計画", "目的",
    "目標", "方法", "手段", "理由", "条件", "状況", "状態", "関係",
    "影響", "変化", "成長", "発展", "進歩", "改善", "解決", "比較",
    "選択", "判断", "決定", "意識", "印象", "想像", "理解", "誤解",
    "表現", "内容", "範囲", "程度", "割合", "平均", "合計", "距離",
    "速度", "温度", "湿度", "気温", "重さ", "高さ", "長さ", "広さ",
    "深さ", "大きさ", "最近", "最初", "最後", "途中", "将来", "未来",
    "過去", "現在", "現実", "理想", "普通", "全部", "半分", "残り",
    # round-4b growth batch 4: daily life / places / objects
    "朝御飯", "晩御飯", "弁当", "箸", "皿", "鍋", "卵", "米", "塩",
    "砂糖", "醤油", "味", "匂い", "果物", "林檎", "蜜柑", "葡萄",
    "苺", "西瓜", "玄関", "廊下", "階段", "屋根", "壁", "床", "天井",
    "押入れ", "布団", "枕", "毛布", "石鹸", "歯磨き", "鏡", "椿",
    "桜", "紅葉", "松", "竹", "梅", "森", "林", "畑", "田んぼ",
    "池", "湖", "島", "岩", "石", "砂", "土", "波", "氷", "虹",
    "月曜日", "火曜日", "水曜日", "木曜日", "金曜日", "土曜日",
    "日曜日", "曜日", "祝日", "休日", "平日", "正月", "祭り",
    "神社", "寺", "城", "美術館", "博物館", "動物園", "水族館",
    "映画館", "劇場", "空席", "入口", "出口", "受付", "窓口",
    "切手", "封筒", "葉書", "小包", "郵便", "郵便局",
    # blind2 fold (after its 0.9773 first-pass was recorded — PERF.md):
    # 口座 and 毎週 were two of the three actual misses (the third,
    # について, is filed with the 連語 particles); 毎年/毎月/温泉 are
    # opportunistic siblings added in the same pass, NOT blind misses
    # (温泉 the unknown-word model already segmented correctly)
    "口座", "毎週", "毎年", "毎月", "温泉",
]

_PREFIXES = ["お", "ご"]  # 接頭詞 (お風呂, ご飯 is lexicalized whole)

_MISC_VERBS = [  # polite/formulaic chunks, IPADic-style single units
    "ください", "下さい", "いただき", "いただく", "くれ", "くれる",
    "もらい", "もらう", "あげる", "あり", "ある", "あっ", "なり", "なる",
    "なっ", "思い", "思っ", "言い", "言っ", "行っ", "来まし",
    # ~ておく/~てしまう/~てみる/~てくる benefactive-aspect chains (kana
    # verb forms IPADic lists as ordinary 動詞 entries; blind6 caught おい)
    "おく", "おき", "おい", "おか", "しまう", "しまい", "しまっ",
    "みる", "み", "みれ", "くる", "きまし",
]

_INTERJECTIONS = ["ありがとう", "こんにちは", "こんばんは", "おはよう",
                  "すみません", "さようなら", "はい", "いいえ"]

_KATAKANA_NOUNS = [
    # common loanwords, lexicalized like IPADic so EXTENDED mode's
    # unknown-word unigramming (tokenizer.py) only hits genuinely OOV runs
    "ペン", "テレビ", "ラジオ", "カメラ", "パソコン", "コンピュータ",
    "コンピューター", "スマホ", "インターネット", "メール", "ニュース",
    "データ", "テキスト", "ファイル", "システム", "プログラム", "モデル",
    "テスト", "クラス", "サービス", "ネットワーク", "ソフトウェア",
    "ハードウェア", "ユーザー", "ユーザ", "サーバー", "サーバ", "クラウド",
    "ホテル", "レストラン", "カフェ", "コーヒー", "ビール", "ワイン",
    "ジュース", "パン", "ケーキ", "アイス", "サラダ", "スープ", "バス",
    "タクシー", "バイク", "ドア", "テーブル", "イス", "ベッド", "トイレ",
    "シャワー", "エアコン", "ゲーム", "スポーツ", "サッカー", "テニス",
    "ゴルフ", "ピアノ", "ギター", "コンサート", "パーティー", "プレゼント",
    "アルバイト", "ビジネス", "プロジェクト", "チーム", "グループ",
    "リスト", "ページ", "カード", "チケット", "シャツ", "ズボン", "クツ",
    "カバン", "メートル", "キロ", "グラム", "パーセント", "エネルギー",
    "アメリカ", "ヨーロッパ", "アジア", "フランス", "ドイツ", "イギリス",
    "イタリア", "スペイン", "ロシア", "インド", "カナダ",
    # round-4b growth: tech / modern life loanwords
    "スマートフォン", "タブレット", "アプリ", "ウェブ", "サイト",
    "ブログ", "ビデオ", "アニメ", "ドラマ", "デザイン", "イベント",
    "コンビニ", "スーパー", "デパート", "ビル", "マンション",
    "アパート", "エレベーター", "エスカレーター", "ロボット",
    "バッテリー", "エンジン", "ハンドル", "ガソリン", "ミルク",
    "チーズ", "バター", "チョコレート", "クッキー", "ピザ", "パスタ",
    "ハンバーガー", "サンドイッチ", "フォーク", "ナイフ", "スプーン",
    "コップ", "グラス", "ボトル", "メニュー", "ポケット", "ボタン",
    "ポスト", "バッグ", "ランチ", "ディナー", "パスワード",
    "アカウント", "ログイン", "ダウンロード", "キーボード", "マウス",
    "プリンター", "コピー", "レッスン", "クイズ", "レベル", "スコア",
    "メンバー", "リーダー", "コーチ", "ファン", "ステージ",
    "スクリーン", "カレンダー", "スケジュール", "アイデア", "イメージ",
    "スタイル", "タイプ", "ルール", "マナー", "チャンス", "ストレス",
    "アルゴリズム", "ライブラリ", "フレームワーク", "コード", "バグ",
    "リリース", "バージョン", "メモリ", "ディスク", "ベンチマーク",
]

_ADVERBS = [
    "すごく", "少し", "ちょっと", "たくさん", "もっと", "また",
    "まだ", "すぐ", "いつも", "時々", "よく", "あまり", "全然",
    "きっと", "たぶん", "やはり", "やっぱり", "一緒に", "ゆっくり",
    "はっきり", "しっかり", "そろそろ", "だんだん", "どんどん",
    "なかなか", "ほとんど", "必ず", "絶対", "突然", "急に",
    # round-4b growth
    "すっかり", "ずっと", "さっき", "やっと", "ついに", "いきなり",
    "たまに", "ほぼ", "およそ", "特に", "主に", "実は", "実際",
    "かなり", "ずいぶん", "とにかく", "どうぞ", "どうも", "もちろん",
    "しばらく", "さらに", "すでに", "もうすぐ", "いつか", "いつでも",
    "なるべく", "できるだけ", "わざと", "わざわざ", "偶然", "結局",
    "順番に", "初めて", "久しぶりに", "再び", "常に", "決して",
]

# もう gets a below-particle price: the decomposition も(助詞)+う(助動詞)
# costs 250 on the lattice and is never the right analysis
_CHEAP_ADVERBS = [("もう", 140), ("とても", 140)]
# とても joined もう here when the per-POS lattice exposed a cheaper
# (wrong) と+て+も particle chain at the adverb's old 450 price

_CONJUNCTIONS = ["そして", "しかし", "でも", "だから", "それで", "また",
                 "それから", "つまり", "例えば", "それに", "ところが",
                 "さて", "または", "あるいは", "ただし", "なぜなら",
                 "そこで", "すると", "ですから"]

_PRENOMINALS = ["この", "その", "あの", "どの", "大きな", "小さな", "同じ",
                "ある", "あらゆる", "いわゆる", "いろんな", "色んな"]

# (stem, class) — ichidan drops る; godan conjugates by final kana row;
# suru/kuru irregular listed explicitly below
_ICHIDAN = ["食べ", "見", "出", "寝", "起き", "着", "開け", "閉め", "教え",
            "覚え", "忘れ", "考え", "伝え", "感じ", "信じ", "調べ", "続け",
            "始め", "止め", "決め", "入れ", "届け", "受け", "助け", "逃げ",
            "投げ", "見せ", "乗せ", "任せ", "い", "でき", "生き", "着け",
            "借り", "持て", "出かけ", "遅れ", "疲れ", "見つけ", "増え",
            "まとめ", "覚め", "集め", "比べ", "見え", "聞こえ", "あげ",
            "くれ", "答え", "辞め", "別れ", "慣れ", "触れ", "晴れ",
            # round-4b growth
            "得", "与え", "迎え", "数え", "抱え", "超え", "越え", "燃え",
            "冷え", "消え", "植え", "載せ", "痩せ", "混ぜ", "当て",
            "捨て", "育て", "建て", "立て", "変え", "加え", "落ち",
            "付け", "片付け", "間違え", "着替え", "並べ", "曲げ",
            "下げ", "上げ", "挙げ", "避け", "預け", "勧め", "進め",
            "認め", "眺め", "褒め", "攻め", "責め", "温め", "確かめ"]

_GODAN = [  # (stem-without-final, final dictionary kana)
    ("書", "く"), ("行", "く"), ("聞", "く"), ("歩", "く"), ("働", "く"),
    ("泳", "ぐ"), ("急", "ぐ"), ("話", "す"), ("出", "す"), ("返", "す"),
    ("待", "つ"), ("持", "つ"), ("立", "つ"), ("勝", "つ"), ("死", "ぬ"),
    ("遊", "ぶ"), ("呼", "ぶ"), ("飛", "ぶ"), ("読", "む"), ("飲", "む"),
    ("住", "む"), ("休", "む"), ("頼", "む"), ("作", "る"), ("乗", "る"),
    ("取", "る"), ("帰", "る"), ("走", "る"), ("入", "る"), ("分か", "る"),
    ("終わ", "る"), ("始ま", "る"), ("売", "る"), ("降", "る"), ("曲が", "る"),
    ("買", "う"), ("会", "う"), ("使", "う"), ("思", "う"), ("言", "う"),
    ("習", "う"), ("歌", "う"), ("洗", "う"), ("笑", "う"), ("手伝", "う"),
    ("撮", "る"), ("咲", "く"), ("しま", "う"), ("通", "う"), ("送", "る"),
    ("閉ま", "る"), ("もら", "う"), ("置", "く"), ("消", "す"),
    ("向か", "う"), ("上が", "る"), ("下が", "る"), ("開", "く"),
    ("渡", "す"), ("届", "く"), ("探", "す"), ("学", "ぶ"), ("運", "ぶ"),
    ("光", "る"), ("間に合", "う"), ("思い出", "す"), ("動", "く"),
    ("並", "ぶ"), ("選", "ぶ"), ("残", "る"), ("直", "す"), ("写", "す"),
    ("移", "る"), ("戻", "る"), ("登", "る"), ("踊", "る"), ("怒", "る"),
    ("守", "る"), ("触", "る"), ("切", "る"), ("知", "る"), ("頑張", "る"),
    # round-4b growth
    ("願", "う"), ("祈", "る"), ("変わ", "る"), ("伝わ", "る"),
    ("集ま", "る"), ("決ま", "る"), ("止ま", "る"), ("泊ま", "る"),
    ("困", "る"), ("断", "る"), ("謝", "る"), ("払", "う"), ("拾", "う"),
    ("失", "う"), ("追", "う"), ("誘", "う"), ("迷", "う"), ("救", "う"),
    ("吸", "う"), ("違", "う"), ("飾", "る"), ("配", "る"), ("測", "る"),
    ("落と", "す"), ("起こ", "す"), ("起こ", "る"), ("回", "る"),
    ("回", "す"), ("押", "す"), ("引", "く"), ("弾", "く"), ("吹", "く"),
    ("拭", "く"), ("履", "く"), ("焼", "く"), ("磨", "く"), ("招", "く"),
    ("続", "く"), ("着", "く"), ("付", "く"), ("頂", "く"), ("驚", "く"),
    ("泣", "く"), ("鳴", "く"), ("抜", "く"), ("脱", "ぐ"), ("稼", "ぐ"),
    ("防", "ぐ"), ("指", "す"), ("差", "す"), ("示", "す"), ("試", "す"),
    ("貸", "す"), ("倒", "す"), ("離", "す"), ("育", "つ"), ("打", "つ"),
    ("拭", "う"), ("騒", "ぐ"), ("継", "ぐ"), ("注", "ぐ"), ("頼", "る"),
    ("飼", "う"),
    ("余", "る"), ("眠", "る"), ("刺", "す"), ("治", "す"), ("治", "る"),
    ("過ご", "す"), ("暮ら", "す"), ("増や", "す"), ("減ら", "す"),
    ("動か", "す"), ("驚か", "す"), ("鳴ら", "す"), ("冷や", "す"),
    ("飛ば", "す"), ("伸ば", "す"), ("乾か", "す"), ("沸か", "す"),
    ("減", "る"), ("太", "る"), ("痛", "む"), ("進", "む"), ("盗", "む"),
    ("畳", "む"), ("包", "む"), ("悩", "む"), ("喜", "ぶ"), ("転", "ぶ"),
    ("結", "ぶ"), ("叫", "ぶ"),
]

_I_ADJ_STEMS = ["大き", "小さ", "新し", "古", "高", "安", "良", "悪", "早",
                "遅", "暑", "寒", "熱", "冷た", "美し", "おいし", "うま",
                "難し", "易し", "面白", "楽し", "嬉し", "悲し", "忙し",
                "近", "遠", "長", "短", "強", "弱", "多", "少な", "白",
                "黒", "赤", "青", "明る", "暗", "若", "重", "軽", "涼し",
                "素晴らし", "広", "狭", "深", "浅", "速", "甘", "辛",
                "固", "柔らか", "優し", "厳し", "危な", "正し", "細か",
                # round-4b growth
                "珍し", "激し", "詳し", "親し", "懐かし", "恥ずかし",
                "羨まし", "貧し", "等し", "苦し", "眠", "痛", "汚",
                "賢", "鋭", "鈍", "太", "細", "薄", "厚", "硬",
                "温か", "暖か", "丸", "ぬる", "酸っぱ", "偉", "凄",
                "ひど", "かわい", "可愛", "欲し", "乏し", "険し",
                "めでた", "怪し", "幼", "醜", "尊", "清"]

# godan conjugation rows: final kana -> (a, i, e, o, onbin-ta-form)
# round-5 vocabulary scale-up: extended stems feed the SAME conjugation
# generators (lexicon_ja_ext.py holds pure vocabulary; dedup via `seen`)
from .lexicon_ja_ext import (GODAN_EXT as _GODAN_EXT,
                             GODAN_EXT2 as _GODAN_EXT2,
                             GODAN_EXT3 as _GODAN_EXT3,
                             ICHIDAN_EXT as _ICHIDAN_EXT,
                             ICHIDAN_EXT2 as _ICHIDAN_EXT2,
                             ICHIDAN_EXT3 as _ICHIDAN_EXT3,
                             I_ADJ_EXT as _I_ADJ_EXT)

_ICHIDAN = _ICHIDAN + _ICHIDAN_EXT + _ICHIDAN_EXT2 + _ICHIDAN_EXT3
from .lexicon_ja_ext import I_ADJ_EXT2 as _I_ADJ_EXT2

_I_ADJ_STEMS = _I_ADJ_STEMS + _I_ADJ_EXT + _I_ADJ_EXT2

_GODAN_ROWS = {
    "く": ("か", "き", "け", "こ", "いた"),
    "ぐ": ("が", "ぎ", "げ", "ご", "いだ"),
    "す": ("さ", "し", "せ", "そ", "した"),
    "つ": ("た", "ち", "て", "と", "った"),
    "ぬ": ("な", "に", "ね", "の", "んだ"),
    "ぶ": ("ば", "び", "べ", "ぼ", "んだ"),
    "む": ("ま", "み", "め", "も", "んだ"),
    "る": ("ら", "り", "れ", "ろ", "った"),
    "う": ("わ", "い", "え", "お", "った"),
}

_GODAN = _GODAN + [g for g in _GODAN_EXT + _GODAN_EXT2 + _GODAN_EXT3
                   if g[1] in _GODAN_ROWS]

_COSTS = {P: 100, AUX: 150, CONJ: 300, V: 350, N: 400, ADJ: 400, ADV: 450,
          PRE: 350}


def _verb_forms() -> List[Tuple[str, str, int]]:
    out = []
    seen = set()

    def add(surface, cost_bump=0):
        if surface and surface not in seen:
            seen.add(surface)
            out.append((surface, V, _COSTS[V] + cost_bump))

    for stem in _ICHIDAN:
        add(stem + "る")   # dictionary
        add(stem)          # 連用/未然 (combines with ます/た/ない/て)
        add(stem + "れ", 50)   # 仮定
        add(stem + "ろ", 80)   # imperative
    for stem, fin in _GODAN:
        a, i, e, o, onbin = _GODAN_ROWS[fin]
        add(stem + fin)        # dictionary 書く
        add(stem + i)          # 連用 書き (+ます)
        add(stem + a, 30)      # 未然 書か (+ない/れる)
        add(stem + e, 50)      # 仮定/命令 書け
        add(stem + o, 80)      # 意向 書こ (+う)
        add(stem + onbin[:-1], 20)  # 音便 stem 書い/読ん (+た/だ handled as AUX た/で)
        add(stem + onbin, 40)  # fused 書いた/読んだ as single verb token fallback
    # irregulars
    for f in ("する", "し", "さ", "すれ", "しろ", "せよ"):
        add(f)
    add("来る")
    add("来", 60)
    add("くる", 60)
    # kana 来る stems collide with everyday words (き=木/気, こ=子, これ the
    # pronoun) — priced well above them so they only win next to auxiliaries
    # when nothing else parses
    add("き", 300)
    add("こ", 400)
    return out


def _adj_forms() -> List[Tuple[str, str, int]]:
    out = []
    for stem in _I_ADJ_STEMS:
        out.append((stem + "い", ADJ, _COSTS[ADJ]))
        out.append((stem + "く", ADJ, _COSTS[ADJ] + 30))
        out.append((stem + "かっ", ADJ, _COSTS[ADJ] + 30))  # +た
        out.append((stem + "けれ", ADJ, _COSTS[ADJ] + 60))  # +ば
        out.append((stem + "さ", N, _COSTS[N] + 80))        # nominalization
    out.append(("いい", ADJ, _COSTS[ADJ]))
    out.append(("よく", ADJ, _COSTS[ADJ] + 30))
    return out


def build_lexicon() -> Dict[str, List[Tuple[str, int]]]:
    """surface -> [(pos, cost), ...] (a surface may be ambiguous, e.g. で as
    particle and auxiliary; の as particle and nominalizer)."""
    lex: Dict[str, List[Tuple[str, int]]] = {}

    def add(surface, pos, cost):
        lex.setdefault(surface, [])
        if all(p != pos for p, _ in lex[surface]):
            lex[surface].append((pos, cost))

    for w in _PARTICLES:
        add(w, P, _COSTS[P] + (len(w) - 1) * 20)
    for w in _AUXILIARIES:
        add(w, AUX, _COSTS[AUX] + (len(w) - 1) * 20)
    for w in _NOUNS:
        add(w, N, _COSTS[N])
    for w in _KATAKANA_NOUNS:
        # below the katakana unknown-run price (lattice._UNK_COST) so the
        # lexical analysis wins, but near it so unseen loanwords still parse
        add(w, N, _COSTS[N] + 100)
    from .lexicon_ja_ext import ADVERBS_EXT as _ADVERBS_EXT
    for w in _ADVERBS + _ADVERBS_EXT:
        add(w, ADV, _COSTS[ADV])
    for w, cost in _CHEAP_ADVERBS:
        add(w, ADV, cost)
    for w in _CONJUNCTIONS:
        add(w, CONJ, _COSTS[CONJ])
    for w in _PRENOMINALS:
        add(w, PRE, _COSTS[PRE])
    for w in _PREFIXES:
        # 接頭詞: priced between particles and nouns so お+噌 never beats a
        # lexicalized whole word (ご飯 stays ご飯) but お風呂 -> お/風呂
        add(w, "接頭詞", 320)
    for w in _MISC_VERBS:
        add(w, V, _COSTS[V])
    from .lexicon_ja_ext import INTERJECTIONS_EXT as _INTERJ_EXT
    for w in _INTERJECTIONS + _INTERJ_EXT:
        add(w, "感動詞", 300)
    for surface, pos, cost in _verb_forms():
        add(surface, pos, cost)
    for surface, pos, cost in _adj_forms():
        add(surface, pos, cost)

    # ---- round-5 vocabulary scale-up (lexicon_ja_ext.py): pure vocabulary
    # priced with the same scheme; the conjugation generators above already
    # consumed the ext verb/adjective stems (see the list extensions below
    # their definitions)
    from . import lexicon_ja_ext as ext  # noqa: the module-level import
    # above only pulls the stem lists; the vocabulary lists are read here

    for w in (ext.NOUNS_TIME + ext.NOUNS_PEOPLE + ext.NOUNS_BODY_HEALTH +
              ext.NOUNS_FOOD + ext.NOUNS_NATURE + ext.NOUNS_CITY_TRANSPORT +
              ext.NOUNS_ABSTRACT + ext.NOUNS_SOCIETY + ext.NOUNS_OBJECTS +
              ext.NOUNS_TECH + ext.NOUNS_SCHOOL_WORK +
              ext.NOUNS_EMOTION_COMM + ext.NOUNS_ARTS_SPORTS +
              ext.NOUNS_MISC_DAILY + ext.NOUNS_BUSINESS_LAW +
              ext.NOUNS_MEDIA_RELIGION_MIL + ext.NOUNS_AGRI_CRAFT +
              ext.NOUNS_WAVE2 + ext.NOUNS_WAVE4 + ext.NOUNS_WAVE5 +
              ext.NOUNS_WAVE6 + ext.NOUNS_WAVE7 + ext.NOUNS_WAVE8 +
              ext.NOUNS_WAVE9 + ext.NOUNS_WAVE10 + ext.NOUNS_WAVE13 +
              ext.NOUNS_WAVE14 + ext.NOUNS_WAVE15 + ext.NOUNS_WAVE16 +
              ext.NOUNS_WAVE17 + ext.NOUNS_WAVE18 + ext.NOUNS_WAVE19 +
              ext.NOUNS_WAVE20 + ext.NOUNS_WAVE21 + ext.YOJI_IDIOMS +
              ext.NOUNS_WAVE23 + ext.NOUNS_WAVE24 + ext.NOUNS_WAVE25 +
              ext.NOUNS_WAVE26 + ext.NOUNS_WAVE27 + ext.NOUNS_WAVE28 +
              ext.NOUNS_WAVE29 + ext.NOUNS_WAVE31 + ext.NOUNS_WAVE32 +
              ext.NOUNS_WAVE33 + ext.NOUNS_WAVE34 + ext.NOUNS_WAVE35 +
              ext.NOUNS_WAVE36 + ext.NOUNS_WAVE37 + ext.NOUNS_WAVE38):
        # +30 over the core (most-frequent) noun tier
        add(w, N, _COSTS[N] + 30)
    for w in ext.SURU_NOUNS + ext.SURU_NOUNS2 + ext.SURU_NOUNS3:
        add(w, N, _COSTS[N] + 10)
    for w in ext.NA_ADJ_STEMS + ext.NA_ADJ_STEMS2:
        add(w, N, _COSTS[N] + 30)
    for w in ext.KATAKANA_EXT + ext.KATAKANA_EXT2 + ext.KATAKANA_EXT3:
        add(w, N, _COSTS[N] + 100)  # same tier as the core katakana list
    for w in (ext.SURNAMES + ext.SURNAMES2 + ext.GIVEN_NAMES +
              ext.PLACES_JAPAN + ext.PLACES_JAPAN2 + ext.PLACES_WORLD):
        add(w, N, _COSTS[N] + 60)  # proper nouns: rarer a priori
    for w in ext.NUMBER_WORDS:
        add(w, N, _COSTS[N] + 20)
    for w in ("さん", "さま", "様", "くん", "君", "ちゃん", "氏", "殿",
              "たち", "達"):
        # 名詞-接尾 honorific/plural: must beat the verb-stem+auxiliary
        # analysis of さ+ん after a name (V+AUX connection is -250, so with
        # the +150 N,N connection these need to be VERY cheap — IPADic
        # likewise prices 接尾 far below content words). Overwrite any
        # dearer homograph from the core noun list
        lex[w] = [(p, min(c, 60) if p == N else c)
                  for p, c in lex.get(w, [])]
        if all(p != N for p, _ in lex[w]):
            lex[w].append((N, 60))
    for w in ext.KANJI_SUFFIXES:
        # Pricing (blind3/blind4 post-record fixes, PERF.md round 5; the
        # kanji unknown model is (1100, 500) -> runs price 1600/2100/2600):
        # a suffix must lose to the 2-kanji unknown price when its host is
        # ALSO unknown — at 540 the tier shredded unseen compounds (減税 ->
        # 減/税; first-pass blind3 F1 0.932). At 1400: lexicalized-host
        # splits win (研究(400)+者(1400)+conn(150) = 1950 << the 3-kanji
        # unknown 2600), numeral+counter splits stay under the 2-kanji
        # unknown (二(400)+階: 1950 < 2100), while 1-kanji-UNK+suffix
        # (1600+1400 = 3000) exceeds it, so fresh compounds stay whole
        add(w, N, 1400)
    for w in ext.KANJI_PREFIXES:
        # same bound from the prefix side: 超(1400)+伝導(2100) exceeds the
        # 3-kanji unknown 2600 (超伝導 stays whole) and prefix+suffix
        # pairs (新+型: 1400+1400-200 = 2600) clear the 2-kanji 2100
        add(w, "接頭詞", 1400)
    return lex
