"""Built-in Japanese lexicon for the lattice tokenizer (nlp/lattice.py).

A compact IPADic-style morpheme inventory — function words enumerated, verb
and adjective inflections GENERATED from stems by conjugation class — so the
in-image `tokenize_ja` default is a real morphological analyzer rather than
a character-class splitter (parity target: KuromojiUDF NORMAL mode,
ref: nlp/src/main/java/hivemall/nlp/tokenizer/KuromojiUDF.java:55-86, whose
Lucene JapaneseTokenizer consults the bundled IPADic the same way).

Granularity matches IPADic: inflected predicates split stem + auxiliaries
(食べました -> 食べ/まし/た), particles are single morphemes, compounds stay
whole when lexicalized. Costs are hand-scaled integers: lower = preferred;
the unknown-word models in lattice.py are priced above lexicon entries so
known analyses win.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

# POS tags (IPADic top-level)
N = "名詞"          # noun
P = "助詞"          # particle
AUX = "助動詞"      # auxiliary verb
V = "動詞"          # verb
ADJ = "形容詞"      # i-adjective
ADV = "副詞"        # adverb
CONJ = "接続詞"     # conjunction
PRE = "連体詞"      # prenominal
PRON = "名詞"       # pronouns filed as nouns, like IPADic 名詞-代名詞
SYM = "記号"        # symbol

_PARTICLES = [
    # 格助詞 / 係助詞 / 接続助詞 / 終助詞 / 副助詞
    "が", "を", "に", "で", "と", "へ", "から", "まで", "より", "の",
    "は", "も", "こそ", "さえ", "しか", "だけ", "ほど", "くらい", "ぐらい",
    "など", "なら", "ば", "ながら", "つつ", "ので", "のに", "けど", "けれど",
    "けれども", "か", "ね", "よ", "な", "わ", "ぞ", "や", "とか", "って",
]

_AUXILIARIES = [
    # copulas + inflecting auxiliaries, IPADic-style split units: です
    # conjugates でし+た / でしょ+う, だ conjugates だっ+た / だろ+う,
    # ます conjugates まし+た / ましょ+う (the fused surfaces でした etc.
    # are NOT entries, exactly like IPADic)
    "です", "でし", "でしょ", "だ", "だっ", "だろ", "である",
    "ます", "まし", "ませ", "ましょ", "た", "て", "で",
    "ない", "なかっ", "なく", "ぬ", "ん", "う", "よう", "たら", "だら",
    "れる", "られる", "れ", "られ", "せる", "させる", "せ", "させ",
    "たい", "たかっ", "そう", "らしい", "みたい", "べき", "ちゃ", "じゃ",
]

_NOUNS = [
    # pronouns / demonstratives
    "私", "僕", "俺", "彼", "彼女", "誰", "何", "これ", "それ", "あれ",
    "どれ", "ここ", "そこ", "あそこ", "どこ", "こちら", "そちら",
    # time
    "今日", "明日", "昨日", "今", "今年", "去年", "来年", "毎日", "朝",
    "昼", "夜", "時間", "時", "年", "月", "日", "週", "分", "秒", "午前",
    "午後",
    # common concrete/abstract
    "人", "人間", "子供", "男", "女", "友達", "家族", "先生", "学生",
    "日本", "日本語", "英語", "東京", "京都", "世界", "国", "町", "村",
    "学校", "大学", "会社", "仕事", "電話", "映画", "音楽", "写真",
    "本", "新聞", "手紙", "名前", "言葉", "話", "意味", "問題", "質問",
    "答え", "勉強", "研究", "旅行", "買い物", "料理", "食事", "朝食",
    "昼食", "夕食", "水", "お茶", "御飯", "ご飯", "肉", "魚", "野菜",
    "寿司", "犬", "猫", "鳥", "花", "木", "山", "川", "海", "空", "雨",
    "雪", "風", "天気", "車", "電車", "自転車", "飛行機", "駅", "道",
    "家", "部屋", "店", "お金", "金", "手", "足", "目", "耳", "口",
    "頭", "体", "心", "気", "声", "色", "形", "数", "前", "後", "上",
    "下", "中", "外", "間", "こと", "もの", "ところ", "とき", "ため",
    "ほう", "方", "的", "さん", "君", "様", "機械", "学習", "計算",
    "情報", "技術", "言語", "処理", "自然", "国際", "空港", "科学",
    "関西", "関東", "経済", "政治", "社会", "文化", "歴史", "教育",
    "環境", "開発", "分析", "予測", "回帰", "分類", "学会", "論文",
    # round-4 growth toward the gold-set gate (everyday vocabulary)
    "椅子", "興味", "窓", "予定", "来週", "来月", "毎朝", "紅茶",
    "どちら", "妹", "弟", "兄", "姉", "母", "父", "医者", "荷物",
    "夏休み", "春", "夏", "秋", "冬", "気持ち", "銀行", "番号", "地図",
    "病院", "薬", "約束", "漢字", "宿題", "歌", "みんな", "景色",
    "台所", "公園", "散歩", "会議", "資料", "電気", "風呂", "男の子",
    "女の子", "場所", "道具", "人口", "結果", "準備", "原因", "注目",
    "確認", "発表", "精度", "基本", "本当", "掃除", "図書館", "たち",
    # post-held-out growth (everyday nouns/compounds; the held-out
    # fixture's blind first-pass number was recorded BEFORE this batch)
    "駅前", "今朝", "今夜", "夜空", "歌手", "誕生日", "週末", "牛乳",
    "靴", "庭", "星", "隣", "自分", "意見", "橋", "昔", "山頂", "空気",
    "通り", "角", "信号", "交差点", "地下鉄", "切符", "財布", "鍵",
    "眼鏡", "帽子", "服", "洗濯", "冷蔵庫", "電子", "機器", "画面",
    "携帯", "番組", "広告", "記事", "作品", "小説", "詩", "絵", "曲",
    "声優", "俳優", "選手", "監督", "観客", "客", "店員", "社員",
    "社長", "部長", "課長", "同僚", "上司", "隣人", "親", "祖父",
    "祖母", "孫", "夫", "妻", "息子", "娘", "赤ちゃん", "大人",
    "老人", "若者", "皆", "全員", "相手", "他人", "知り合い",
    # 形容動詞語幹 (na-adjective stems), IPADic files them 名詞
    "好き", "嫌い", "きれい", "静か", "有名", "大切", "便利", "元気",
    "大変", "簡単", "上手", "下手", "得意", "親切", "特別", "必要",
    # numerals + common counters (IPADic 名詞,数 / 名詞,接尾,助数詞)
    "一", "二", "三", "四", "五", "六", "七", "八", "九", "十",
    "百", "千", "万", "円", "度", "回", "個", "冊", "枚", "匹",
    "一つ", "二つ", "三つ", "四つ", "五つ",
]

_PREFIXES = ["お", "ご"]  # 接頭詞 (お風呂, ご飯 is lexicalized whole)

_MISC_VERBS = [  # polite/formulaic chunks, IPADic-style single units
    "ください", "下さい", "いただき", "いただく", "くれ", "くれる",
    "もらい", "もらう", "あげる", "あり", "ある", "あっ", "なり", "なる",
    "なっ", "思い", "思っ", "言い", "言っ", "行っ", "来まし",
]

_INTERJECTIONS = ["ありがとう", "こんにちは", "こんばんは", "おはよう",
                  "すみません", "さようなら", "はい", "いいえ"]

_KATAKANA_NOUNS = [
    # common loanwords, lexicalized like IPADic so EXTENDED mode's
    # unknown-word unigramming (tokenizer.py) only hits genuinely OOV runs
    "ペン", "テレビ", "ラジオ", "カメラ", "パソコン", "コンピュータ",
    "コンピューター", "スマホ", "インターネット", "メール", "ニュース",
    "データ", "テキスト", "ファイル", "システム", "プログラム", "モデル",
    "テスト", "クラス", "サービス", "ネットワーク", "ソフトウェア",
    "ハードウェア", "ユーザー", "ユーザ", "サーバー", "サーバ", "クラウド",
    "ホテル", "レストラン", "カフェ", "コーヒー", "ビール", "ワイン",
    "ジュース", "パン", "ケーキ", "アイス", "サラダ", "スープ", "バス",
    "タクシー", "バイク", "ドア", "テーブル", "イス", "ベッド", "トイレ",
    "シャワー", "エアコン", "ゲーム", "スポーツ", "サッカー", "テニス",
    "ゴルフ", "ピアノ", "ギター", "コンサート", "パーティー", "プレゼント",
    "アルバイト", "ビジネス", "プロジェクト", "チーム", "グループ",
    "リスト", "ページ", "カード", "チケット", "シャツ", "ズボン", "クツ",
    "カバン", "メートル", "キロ", "グラム", "パーセント", "エネルギー",
    "アメリカ", "ヨーロッパ", "アジア", "フランス", "ドイツ", "イギリス",
    "イタリア", "スペイン", "ロシア", "インド", "カナダ",
]

_ADVERBS = [
    "とても", "すごく", "少し", "ちょっと", "たくさん", "もっと", "また",
    "まだ", "すぐ", "いつも", "時々", "よく", "あまり", "全然",
    "きっと", "たぶん", "やはり", "やっぱり", "一緒に", "ゆっくり",
    "はっきり", "しっかり", "そろそろ", "だんだん", "どんどん",
    "なかなか", "ほとんど", "必ず", "絶対", "突然", "急に",
]

# もう gets a below-particle price: the decomposition も(助詞)+う(助動詞)
# costs 250 on the lattice and is never the right analysis
_CHEAP_ADVERBS = [("もう", 140)]

_CONJUNCTIONS = ["そして", "しかし", "でも", "だから", "それで", "また",
                 "それから", "つまり", "例えば"]

_PRENOMINALS = ["この", "その", "あの", "どの", "大きな", "小さな", "同じ"]

# (stem, class) — ichidan drops る; godan conjugates by final kana row;
# suru/kuru irregular listed explicitly below
_ICHIDAN = ["食べ", "見", "出", "寝", "起き", "着", "開け", "閉め", "教え",
            "覚え", "忘れ", "考え", "伝え", "感じ", "信じ", "調べ", "続け",
            "始め", "止め", "決め", "入れ", "届け", "受け", "助け", "逃げ",
            "投げ", "見せ", "乗せ", "任せ", "い", "でき", "生き", "着け",
            "借り", "持て", "出かけ", "遅れ", "疲れ", "見つけ", "増え",
            "まとめ", "覚め", "集め", "比べ", "見え", "聞こえ", "あげ",
            "くれ", "答え", "辞め", "別れ", "慣れ", "触れ", "晴れ"]

_GODAN = [  # (stem-without-final, final dictionary kana)
    ("書", "く"), ("行", "く"), ("聞", "く"), ("歩", "く"), ("働", "く"),
    ("泳", "ぐ"), ("急", "ぐ"), ("話", "す"), ("出", "す"), ("返", "す"),
    ("待", "つ"), ("持", "つ"), ("立", "つ"), ("勝", "つ"), ("死", "ぬ"),
    ("遊", "ぶ"), ("呼", "ぶ"), ("飛", "ぶ"), ("読", "む"), ("飲", "む"),
    ("住", "む"), ("休", "む"), ("頼", "む"), ("作", "る"), ("乗", "る"),
    ("取", "る"), ("帰", "る"), ("走", "る"), ("入", "る"), ("分か", "る"),
    ("終わ", "る"), ("始ま", "る"), ("売", "る"), ("降", "る"), ("曲が", "る"),
    ("買", "う"), ("会", "う"), ("使", "う"), ("思", "う"), ("言", "う"),
    ("習", "う"), ("歌", "う"), ("洗", "う"), ("笑", "う"), ("手伝", "う"),
    ("撮", "る"), ("咲", "く"), ("しま", "う"), ("通", "う"), ("送", "る"),
    ("閉ま", "る"), ("もら", "う"), ("置", "く"), ("消", "す"),
    ("向か", "う"), ("上が", "る"), ("下が", "る"), ("開", "く"),
    ("渡", "す"), ("届", "く"), ("探", "す"), ("学", "ぶ"), ("運", "ぶ"),
    ("光", "る"), ("間に合", "う"), ("思い出", "す"), ("動", "く"),
    ("並", "ぶ"), ("選", "ぶ"), ("残", "る"), ("直", "す"), ("写", "す"),
    ("移", "る"), ("戻", "る"), ("登", "る"), ("踊", "る"), ("怒", "る"),
    ("守", "る"), ("触", "る"), ("切", "る"), ("知", "る"), ("頑張", "る"),
]

_I_ADJ_STEMS = ["大き", "小さ", "新し", "古", "高", "安", "良", "悪", "早",
                "遅", "暑", "寒", "熱", "冷た", "美し", "おいし", "うま",
                "難し", "易し", "面白", "楽し", "嬉し", "悲し", "忙し",
                "近", "遠", "長", "短", "強", "弱", "多", "少な", "白",
                "黒", "赤", "青", "明る", "暗", "若", "重", "軽", "涼し",
                "素晴らし", "広", "狭", "深", "浅", "速", "甘", "辛",
                "固", "柔らか", "優し", "厳し", "危な", "正し", "細か"]

# godan conjugation rows: final kana -> (a, i, e, o, onbin-ta-form)
_GODAN_ROWS = {
    "く": ("か", "き", "け", "こ", "いた"),
    "ぐ": ("が", "ぎ", "げ", "ご", "いだ"),
    "す": ("さ", "し", "せ", "そ", "した"),
    "つ": ("た", "ち", "て", "と", "った"),
    "ぬ": ("な", "に", "ね", "の", "んだ"),
    "ぶ": ("ば", "び", "べ", "ぼ", "んだ"),
    "む": ("ま", "み", "め", "も", "んだ"),
    "る": ("ら", "り", "れ", "ろ", "った"),
    "う": ("わ", "い", "え", "お", "った"),
}

_COSTS = {P: 100, AUX: 150, CONJ: 300, V: 350, N: 400, ADJ: 400, ADV: 450,
          PRE: 350}


def _verb_forms() -> List[Tuple[str, str, int]]:
    out = []
    seen = set()

    def add(surface, cost_bump=0):
        if surface and surface not in seen:
            seen.add(surface)
            out.append((surface, V, _COSTS[V] + cost_bump))

    for stem in _ICHIDAN:
        add(stem + "る")   # dictionary
        add(stem)          # 連用/未然 (combines with ます/た/ない/て)
        add(stem + "れ", 50)   # 仮定
        add(stem + "ろ", 80)   # imperative
    for stem, fin in _GODAN:
        a, i, e, o, onbin = _GODAN_ROWS[fin]
        add(stem + fin)        # dictionary 書く
        add(stem + i)          # 連用 書き (+ます)
        add(stem + a, 30)      # 未然 書か (+ない/れる)
        add(stem + e, 50)      # 仮定/命令 書け
        add(stem + o, 80)      # 意向 書こ (+う)
        add(stem + onbin[:-1], 20)  # 音便 stem 書い/読ん (+た/だ handled as AUX た/で)
        add(stem + onbin, 40)  # fused 書いた/読んだ as single verb token fallback
    # irregulars
    for f in ("する", "し", "さ", "すれ", "しろ", "せよ"):
        add(f)
    add("来る")
    add("来", 60)
    add("くる", 60)
    # kana 来る stems collide with everyday words (き=木/気, こ=子, これ the
    # pronoun) — priced well above them so they only win next to auxiliaries
    # when nothing else parses
    add("き", 300)
    add("こ", 400)
    return out


def _adj_forms() -> List[Tuple[str, str, int]]:
    out = []
    for stem in _I_ADJ_STEMS:
        out.append((stem + "い", ADJ, _COSTS[ADJ]))
        out.append((stem + "く", ADJ, _COSTS[ADJ] + 30))
        out.append((stem + "かっ", ADJ, _COSTS[ADJ] + 30))  # +た
        out.append((stem + "けれ", ADJ, _COSTS[ADJ] + 60))  # +ば
        out.append((stem + "さ", N, _COSTS[N] + 80))        # nominalization
    out.append(("いい", ADJ, _COSTS[ADJ]))
    out.append(("よく", ADJ, _COSTS[ADJ] + 30))
    return out


def build_lexicon() -> Dict[str, List[Tuple[str, int]]]:
    """surface -> [(pos, cost), ...] (a surface may be ambiguous, e.g. で as
    particle and auxiliary; の as particle and nominalizer)."""
    lex: Dict[str, List[Tuple[str, int]]] = {}

    def add(surface, pos, cost):
        lex.setdefault(surface, [])
        if all(p != pos for p, _ in lex[surface]):
            lex[surface].append((pos, cost))

    for w in _PARTICLES:
        add(w, P, _COSTS[P] + (len(w) - 1) * 20)
    for w in _AUXILIARIES:
        add(w, AUX, _COSTS[AUX] + (len(w) - 1) * 20)
    for w in _NOUNS:
        add(w, N, _COSTS[N])
    for w in _KATAKANA_NOUNS:
        # below the katakana unknown-run price (lattice._UNK_COST) so the
        # lexical analysis wins, but near it so unseen loanwords still parse
        add(w, N, _COSTS[N] + 100)
    for w in _ADVERBS:
        add(w, ADV, _COSTS[ADV])
    for w, cost in _CHEAP_ADVERBS:
        add(w, ADV, cost)
    for w in _CONJUNCTIONS:
        add(w, CONJ, _COSTS[CONJ])
    for w in _PRENOMINALS:
        add(w, PRE, _COSTS[PRE])
    for w in _PREFIXES:
        # 接頭詞: priced between particles and nouns so お+噌 never beats a
        # lexicalized whole word (ご飯 stays ご飯) but お風呂 -> お/風呂
        add(w, "接頭詞", 320)
    for w in _MISC_VERBS:
        add(w, V, _COSTS[V])
    for w in _INTERJECTIONS:
        add(w, "感動詞", 300)
    for surface, pos, cost in _verb_forms():
        add(surface, pos, cost)
    for surface, pos, cost in _adj_forms():
        add(surface, pos, cost)
    return lex
