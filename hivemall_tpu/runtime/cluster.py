"""Multi-host cluster bring-up — the MIX-server-fleet replacement.

The reference deploys a Netty parameter-server fleet via ssh fan-out
(ref: bin/mixserv_cluster.sh:44-56, conf/MIXSERV_LIST, mixserv/.../MixServer.java:83-200)
and clients learn the servers from a `-mix host1,host2` option. TPU-native
there is no server process at all: multi-host runs are SPMD jax processes
joined through the JAX coordination service, and "mixing" is the psum inside
the train step (parallel/mix.py). This module is the bin/*.sh analog:

- `init_cluster(coordinator, num_processes, process_id)` — join the cluster
  (jax.distributed.initialize); afterwards jax.devices() is the global pod
  and the SAME MixTrainer program scales across hosts with DCN collectives.
- `cluster_env()` — resolve the same settings from environment variables
  (HIVEMALL_TPU_COORDINATOR / _NUM_PROCS / _PROC_ID), the MIXSERV_LIST analog.
- `parse_mix_option("host1,host2")` — accepts the reference's -mix syntax and
  maps the first host to the coordinator address for API compatibility.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

DEFAULT_PORT = 11212  # kept from MixEnv.java:21 for familiarity


def parse_mix_option(mix: str) -> Tuple[str, int]:
    """-mix "host1[:port][,host2...]" -> (coordinator_host, port)
    (ref: MixClient parses the same list; here the first entry coordinates)."""
    first = mix.split(",")[0].strip()
    if ":" in first:
        host, port = first.rsplit(":", 1)
        return host, int(port)
    return first, DEFAULT_PORT


def cluster_env() -> Optional[Tuple[str, int, int]]:
    coord = os.environ.get("HIVEMALL_TPU_COORDINATOR")
    if not coord:
        return None
    n = int(os.environ.get("HIVEMALL_TPU_NUM_PROCS", "1"))
    pid = int(os.environ.get("HIVEMALL_TPU_PROC_ID", "0"))
    return coord, n, pid


def init_cluster(coordinator: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None) -> bool:
    """Join (or no-op for single-process). Returns True if distributed init
    ran. Safe to call twice."""
    import jax

    if coordinator is None:
        env = cluster_env()
        if env is None:
            return False
        coordinator, num_processes, process_id = env
    if num_processes is None or num_processes <= 1:
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except RuntimeError as e:  # already initialized
        if "already" in str(e).lower():
            return True
        raise


def main() -> None:
    """`python -m hivemall_tpu.runtime.cluster --coordinator host:port
    --num-procs N --proc-id I` — join the cluster and report the global
    device view (the start_mixserv.sh analog)."""
    import argparse

    import jax

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--num-procs", type=int, default=None)
    ap.add_argument("--proc-id", type=int, default=None)
    args = ap.parse_args()
    joined = init_cluster(args.coordinator, args.num_procs, args.proc_id)
    print(f"distributed={'joined' if joined else 'single-process'} "
          f"process={jax.process_index()}/{jax.process_count()} "
          f"devices={len(jax.devices())}")


if __name__ == "__main__":
    main()
