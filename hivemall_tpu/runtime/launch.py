"""Cluster launcher: run a training program inside a joined multi-host
cluster — `python -m hivemall_tpu.runtime.launch [cluster flags] prog.py
[prog args...]`.

The reference deploys its distributed tier as daemon processes fanned out
over ssh (`java -jar hivemall-mixserv-*-fat.jar`, ref: bin/mixserv_daemon.sh
start branch; fleet control ref: bin/mixserv_cluster.sh:44-56). TPU-native
there is no separate server binary to start: the "fleet" is N identical SPMD
jax processes, so the launcher's job is (1) join the JAX coordination
service (runtime/cluster.py::init_cluster — the coordinator replaces
conf/MIXSERV_LIST's server fleet), then (2) hand the process over to the
user's unmodified training program via runpy. The same script scales from
one process to N hosts with zero code changes; collectives ride ICI within
a host and DCN across hosts.

Cluster flags come either from the CLI (--coordinator/--num-procs/--proc-id)
or from HIVEMALL_TPU_COORDINATOR / _NUM_PROCS / _PROC_ID (set per-host by
bin/hivemall_tpu_daemon.sh). A `-mix host1,host2` style list (the
reference's client option, ref: LearnerBaseUDTF.java:98) is accepted via
--mix and maps its first host to the coordinator.
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys

from hivemall_tpu.runtime.cluster import init_cluster, parse_mix_option


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m hivemall_tpu.runtime.launch",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (default: env/single-process)")
    ap.add_argument("--mix", default=None,
                    help="reference-style 'host1[:port],host2' list; first "
                         "entry becomes the coordinator")
    ap.add_argument("--num-procs", type=int, default=None)
    ap.add_argument("--proc-id", type=int, default=None)
    ap.add_argument("--module", "-m", default=None,
                    help="run a module (python -m semantics) instead of a path")
    ap.add_argument("prog", nargs="?", default=None,
                    help="training program path (ignored with --module)")
    ap.add_argument("prog_args", nargs=argparse.REMAINDER,
                    help="arguments passed through to the program")
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    coordinator = args.coordinator
    if coordinator is None and args.mix:
        host, port = parse_mix_option(args.mix)
        coordinator = f"{host}:{port}"

    # JMX-analog scrape endpoint (runtime/metrics_http.py): workers started
    # by bin/hivemall_tpu_daemon.sh opt in via env
    mport = os.environ.get("HIVEMALL_TPU_METRICS_PORT")
    if mport:
        from hivemall_tpu.runtime.metrics_http import serve_metrics

        # loopback unless the operator opts in: the endpoint is
        # unauthenticated, so exposing it beyond the host must be an
        # explicit HIVEMALL_TPU_METRICS_HOST=0.0.0.0 decision (remote
        # scrapers in a fleet set it in conf/cluster_env.sh)
        mhost = os.environ.get("HIVEMALL_TPU_METRICS_HOST", "127.0.0.1")
        srv = serve_metrics(int(mport), host=mhost)
        print(f"[launch] metrics on {mhost}:{srv.server_address[1]}/metrics",
              file=sys.stderr, flush=True)

    joined = init_cluster(coordinator, args.num_procs, args.proc_id)
    import jax

    print(f"[launch] distributed={'joined' if joined else 'single-process'} "
          f"process={jax.process_index()}/{jax.process_count()} "
          f"local_devices={len(jax.local_devices())} "
          f"global_devices={len(jax.devices())}", file=sys.stderr, flush=True)

    if args.module is None and args.prog is None:
        # nothing to run: behave like runtime.cluster's report-only mode
        return 0
    if args.module is not None:
        sys.argv = [args.module] + ([args.prog] if args.prog else []) \
            + args.prog_args
        runpy.run_module(args.module, run_name="__main__", alter_sys=True)
    else:
        sys.argv = [args.prog] + args.prog_args
        sys.path.insert(0, os.path.dirname(os.path.abspath(args.prog)))
        runpy.run_path(args.prog, run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
