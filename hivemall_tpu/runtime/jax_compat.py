"""Version-portable JAX API surface for the distributed paths.

The shard_map API moved twice across the jax versions this repo must run
on:

- jax <= 0.4.x ships ``jax.experimental.shard_map.shard_map`` with a
  ``check_rep=`` replication checker (no vma system, no ``jax.lax.pcast``);
- newer jax promotes it to ``jax.shard_map`` with ``check_vma=`` (the
  varying-manual-axes checker) and adds ``jax.lax.pcast`` to re-tag
  device-invariant values as mesh-varying.

Every trainer imports ``shard_map`` / ``pcast`` from here instead of
touching either spelling directly; graftcheck rule G009 enforces that (and
its autofix performs the rewrite). This module is the only file allowed to
reference the raw APIs — it is excluded from G009 by path.

Legacy note: on the 0.4.x path ``check_vma`` is accepted but the legacy
``check_rep`` checker is kept OFF regardless of its value. The legacy
rewrite rules predate the vma system (scan-carry re-tagging needs pcast,
which does not exist there, so this module's ``pcast`` is the identity) —
running the old checker against code written for vma semantics produces
spurious failures, not safety. The real vma check still runs wherever a
newer jax is installed, and graftcheck's static G007/G010 rules cover the
collective-safety classes on every version.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax

__all__ = ["shard_map", "pcast", "named_mesh", "HAS_NATIVE_SHARD_MAP"]


def named_mesh(axis_sizes: Sequence[int],
               axis_names: Tuple[str, ...] = ("batch", "model"),
               devices: Optional[Sequence] = None):
    """A ``jax.sharding.Mesh`` of shape ``axis_sizes`` over the FIRST
    ``prod(axis_sizes)`` devices, in enumeration order.

    This is the one sanctioned mesh-construction spelling for the serving
    placements (serving/placement.py) and the G008 analyzer resolves its
    axis names (default ``("batch", "model")`` — the serving convention).
    Newer jax ships ``jax.make_mesh``, which may REORDER devices for ICI
    locality; that reordering is a perf nicety training can afford but
    serving cannot take by default — stripe ownership must be a pure
    function of device index so (a) the process-wide sharded-jit cache can
    key on the device list and (b) a re-deploy on the same host places
    every stripe on the same chip it was warmed on. Enumeration order is
    also exactly what parallel/mesh.make_mesh{,_2d} use, so serving and
    training stripes of the same table land on the same devices."""
    import numpy as np

    if devices is None:
        devices = jax.devices()
    need = 1
    for s in axis_sizes:
        need *= int(s)
    if len(devices) < need:
        raise ValueError(
            f"named_mesh{tuple(axis_sizes)}: needs {need} devices, have "
            f"{len(devices)}")
    grid = np.asarray(devices[:need]).reshape(tuple(axis_sizes))
    from jax.sharding import Mesh

    return Mesh(grid, tuple(axis_names))

HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")

if HAS_NATIVE_SHARD_MAP:

    def shard_map(f: Optional[Callable] = None, *, mesh, in_specs, out_specs,
                  check_vma: bool = True, **kwargs):
        """``jax.shard_map`` with a version-stable keyword surface."""
        if f is None:  # decorator-style: shard_map(mesh=..., ...)(fn)
            return lambda g: shard_map(g, mesh=mesh, in_specs=in_specs,
                                       out_specs=out_specs,
                                       check_vma=check_vma, **kwargs)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             **kwargs)

    if hasattr(jax.lax, "pcast"):
        pcast = jax.lax.pcast
    else:  # vma jax without pcast spelling: pvary covers the to="varying" use

        def pcast(x, axis_name, *, to: str = "varying"):
            if to != "varying":
                raise NotImplementedError(
                    f"pcast(to={to!r}) has no equivalent on jax "
                    f"{jax.__version__}")
            return jax.lax.pvary(x, axis_name)

else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    # Modern jax defaults jax_threefry_partitionable=True: random bits are a
    # pure function of (key, flat index), so a padded [D_pad] table's prefix
    # equals the unpadded [D] one. Legacy jax defaults False, where bits
    # depend on the TOTAL array size — padded-sharded init then silently
    # diverges from single-device init past the threefry half-split point
    # and every sharded-vs-reference parity guarantee breaks. Align the
    # semantics with the modern default on the legacy path.
    #
    # This is a process-global flip at import time, so on legacy jax the
    # raw jax.random stream for a given key changes once this module (or
    # anything under hivemall_tpu.parallel / models.trees.grow) is first
    # imported. The deliberate trade-off: hivemall_tpu/__init__.py must
    # stay jax-free (the stdlib-only analyzer imports through it), so the
    # flip cannot be hoisted there; import this module first if external
    # code needs the aligned stream from the start of the process.
    try:
        jax.config.update("jax_threefry_partitionable", True)
    except Exception:  # graftcheck: disable=G029 (flag probe: very old jax lacks it)
        pass

    def shard_map(f: Optional[Callable] = None, *, mesh, in_specs, out_specs,
                  check_vma: bool = True, **kwargs):
        """Legacy ``jax.experimental.shard_map`` adapter.

        ``check_vma`` is accepted for source compatibility; the legacy
        ``check_rep`` checker stays off (see module docstring).
        """
        del check_vma
        if f is None:
            return lambda g: shard_map(g, mesh=mesh, in_specs=in_specs,
                                       out_specs=out_specs, **kwargs)
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=False,
                                 **kwargs)

    def pcast(x, axis_name, *, to: str = "varying"):
        """No vma system on legacy jax: values carry no varying/invariant
        tag, so the re-tag is the identity."""
        del axis_name, to
        return x
