from .cluster import cluster_env, init_cluster  # noqa: F401
from .metrics import Counter, MetricsRegistry, StopWatch, ThroughputCounter  # noqa: F401
from .tracing import TRACER, Tracer, step_span, sync_ready  # noqa: F401
