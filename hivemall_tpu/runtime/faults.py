"""Deterministic fault injection — the chaos harness behind the elastic
tests and scripts/bench_chaos.py.

The terascale paper's reliability claim is about flaky fleets; this repo's
own bench host losing its TPU relay for three straight rounds (BENCH
r03-r05) is the live example. Reliability claims need reproducible
failures: a seeded ``FaultPlan`` names exactly which fault fires at which
step or checkpoint write, and ``inject(plan)`` arms it through
monkeypatchable hooks — the driver's per-step hook plus the two seams
io/checkpoint.py exposes on the write path (``crash_point`` between write
and rename, ``checkpoint_written`` after a successful publish). The same
plan replays bit-for-bit: the corruption byte offset comes from the plan's
seed, never the wall clock.

Fault kinds (the ISSUE-8 robustness matrix):

- ``device_loss``     — step hook raises WorkerLost(n_lost): the SPMD job
                        is dead; the driver must rebuild the mesh over the
                        survivors and resume from the last checkpoint.
- ``transient_step``  — step hook raises TransientStepError once: a
                        recoverable hiccup; same topology, resume.
- ``crash_mid_write`` — the checkpoint writer dies between the payload
                        write and the atomic rename (CrashMidWrite out of
                        io/checkpoint.crash_point); the previous checkpoint
                        must survive intact.
- ``corrupt``         — after the Nth successful write, flip a byte in the
                        middle of the file (digest / zip-CRC mismatch on
                        load -> loud fallback to ``.prev``).
- ``truncate``        — after the Nth successful write, truncate the file
                        to half (unreadable zip -> loud fallback).

Single-threaded by design: one injector arms per driver loop (the
``inject`` context manager refuses to nest), matching run_elastic's
single-driver model — no cross-thread shared state.

# graftcheck: serving-module
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from ..io import checkpoint as io_checkpoint
from .tracing import TRACER

FAULT_KINDS = ("device_loss", "transient_step", "crash_mid_write",
               "corrupt", "truncate")


class InjectedFault(Exception):
    """Base of every injected failure (so drivers can catch the family)."""


class WorkerLost(InjectedFault):
    """A worker/device vanished mid-run — under synchronous SPMD the whole
    job fails; carry how many devices the 'fleet' lost so the driver can
    rebuild the mesh over the survivors."""

    def __init__(self, n_lost: int = 1, step: Optional[int] = None):
        super().__init__(f"worker lost at step {step}: {n_lost} device(s)")
        self.n_lost = int(n_lost)
        self.step = step


class TransientStepError(InjectedFault):
    """A recoverable step failure (spurious collective timeout, preempt
    warning): resume on the SAME topology from the last checkpoint."""


class CrashMidWrite(InjectedFault):
    """The process 'died' on the checkpoint write path — between the
    payload write and the atomic rename."""


@dataclass(frozen=True)
class Fault:
    """One planned fault. ``at_step`` indexes the driver's step loop
    (fires BEFORE that step runs); ``at_write`` counts successful-or-
    attempted checkpoint writes (1-based) for the write-path kinds."""

    kind: str
    at_step: Optional[int] = None
    at_write: Optional[int] = None
    n_lost: int = 1

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {FAULT_KINDS}")
        step_kinds = ("device_loss", "transient_step")
        if self.kind in step_kinds and self.at_step is None:
            raise ValueError(f"{self.kind} needs at_step")
        if self.kind not in step_kinds and self.at_write is None:
            raise ValueError(f"{self.kind} needs at_write")


@dataclass(frozen=True)
class FaultPlan:
    """A seeded, fully-explicit fault schedule. The seed drives the
    corruption byte offsets (and ``generate``'s placement) so the same
    plan replays the same run, byte for byte."""

    seed: int
    faults: Tuple[Fault, ...]

    @classmethod
    def generate(cls, seed: int, n_steps: int, kinds=("device_loss",),
                 n_faults: int = 1, checkpoint_every: int = 8,
                 max_lost: int = 1) -> "FaultPlan":
        """Seeded random placement: step faults land uniformly in
        [1, n_steps); write faults land on write 2+ (the first write has
        no ``.prev`` to fall back to — corrupting it tests nothing but a
        cold start). Deterministic for a given argument tuple."""
        rng = np.random.RandomState(seed)
        out: List[Fault] = []
        n_writes = max(2, n_steps // max(1, checkpoint_every))
        for _ in range(n_faults):
            kind = kinds[int(rng.randint(len(kinds)))]
            if kind in ("device_loss", "transient_step"):
                out.append(Fault(
                    kind, at_step=int(rng.randint(1, max(2, n_steps))),
                    n_lost=int(rng.randint(1, max_lost + 1))))
            else:
                out.append(Fault(kind,
                                 at_write=int(rng.randint(2, n_writes + 1))))
        return cls(seed=seed, faults=tuple(out))


@dataclass
class Injector:
    """Armed instance of a plan: counts steps and checkpoint writes, fires
    each fault exactly once, and keeps a log of what fired (mirrored as
    ``fault.injected`` tracer instants so restarts are attributable in the
    Perfetto timeline next to the driver's ``recovery.restore`` spans)."""

    plan: FaultPlan
    fired: List[dict] = field(default_factory=list)
    _done: set = field(default_factory=set)
    _writes: int = 0

    def _fire(self, i: int, fault: Fault, **extra) -> None:
        self._done.add(i)
        record = {"kind": fault.kind, "at_step": fault.at_step,
                  "at_write": fault.at_write, **extra}
        self.fired.append(record)
        TRACER.instant("fault.injected", args=record)

    def on_step(self, step_idx: int) -> None:
        """Driver seat: call before each training step."""
        for i, f in enumerate(self.plan.faults):
            if i in self._done or f.at_step != step_idx:
                continue
            if f.kind == "device_loss":
                self._fire(i, f, step=step_idx)
                raise WorkerLost(n_lost=f.n_lost, step=step_idx)
            if f.kind == "transient_step":
                self._fire(i, f, step=step_idx)
                raise TransientStepError(
                    f"injected transient failure at step {step_idx}")

    # -- io/checkpoint.py write-path seams -----------------------------------

    def on_crash_point(self, tag: str, path: str) -> None:
        """Patched over io/checkpoint.crash_point: the write counter ticks
        on the first crash point of each save, and a planned
        crash_mid_write for that write index kills the writer there —
        AFTER the payload write, BEFORE the rename."""
        if tag == "elastic.after_write":
            self._writes += 1
        for i, f in enumerate(self.plan.faults):
            if i in self._done or f.kind != "crash_mid_write":
                continue
            if f.at_write == self._writes:
                self._fire(i, f, tag=tag, path=path)
                raise CrashMidWrite(f"injected crash at {tag} "
                                    f"(write {self._writes}) for {path}")

    def on_checkpoint_written(self, path: str) -> None:
        """Patched over io/checkpoint.checkpoint_written: rot the file the
        plan says to rot. The byte offset is seeded from (plan.seed,
        write index) — deterministic, replayable corruption."""
        for i, f in enumerate(self.plan.faults):
            if i in self._done or f.kind not in ("corrupt", "truncate"):
                continue
            if f.at_write != self._writes:
                continue
            size = os.path.getsize(path)
            if f.kind == "truncate":
                self._fire(i, f, path=path, truncated_to=size // 2)
                with open(path, "r+b") as fh:
                    fh.truncate(size // 2)
            else:
                rng = np.random.RandomState(
                    (self.plan.seed * 1_000_003 + self._writes) % (2**31))
                # land inside the compressed payload (skip the zip header)
                off = int(rng.randint(size // 4, max(size // 4 + 1,
                                                     size - 64)))
                self._fire(i, f, path=path, flipped_offset=off)
                with open(path, "r+b") as fh:
                    fh.seek(off)
                    b = fh.read(1)
                    fh.seek(off)
                    fh.write(bytes([b[0] ^ 0xFF]))


_ACTIVE: Optional[Injector] = None


def active() -> Optional[Injector]:
    """The armed injector, if any — the driver's step hook reads it."""
    return _ACTIVE


def step_hook(step_idx: int) -> None:
    """run_elastic's per-step seat: no-op unless a plan is armed."""
    if _ACTIVE is not None:
        _ACTIVE.on_step(step_idx)


@contextlib.contextmanager
def inject(plan: FaultPlan):
    """Arm a plan: installs the injector and patches the io/checkpoint
    write-path hooks for the extent of the block. Yields the Injector so
    callers can assert on ``injector.fired``. Refuses to nest — one
    driver, one plan."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("a fault plan is already armed; inject() does "
                           "not nest")
    injector = Injector(plan)
    saved = (io_checkpoint.crash_point, io_checkpoint.checkpoint_written)
    io_checkpoint.crash_point = injector.on_crash_point
    io_checkpoint.checkpoint_written = injector.on_checkpoint_written
    _ACTIVE = injector
    try:
        yield injector
    finally:
        _ACTIVE = None
        io_checkpoint.crash_point, io_checkpoint.checkpoint_written = saved
