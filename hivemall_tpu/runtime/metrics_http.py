"""HTTP metrics endpoint — the JMX MBean surface, reachable the modern way.

The reference exposes its MIX server metrics over JMX
(ref: mixserv/.../metrics/MetricsRegistry.java registers
MixServerMetricsMBean per port; ThroughputCounter feeds it msgs/sec every
5s, MixServer.java:144-149). A JVM-less runtime exposes the same registry
as an HTTP scrape endpoint instead:

- `GET /metrics`  — Prometheus text exposition of the process-wide
  `runtime.metrics.REGISTRY` snapshot (counters, gauges, meters);
  `?exemplars=1` appends OpenMetrics-style exemplars to histogram bucket
  lines (`# {trace_id="..."} value ts`) linking buckets to traces;
- `GET /healthz`  — liveness (200 + json with process/device info);
- `GET /trace?n=` — the last n committed traces from the process tracer
  (runtime/tracing.py) as Chrome trace_event JSON: save the body to a
  file and load it in ui.perfetto.dev (docs/observability.md);
- `GET /slo`      — every registered objective's multi-window burn rates,
  ok/warn/page state and recent transitions (runtime/slo.py);
- `GET /debug/bundle?n=` — the flight-recorder snapshot: versions,
  device set, deployed models, metrics + time-series history, SLO state,
  last-n traces (slow reserve included) and recompile attributions in one
  strictly-JSON document (runtime/debug_bundle.py). When the server
  carries a serving registry (serving/server.py rides this handler), the
  bundle includes every model's describe().

`serve_metrics(port)` starts a daemon thread (stdlib only); every worker
started by bin/hivemall_tpu_daemon.sh can enable it with
HIVEMALL_TPU_METRICS_PORT.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .metrics import REGISTRY
from .tracing import TRACER

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(key: str) -> str:
    """Metric keys like "train.rows_processed" -> prometheus-legal names."""
    return _NAME_OK.sub("_", key.replace(".", "_"))


def _fmt_le(ub: float) -> str:
    if ub == float("inf"):
        return "+Inf"
    return repr(ub)


def render_prometheus(snapshot: Optional[dict] = None,
                      exemplars: bool = False) -> str:
    """Prometheus text exposition with `# HELP` / `# TYPE` metadata.

    With no argument, renders the process registry with true metric kinds
    (counter / gauge / histogram; meters surface as gauges). Passing a plain
    `{key: value}` snapshot renders every sample as an untyped gauge — the
    legacy scrape shape, kept for callers that post-process dicts.

    ``exemplars=True`` appends OpenMetrics-style exemplars to histogram
    bucket lines (``... # {trace_id="..."} value ts``) for buckets that
    carry one — the link from a bad latency bucket to its sampled trace.
    Off by default: the 0.0.4 text format predates exemplars and strict
    scrapers may reject the suffix (OpenMetrics scrapers accept it).
    """
    lines = []

    def head(name: str, kind: str, help_text: str) -> None:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")

    if snapshot is not None:
        for key in sorted(snapshot):
            name = f"hivemall_tpu_{_prom_name(key)}"
            head(name, "gauge", f"snapshot value {key}")
            lines.append(f"{name} {float(snapshot[key])}")
        return "\n".join(lines) + ("\n" if lines else "")

    snap = REGISTRY.typed_snapshot()
    for key in sorted(snap["counters"]):
        name = f"hivemall_tpu_{_prom_name(key)}"
        head(name, "counter", f"monotonic counter {key}")
        lines.append(f"{name} {snap['counters'][key]}")
    for key in sorted(snap["gauges"]):
        name = f"hivemall_tpu_{_prom_name(key)}"
        head(name, "gauge", f"gauge {key}")
        lines.append(f"{name} {float(snap['gauges'][key])}")
    for key in sorted(snap["meters"]):
        name = f"hivemall_tpu_{_prom_name(key)}"
        head(name, "gauge", f"sliding-window throughput {key}")
        lines.append(f"{name} {float(snap['meters'][key])}")
    for key in sorted(snap["histograms"]):
        h = snap["histograms"][key]
        name = f"hivemall_tpu_{_prom_name(key)}"
        head(name, "histogram", f"fixed-bucket histogram {key}")
        ex = h.get("exemplars", {}) if exemplars else {}
        for ub, cum in h["buckets"]:
            line = f'{name}_bucket{{le="{_fmt_le(ub)}"}} {cum}'
            e = ex.get(ub)
            if e is not None:
                line += (f' # {{trace_id="{e["trace_id"]}"}} '
                         f'{e["value"]} {e["unix"]}')
            lines.append(line)
        lines.append(f"{name}_sum {float(h['sum'])}")
        lines.append(f"{name}_count {h['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - http.server API
        if self.path.split("?")[0] == "/metrics":
            qs = parse_qs(urlparse(self.path).query)
            with_ex = qs.get("exemplars", ["0"])[0] not in ("0", "")
            body = render_prometheus(exemplars=with_ex).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
        elif self.path.split("?")[0] == "/trace":
            qs = parse_qs(urlparse(self.path).query)
            try:
                n = int(qs.get("n", ["20"])[0])
            except ValueError:
                n = 20
            body = json.dumps(TRACER.chrome_trace(n=n)).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path.split("?")[0] == "/slo":
            # late import: slo pulls timeseries; scrape-only processes
            # that never registered an objective still stay light
            from .slo import ENGINE

            body = json.dumps(ENGINE.status()).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path.split("?")[0] == "/debug/bundle":
            from .debug_bundle import build_bundle

            qs = parse_qs(urlparse(self.path).query)
            try:
                n = int(qs.get("n", ["50"])[0])
            except ValueError:
                n = 50
            # serving servers carry a registry attribute (serve() in
            # serving/server.py); the bare metrics endpoint does not —
            # the bundle simply omits the models section there
            body = json.dumps(build_bundle(
                registry=getattr(self.server, "registry", None),
                n_traces=n)).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path.split("?")[0] == "/healthz":
            info = {"status": "ok"}
            try:
                import jax

                info["process_index"] = jax.process_index()
                info["process_count"] = jax.process_count()
                info["local_devices"] = len(jax.local_devices())
            except Exception:  # graftcheck: disable=G029 (probe: jax absent means the health doc just omits device fields)
                pass
            body = json.dumps(info).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        else:
            body = b"not found\n"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        pass


def serve_metrics(port: int = 0, host: str = "127.0.0.1"
                  ) -> ThreadingHTTPServer:
    """Start the scrape endpoint on a daemon thread; returns the server
    (``server.server_address[1]`` is the bound port — pass port=0 for an
    ephemeral one). Call ``server.shutdown()`` to stop."""
    server = ThreadingHTTPServer((host, port), _Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="hivemall-tpu-metrics")
    t.start()
    return server
