"""Flight recorder: one self-contained JSON snapshot of the whole process.

A postmortem today starts with "what did /metrics say before it died" —
answered, if at all, by whatever a human happened to scrape. The bundle
answers it by construction: everything the process knows about itself, in
one strictly-JSON document —

- ``versions`` + ``device_set``: what code ran on what hardware;
- ``models``: the serving registry's full ``describe()`` per model —
  placement, admission/controller state, cache, lineage, retrieval;
- ``metrics``: the registry's typed snapshot (exemplars included — in a
  postmortem the trace links ARE the payload);
- ``timeseries``: the recent history ring (runtime/timeseries.py), so
  trends up to the incident survive it;
- ``slo``: every objective's burn rates, state and transition history;
- ``traces``: the last-N committed traces INCLUDING the slow reserve,
  the top-5 slowest, and the per-stage breakdown (runtime/tracing.py);
- ``recompiles``: the per-guard counters plus the process-wide
  last-compiled-shapes table — retrace attribution at the crash site.

Two consumers: ``GET /debug/bundle`` (runtime/metrics_http.py — one curl
mid-incident) and ``write_crash_bundle`` at the supervisor give-up points
(pipeline/loop.py, runtime/recovery.py — every crash leaves this artifact
next to its checkpoints). The crash writer NEVER raises: masking the
original exception with a telemetry error would be strictly worse than
losing the bundle.

Strict JSON: ``float('inf')`` histogram bounds and NaN gauges are
sanitized to strings/None (``json.dumps`` would happily emit
``Infinity``, which ``JSON.parse`` and strict decoders reject — the
Histogram.quantile docstring's warning, applied at the boundary).
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from typing import Optional

from .metrics import _LAST_COMPILED_SHAPES, REGISTRY
from .tracing import TRACER

BUNDLE_VERSION = 1

# every top-level section a complete bundle carries (tests and the --slo
# bench gate check the document against this list)
SECTIONS = ("bundle_version", "generated_unix", "reason", "versions",
            "device_set", "models", "health", "metrics", "timeseries",
            "slo", "traces", "recompiles")


def _sanitize(obj):
    """Strict-JSON walker: inf/-inf/NaN floats become "+Inf"/"-Inf"/None,
    tuples become lists, dict keys become strings (histogram bucket maps
    key on float bounds), unknown objects fall back to repr."""
    if isinstance(obj, float):
        if math.isinf(obj):
            return "+Inf" if obj > 0 else "-Inf"
        if math.isnan(obj):
            return None
        return obj
    if isinstance(obj, (str, int, bool)) or obj is None:
        return obj
    if isinstance(obj, dict):
        return {_key(k): _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [_sanitize(v) for v in obj]
    item = getattr(obj, "item", None)
    if callable(item):  # numpy scalars without importing numpy here
        try:
            return _sanitize(item())
        except Exception:  # graftcheck: disable=G029 (best-effort serialization: repr below is the documented degrade)
            pass
    return repr(obj)


def _key(k) -> str:
    if isinstance(k, str):
        return k
    if isinstance(k, float) and math.isinf(k):
        return "+Inf" if k > 0 else "-Inf"
    return str(k)


def _versions() -> dict:
    from .. import VERSION

    out = {"hivemall_tpu": VERSION,
           "python": sys.version.split()[0]}
    for mod in ("jax", "numpy"):
        try:
            out[mod] = __import__(mod).__version__
        except Exception:  # graftcheck: disable=G028,G029 (version probe: an absent dep is recorded as absent, not an error)
            out[mod] = None
    return out


def _device_set() -> dict:
    try:
        import jax

        return {"platform": jax.default_backend(),
                "device_count": jax.device_count(),
                "local_device_count": jax.local_device_count(),
                "process_count": jax.process_count(),
                "process_index": jax.process_index(),
                "device_kinds": sorted({d.device_kind
                                        for d in jax.devices()})}
    except Exception:  # graftcheck: disable=G028,G029 (probe: a bundle written before/without jax init records the absence instead of failing the crash path)
        return {"platform": None}


def build_bundle(registry=None, reason: str = "on-demand",
                 n_traces: int = 50,
                 history_s: Optional[float] = None,
                 max_history_samples: int = 240) -> dict:
    """The bundle as a strictly-JSON-safe dict. ``registry`` is a serving
    ``ModelRegistry`` when one exists (the /debug/bundle handler passes
    the server's); None leaves ``models``/``health`` empty — the crash
    writers in training-only processes have no registry to describe."""
    from . import timeseries
    from .slo import ENGINE

    models, health = [], None
    if registry is not None:
        try:
            models = registry.list_models()
            health = registry.health()
        except Exception as e:  # graftcheck: disable=G029 (a mid-shutdown registry must not fail the bundle; the error string IS the section's content)
            health = {"error": repr(e)}
    bundle = {
        "bundle_version": BUNDLE_VERSION,
        "generated_unix": time.time(),
        "reason": reason,
        "versions": _versions(),
        "device_set": _device_set(),
        "models": models,
        "health": health,
        "metrics": REGISTRY.typed_snapshot(),
        "timeseries": timeseries.RING.history(
            seconds=history_s, max_samples=max_history_samples),
        "slo": ENGINE.status(),
        "traces": {
            "last": TRACER.traces(n_traces),
            "slowest": TRACER.slowest(5),
            "stage_breakdown_ms": TRACER.stage_breakdown(),
            "dropped": TRACER.dropped,
        },
        "recompiles": {
            "counters": {k.split("graftcheck.recompiles.", 1)[1]: v
                         for k, v in REGISTRY.snapshot().items()
                         if k.startswith("graftcheck.recompiles.")},
            "last_compiled_shapes": dict(_LAST_COMPILED_SHAPES),
        },
    }
    return _sanitize(bundle)


def write_bundle(path: str, registry=None, reason: str = "on-demand",
                 **kwargs) -> str:
    """Build and write a bundle to ``path`` atomically (tmp + replace —
    a crash mid-write leaves no half-bundle). Raises on IO errors; the
    crash path wants ``write_crash_bundle`` instead."""
    doc = build_bundle(registry=registry, reason=reason, **kwargs)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)
    return path


def write_crash_bundle(path: str, reason: str,
                       registry=None) -> Optional[str]:
    """``write_bundle`` that NEVER raises — the supervisor give-up paths
    (pipeline/loop.py, runtime/recovery.py) call this immediately before
    re-raising the fatal exception, and a telemetry failure must not mask
    it. Returns the path, or None when the write failed (the caller's
    exception is already the loud signal)."""
    try:
        return write_bundle(path, registry=registry, reason=reason)
    except Exception:  # graftcheck: disable=G028,G029 (crash path: the original exception re-raised by the caller is the signal; a bundle-write error must not replace it)
        return None
