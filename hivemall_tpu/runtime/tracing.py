# graftcheck: serving-module
"""End-to-end span tracing: request spans through serving, step timelines
through training, one Perfetto-loadable export for both.

Aggregate counters and histograms (runtime/metrics.py) say THAT a p99
regressed or a mesh step stalled; this module says WHERE the time went —
HTTP parse vs. batcher queue wait vs. bucket pad vs. device dispatch vs.
host sync. Per-stage timing attribution is a first-class subsystem in the
production stacks this repo mirrors (PAPERS.md: the ads-infra paper's
per-stage serving telemetry, the terascale learner's per-phase timing).

Design constraints, in order:

1. **Never block the serving hot path.** Span start/stop is a
   ``perf_counter_ns`` read plus slot writes; the tracer's single lock
   guards only the committed-trace ring buffer append and the sampling
   RNG — no IO, no device sync, no jit dispatch ever runs under it
   (graftcheck G013 enforces this; the module opts into the serving-module
   scope with the marker on line 1).
2. **Spans cross threads by explicit handoff, not ambient magic.** The
   contextvar tracks the current span per thread; the batcher hop
   (serving/batcher.py) carries the request's span on the queue entry and
   the worker parents its spans to it explicitly.
3. **One trace format.** ``export_chrome()`` emits Chrome ``trace_event``
   JSON that loads in ui.perfetto.dev / chrome://tracing for serving
   requests and training steps alike.

Vocabulary:

- a **trace** is one request (or one training step): a root span plus its
  descendants, identified by ``trace_id``;
- a **span** is one timed stage (``name``, ``span_id``, ``parent_id``,
  start/duration, thread, args);
- an **instant event** is a point-in-time marker inside a span — e.g. a
  ``jit_recompile`` emitted by ``runtime.metrics.recompile_guard``, so the
  recompile shows up INSIDE the request that paid for it.

Sampling: the *decision* is made per root span with a seeded RNG
(deterministic for tests); child spans inherit it. Spans are timed
regardless (they are cheap); the decision gates which traces are
*committed* to the ring buffer — plus ``slow_ms``: a root slower than the
threshold commits even when unsampled, so the tail is never invisible.
``enabled=False`` turns span creation into a no-op entirely.

Usage::

    from hivemall_tpu.runtime.tracing import TRACER, step_span

    with TRACER.span("engine.pad", args={"rows": n}):
        staged = servable.stage(chunk, b_pad, width_cap)

    with step_span("sharded_1d", step=i):        # training timeline
        with TRACER.span("train.data_prep"):
            blocks = make_blocks(...)
        state, loss = trainer.step(state, *blocks)   # train.compiled_step
        sync_ready(loss)                             # train.sync

    TRACER.export_chrome("trace.json")   # -> ui.perfetto.dev
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import random
import re
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional, Tuple

_ID_COUNTER = itertools.count(1)  # __next__ is GIL-atomic: no lock needed


def _new_id(prefix: str) -> str:
    return f"{prefix}{next(_ID_COUNTER):x}"


# W3C Trace Context traceparent (https://www.w3.org/TR/trace-context/):
# a version-00 parser reads the first four fields and, for versions ABOVE
# 00, tolerates appended future fields; version 00 itself must have
# exactly four, version 0xff and all-zero trace/span ids are invalid
_TRACEPARENT = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})"
    r"(-[^\s]*)?$")


def _w3c_hex(ident: Optional[str], width: int) -> str:
    """Render an internal id ("t2a"/"s1f") or an adopted 32-hex trace id
    as a W3C fixed-width lowercase hex field (all-zero is invalid per
    spec, so 0 maps to 1)."""
    h = ident or ""
    if h and h[0] in "ts":
        h = h[1:]
    try:
        v = int(h, 16)
    except ValueError:  # graftcheck: disable=G028 (not degraded: non-hex idents hash via bytes, same mapping)
        v = int.from_bytes(h.encode(), "big")
    v %= 16 ** width
    return format(v or 1, f"0{width}x")


class _NullSpan:
    """Returned when the tracer is disabled — every operation is a no-op,
    so call sites never branch on tracer state."""

    __slots__ = ()
    recording = False
    sampled = False
    trace_id: Optional[str] = None
    span_id: Optional[str] = None

    def set(self, **args) -> None:
        pass

    def event(self, name: str, **args) -> None:
        pass


NULL_SPAN = _NullSpan()


class _Trace:
    """Per-trace accumulator: the root's sampling decision plus every
    finished span, committed (or dropped) when the root ends."""

    __slots__ = ("trace_id", "sampled", "spans", "root")

    def __init__(self, trace_id: str, sampled: bool) -> None:
        self.trace_id = trace_id
        self.sampled = sampled
        self.spans: List["Span"] = []  # list.append is GIL-atomic
        self.root: Optional["Span"] = None


class Span:
    """One timed stage of a trace. Created via Tracer.span()/begin();
    mutated by exactly one thread at a time (the thread that opened it)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_ns",
                 "end_ns", "tid", "args", "events", "_trace")

    recording = True

    def __init__(self, name: str, trace: _Trace, parent_id: Optional[str],
                 start_ns: int) -> None:
        self.name = name
        self.trace_id = trace.trace_id
        self.span_id = _new_id("s")
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns: Optional[int] = None
        self.tid = threading.get_ident()
        self.args: Dict = {}
        self.events: List = []  # (name, ts_ns, args)
        self._trace = trace

    @property
    def sampled(self) -> bool:
        return self._trace.sampled

    def set(self, **args) -> None:
        """Attach key/value annotations (shown in the Perfetto args pane)."""
        self.args.update(args)

    def event(self, name: str, **args) -> None:
        """Attach an instant event at now (e.g. a jit recompile marker)."""
        self.events.append((name, time.perf_counter_ns(), args))

    def to_dict(self) -> dict:
        dur = (self.end_ns - self.start_ns) if self.end_ns is not None else 0
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_us": self.start_ns / 1e3,
            "dur_us": dur / 1e3,
            "tid": self.tid,
            "args": dict(self.args),
            "events": [{"name": n, "ts_us": ts / 1e3, "args": dict(a)}
                       for n, ts, a in self.events],
        }


# the thread's (task's) innermost open span; crossed threads only by
# explicit handoff (Tracer.add_span / span(parent=...))
_current: contextvars.ContextVar = contextvars.ContextVar(
    "hivemall_tpu_current_span", default=None)

_UNSET = object()


class Tracer:
    """Thread-safe span tracer with a bounded ring of committed traces.

    The hot path (begin/end) takes the lock only to (a) draw one sampling
    decision per root and (b) append one committed trace per root — both
    O(1) pointer work. Exports copy the ring under the lock and serialize
    outside it.
    """

    def __init__(self, capacity: int = 256, sample_rate: float = 1.0,
                 slow_ms: Optional[float] = None, seed: Optional[int] = None,
                 enabled: bool = True, jax_annotations: bool = False,
                 slow_reserve: float = 0.25) -> None:
        self.capacity = int(capacity)
        self.sample_rate = float(sample_rate)
        self.slow_ms = slow_ms
        self.enabled = bool(enabled)
        self.jax_annotations = bool(jax_annotations)
        self._rng = random.Random(seed)
        # slow-trace retention: with slow_ms set, a fraction of the ring is
        # RESERVED for slow_ms-qualified traces — under sustained overload
        # a flood of fast sampled traces would otherwise FIFO-evict the
        # slow outliers that are the whole point of the slow escape. The
        # two rings share one commit sequence so traces() stays ordered.
        reserved = int(self.capacity * float(slow_reserve)) \
            if slow_ms is not None else 0
        reserved = min(reserved, max(0, self.capacity - 1))
        self.slow_reserved = reserved
        self._ring: deque = deque(maxlen=self.capacity - reserved)
        self._slow_ring: Optional[deque] = \
            deque(maxlen=reserved) if reserved else None
        self._seq = 0  # commit order across both rings (guarded by _lock)
        self._lock = threading.Lock()
        self.dropped = 0  # unsampled-and-fast roots (observability of loss)

    # -- span lifecycle ------------------------------------------------------

    def current(self) -> Optional[Span]:
        """The calling thread's innermost open span (None outside any)."""
        span = _current.get()
        return span if span is not None and span.recording else None

    def exemplar_id(self, span=None) -> Optional[str]:
        """trace_id usable as a histogram exemplar (None when the trace
        cannot land in the ring). Sampled traces always commit; with
        ``slow_ms`` set, an unsampled trace MAY commit via the slow
        escape — exactly the tail an exemplar should link to — so its id
        is returned too (the link can dangle if the root finishes fast;
        a missing link on the slow tail is the worse failure)."""
        if span is None:
            span = self.current()
        if span is None or not span.recording:
            return None
        if span.sampled or self.slow_ms is not None:
            return span.trace_id
        return None

    def _sample(self) -> bool:
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        with self._lock:
            return self._rng.random() < self.sample_rate

    # -- W3C Trace Context (traceparent) -------------------------------------

    @staticmethod
    def parse_traceparent(header: Optional[str]
                          ) -> Optional[Tuple[str, str, bool]]:
        """Parse a W3C ``traceparent`` header into a remote context
        ``(trace_id, parent_span_id, sampled_flag)`` usable as
        ``begin/span(remote=...)``. Returns None on anything malformed —
        version 0xff, wrong field widths, all-zero ids — so the caller
        falls back to a fresh trace (the fail-open contract)."""
        if not header or not isinstance(header, str):
            return None
        m = _TRACEPARENT.match(header.strip().lower())
        if m is None:
            return None
        version, trace_id, span_id, flags, extra = m.groups()
        if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        if extra is not None and version == "00":
            return None  # version 00 has exactly four fields
        return trace_id, span_id, bool(int(flags, 16) & 1)

    def format_traceparent(self, span) -> Optional[str]:
        """The ``traceparent`` to echo back for ``span``: its trace id
        (the adopted client id verbatim for remote-parented roots) and
        ITS span id as the new parent, sampled flag from the trace's
        commit decision. None when the span records nothing."""
        if span is None or not getattr(span, "recording", False):
            return None
        flags = "01" if span.sampled else "00"
        return (f"00-{_w3c_hex(span.trace_id, 32)}-"
                f"{_w3c_hex(span.span_id, 16)}-{flags}")

    def begin(self, name: str, parent=_UNSET,
              start_ns: Optional[int] = None, args: Optional[dict] = None,
              remote: Optional[Tuple[str, str, bool]] = None):
        """Open a span (manual pairing with end(); prefer span()). parent
        defaults to the calling thread's current span; pass an explicit
        Span for cross-thread parenting or None to force a new root.
        ``remote`` (a parse_traceparent result) makes the new root adopt
        the client's trace id and parent the client's span — it applies
        only when no local parent is in effect."""
        if not self.enabled:
            return NULL_SPAN
        if parent is _UNSET:
            parent = self.current()
        if parent is not None and parent.recording:
            trace = parent._trace
            parent_id = parent.span_id
            span = Span(name, trace, parent_id,
                        start_ns if start_ns is not None
                        else time.perf_counter_ns())
        else:
            if remote is not None:
                # adopt the client's trace: their trace id IS ours, their
                # span is our root's parent; their sampled flag is a vote,
                # not a veto — our sampler can still commit the trace
                r_trace, r_span, r_sampled = remote
                trace = _Trace(r_trace, r_sampled or self._sample())
                parent_id = r_span
            else:
                trace = _Trace(_new_id("t"), self._sample())
                parent_id = None
            span = Span(name, trace, parent_id,
                        start_ns if start_ns is not None
                        else time.perf_counter_ns())
            trace.root = span
        if args:
            span.args.update(args)
        return span

    def end(self, span, end_ns: Optional[int] = None) -> None:
        """Close a span; when it is its trace's root, commit (sampled or
        slower than slow_ms) or drop the whole trace."""
        if not span.recording:
            return
        span.end_ns = end_ns if end_ns is not None else time.perf_counter_ns()
        trace = span._trace
        trace.spans.append(span)
        if span is not trace.root:
            return
        dur_ms = (span.end_ns - span.start_ns) / 1e6
        slow = self.slow_ms is not None and dur_ms >= self.slow_ms
        if trace.sampled or slow:
            committed = {
                "trace_id": trace.trace_id,
                "root": span.name,
                "duration_ms": dur_ms,
                "sampled": trace.sampled,
                "spans": [s.to_dict() for s in trace.spans],
            }
            with self._lock:
                committed["seq"] = self._seq
                self._seq += 1
                # slow outliers land in their reserved slots, where a
                # flood of fast sampled traces cannot FIFO-evict them; the
                # reserve is a FLOOR, not a partition — when it is full
                # the oldest slow trace overflows into the general ring
                # and competes there, so an all-slow workload still
                # retains up to the full capacity
                if slow and self._slow_ring is not None:
                    if len(self._slow_ring) == self._slow_ring.maxlen:
                        self._ring.append(self._slow_ring.popleft())
                    self._slow_ring.append(committed)
                else:
                    self._ring.append(committed)
        else:
            with self._lock:  # read-modify-write: racy without the lock
                self.dropped += 1

    @contextlib.contextmanager
    def span(self, name: str, parent=_UNSET,
             args: Optional[dict] = None,
             remote: Optional[Tuple[str, str, bool]] = None
             ) -> Iterator[Span]:
        """Context-managed span, set as the thread's current for its
        extent so nested spans parent automatically. ``remote`` threads a
        parsed client ``traceparent`` through to begin(). With
        ``jax_annotations=True`` the extent is also wrapped in a
        jax.profiler.TraceAnnotation, so the stage shows up in xprof
        device timelines under the same name."""
        span = self.begin(name, parent=parent, args=args, remote=remote)
        if span is NULL_SPAN:
            yield span
            return
        token = _current.set(span)
        try:
            if self.jax_annotations:
                import jax

                with jax.profiler.TraceAnnotation(name):
                    yield span
            else:
                yield span
        finally:
            _current.reset(token)
            self.end(span)

    def add_span(self, name: str, parent, start_ns: int, end_ns: int,
                 args: Optional[dict] = None) -> None:
        """Record an already-elapsed interval as a child span — the
        queue-wait idiom: the batcher worker stamps [enqueued, taken] as a
        span parented to the span the request was submitted under."""
        if not self.enabled or parent is None or not parent.recording:
            return
        span = Span(name, parent._trace, parent.span_id, start_ns)
        if args:
            span.args.update(args)
        span.end_ns = end_ns
        parent._trace.spans.append(span)

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        """Attach an instant event to the calling thread's current span
        (no-op outside any span) — recompile markers, cache misses."""
        span = self.current()
        if span is not None:
            span.event(name, **(args or {}))

    # -- inspection / export -------------------------------------------------

    def traces(self, n: Optional[int] = None) -> List[dict]:
        """The last ``n`` committed traces, oldest first (n=None: all;
        n <= 0: none — NOT all: out[-0:] would be the whole list). The
        general and reserved-slow rings merge back into one commit-order
        stream."""
        with self._lock:
            out = list(self._ring)
            if self._slow_ring is not None and self._slow_ring:
                out = sorted(out + list(self._slow_ring),
                             key=lambda t: t["seq"])
        if n is not None:
            n = int(n)
            out = out[-n:] if n > 0 else []
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            if self._slow_ring is not None:
                self._slow_ring.clear()
            self._seq = 0
            self.dropped = 0

    def slowest(self, k: int = 5, n: Optional[int] = None) -> List[dict]:
        """Top-k slowest committed traces with their per-stage totals —
        the "where did the p99 go" artifact bench_serving.py dumps."""
        ranked = sorted(self.traces(n), key=lambda t: -t["duration_ms"])[:k]
        out = []
        for t in ranked:
            stages: Dict[str, float] = {}
            for s in t["spans"]:
                stages[s["name"]] = stages.get(s["name"], 0.0) \
                    + s["dur_us"] / 1e3
            out.append({"trace_id": t["trace_id"], "root": t["root"],
                        "duration_ms": round(t["duration_ms"], 3),
                        "stages_ms": {k_: round(v, 3)
                                      for k_, v in sorted(stages.items())}})
        return out

    def stage_breakdown(self, n: Optional[int] = None) -> Dict[str, dict]:
        """Aggregate per-stage time across committed traces:
        {stage: {count, total_ms, mean_ms, max_ms}}."""
        agg: Dict[str, List[float]] = {}
        for t in self.traces(n):
            for s in t["spans"]:
                agg.setdefault(s["name"], []).append(s["dur_us"] / 1e3)
        return {
            name: {
                "count": len(ds),
                "total_ms": round(sum(ds), 3),
                "mean_ms": round(sum(ds) / len(ds), 4),
                "max_ms": round(max(ds), 3),
            }
            for name, ds in sorted(agg.items())
        }

    def chrome_trace(self, n: Optional[int] = None) -> dict:
        """Chrome/Perfetto ``trace_event`` JSON (the dict; export_chrome
        writes it). Spans map to complete ("X") events, instant events to
        "i" events, all stamped with trace/span ids in args so Perfetto
        queries can join them back to exemplars."""
        pid = os.getpid()
        events = []
        committed = self.traces(n)  # ONE ring copy: count == events' source
        for t in committed:
            for s in t["spans"]:
                events.append({
                    "name": s["name"],
                    "cat": "hivemall_tpu",
                    "ph": "X",
                    "ts": s["start_us"],
                    "dur": s["dur_us"],
                    "pid": pid,
                    "tid": s["tid"],
                    "args": {**s["args"], "trace_id": s["trace_id"],
                             "span_id": s["span_id"],
                             "parent_id": s["parent_id"]},
                })
                for ev in s["events"]:
                    events.append({
                        "name": ev["name"],
                        "cat": "hivemall_tpu",
                        "ph": "i",
                        "s": "t",
                        "ts": ev["ts_us"],
                        "pid": pid,
                        "tid": s["tid"],
                        "args": {**ev["args"], "trace_id": s["trace_id"]},
                    })
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"source": "hivemall_tpu.runtime.tracing",
                              "traces": len(committed)}}

    def export_chrome(self, path: str, n: Optional[int] = None) -> dict:
        """Write the Chrome trace to ``path`` (load it in ui.perfetto.dev
        or chrome://tracing); returns the exported dict. Serialization
        happens OUTSIDE the tracer lock (chrome_trace copies first)."""
        doc = self.chrome_trace(n)
        with open(path, "w") as f:
            json.dump(doc, f)
        return doc


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# Process-wide tracer, knobs via environment:
#   HIVEMALL_TPU_TRACE=0             disable entirely
#   HIVEMALL_TPU_TRACE_SAMPLE=0.1    sample 10% of roots
#   HIVEMALL_TPU_TRACE_SLOW_MS=50    always commit roots >= 50 ms
#   HIVEMALL_TPU_TRACE_SLOW_RESERVE=0.25  ring fraction reserved for slow
#                                    traces (only meaningful with SLOW_MS)
#   HIVEMALL_TPU_TRACE_CAPACITY=256  ring size (committed traces)
#   HIVEMALL_TPU_TRACE_JAX=1         bridge spans into jax TraceAnnotations
_slow = os.environ.get("HIVEMALL_TPU_TRACE_SLOW_MS")
TRACER = Tracer(
    capacity=int(_env_float("HIVEMALL_TPU_TRACE_CAPACITY", 256)),
    sample_rate=_env_float("HIVEMALL_TPU_TRACE_SAMPLE", 1.0),
    slow_ms=float(_slow) if _slow else None,
    enabled=os.environ.get("HIVEMALL_TPU_TRACE", "1") != "0",
    jax_annotations=os.environ.get("HIVEMALL_TPU_TRACE_JAX", "0") == "1",
    slow_reserve=_env_float("HIVEMALL_TPU_TRACE_SLOW_RESERVE", 0.25),
)


@contextlib.contextmanager
def step_span(trainer: str, step: Optional[int] = None,
              tracer: Optional[Tracer] = None) -> Iterator[Span]:
    """Root span for ONE training step — the per-step timeline the sharded
    and mix trainers feed: open it in the driving loop, and the trainer's
    dispatch lands as a ``train.compiled_step`` child, host block building
    under ``train.data_prep``, ``sync_ready`` as ``train.sync``::

        for i, blk in enumerate(blocks):
            with step_span("sharded_1d", step=i):
                state, loss = trainer.step(state, *blk)
                sync_ready(loss)
    """
    t = tracer if tracer is not None else TRACER
    args = {"trainer": trainer}
    if step is not None:
        args["step"] = int(step)
    with t.span("train.step", args=args) as s:
        yield s


def sync_ready(tree, tracer: Optional[Tracer] = None):
    """jax.block_until_ready under a ``train.sync`` span — makes the
    host-sync cost of a step visible as its own stage; returns ``tree``."""
    t = tracer if tracer is not None else TRACER
    with t.span("train.sync"):
        import jax

        return jax.block_until_ready(tree)
