"""Declarative SLOs evaluated as multi-window burn rates over the ring.

The serving stack's telemetry is point-in-time; objectives are over time.
This module judges the one against the other the way Google's SRE
workbook prescribes (PAPERS.md ads-infra paper: SLO-driven health is
load-bearing for fleet operation):

- An ``SLO`` declares what "good" means: a latency (or pipeline
  freshness) histogram whose observations must stay under ``threshold_s``
  for at least ``objective`` of events, or an availability ratio over
  good/bad counter sets. The error BUDGET is ``1 - objective``.
- The **burn rate** is ``observed_error_fraction / budget`` over a
  window: burn 1.0 spends the budget exactly; burn 2.0 spends it twice
  as fast. Each SLO is evaluated over TWO windows — a fast one (~1m
  default) that reacts, and a slow one (~10m default) that confirms —
  and an alert condition requires BOTH to burn: a brief spike cannot
  page (the fast window recovers), and a long slow bleed cannot hide
  (the slow window accumulates). Windows ride the time-series ring
  (runtime/timeseries.py), so no external scrape stack is involved.
- Each SLO runs an ok -> warn -> page state machine with hysteresis:
  a state transition needs ``raise_after`` (or ``clear_after``)
  CONSECUTIVE evaluations agreeing — a single bad sample cannot flap
  the alert (tests/test_slo.py pins this). Transitions are recorded
  (bounded) and surfaced as gauges::

      slo.<name>.burn_fast   slo.<name>.burn_slow   slo.<name>.state

  (state: 0 ok / 1 warn / 2 page) plus ``GET /slo`` on the metrics/
  serving port (runtime/metrics_http.py) and the SLO block inside
  ``GET /healthz`` (serving/server.py routes on it).

A window with ZERO observations is "no evidence", not "no burn": the
evaluation reports ``None`` burns and counts toward CLEARING only — a
paged SLO whose traffic stopped entirely drains back to ok instead of
paging forever on stale history, and an idle process never pages.

Locking (graftcheck G012-G016 scope): the engine lock guards the SLO
table and per-SLO state; every ring query and gauge write happens
OUTSIDE it. ``evaluate()`` is normally driven by the ring's sample
listener (``attach()``), so alert cadence equals sample cadence; tests
drive it directly with a fake clock.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from . import timeseries
from .metrics import REGISTRY, MetricsRegistry

OK, WARN, PAGE = "ok", "warn", "page"
STATE_LEVELS = {OK: 0, WARN: 1, PAGE: 2}
_LEVEL_NAMES = {v: k for k, v in STATE_LEVELS.items()}

# kinds sharing the histogram-threshold evaluator; "availability" uses
# the counter-ratio evaluator
_HISTOGRAM_KINDS = ("latency", "freshness")
KINDS = _HISTOGRAM_KINDS + ("availability",)


@dataclass(frozen=True)
class SLO:
    """One declarative objective. ``kind``:

    - ``"latency"`` / ``"freshness"``: at least ``objective`` of
      ``histogram``'s observations stay under ``threshold_s`` seconds;
    - ``"availability"``: bad events (sum of ``bad_keys`` counter deltas)
      stay under ``1 - objective`` of all events (good + bad) — e.g.
      good = accepted, bad = shed + expired + quota-rejected.
    """

    name: str
    kind: str = "latency"
    objective: float = 0.99
    histogram: Optional[str] = None
    threshold_s: Optional[float] = None
    good_keys: Tuple[str, ...] = ()
    bad_keys: Tuple[str, ...] = ()
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    # burn thresholds: the condition needs BOTH windows at/above
    warn_burn: float = 1.0
    page_burn: float = 2.0
    # hysteresis: consecutive agreeing evaluations to move up / down
    raise_after: int = 2
    clear_after: int = 2
    # attribution shown on /slo (which model, which pipeline)
    labels: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"SLO {self.name!r}: unknown kind "
                             f"{self.kind!r} (one of {KINDS})")
        if self.kind in _HISTOGRAM_KINDS and (
                not self.histogram or self.threshold_s is None):
            raise ValueError(f"SLO {self.name!r}: kind {self.kind!r} "
                             f"needs histogram= and threshold_s=")
        if self.kind == "availability" and not self.bad_keys:
            raise ValueError(f"SLO {self.name!r}: kind 'availability' "
                             f"needs bad_keys= (and usually good_keys=)")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"SLO {self.name!r}: objective must be in "
                             f"(0, 1), got {self.objective}")

    @property
    def budget(self) -> float:
        return 1.0 - self.objective


class _SLOState:
    """Mutable per-SLO alert state (engine-lock guarded)."""

    def __init__(self) -> None:
        self.state = OK
        self.up_streak = 0
        self.down_streak = 0
        self.peak = OK  # highest state since registration — bench gate
        self.last: Optional[dict] = None
        self.transitions: List[dict] = []
        self.evals = 0


class SLOEngine:
    """Evaluates registered SLOs against a TimeSeriesRing. One per
    process is the normal shape (module singleton ``ENGINE``); tests
    build private engines over private rings."""

    MAX_TRANSITIONS = 64

    def __init__(self, ring: Optional[timeseries.TimeSeriesRing] = None,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.ring = ring if ring is not None else timeseries.RING
        self.registry = registry if registry is not None else REGISTRY
        self.clock = clock
        self._lock = threading.Lock()
        self._slos: Dict[str, Tuple[SLO, _SLOState]] = {}
        self._listener: Optional[Callable] = None
        self._last_eval_t: Optional[float] = None

    # -- registration -------------------------------------------------------

    def register(self, slo: SLO) -> SLO:
        """Add (or replace — state resets) an objective."""
        with self._lock:
            self._slos[slo.name] = (slo, _SLOState())
        return slo

    def remove(self, name: str) -> bool:
        with self._lock:
            return self._slos.pop(name, None) is not None

    def clear(self) -> None:
        with self._lock:
            self._slos = {}

    def attach(self) -> None:
        """Evaluate on every ring sample (idempotent) — the production
        wiring: alert cadence equals sampler cadence."""
        with self._lock:
            if self._listener is not None:
                return
            listener = self._listener = lambda t, snap: self.evaluate(now=t)
        self.ring.add_listener(listener)

    def detach(self) -> None:
        with self._lock:
            listener, self._listener = self._listener, None
        if listener is not None:
            self.ring.remove_listener(listener)

    # -- evaluation ---------------------------------------------------------

    def _burn(self, slo: SLO, window_s: float,
              now: Optional[float]) -> Optional[float]:
        """Burn rate of one window; None = no events in it."""
        if slo.kind in _HISTOGRAM_KINDS:
            frac = self.ring.frac_over(slo.histogram, slo.threshold_s,
                                       window_s, now=now)
            if frac is None:
                return None
            return frac / slo.budget
        good = sum(self.ring.delta(k, window_s, now=now)
                   for k in slo.good_keys)
        bad = sum(self.ring.delta(k, window_s, now=now)
                  for k in slo.bad_keys)
        total = good + bad
        if total <= 0:
            return None
        return (bad / total) / slo.budget

    def evaluate(self, now: Optional[float] = None) -> dict:
        """Evaluate every SLO once: compute both burns, advance the state
        machines, set the gauges. Returns {name: evaluation}. Ring reads
        and gauge writes happen outside the engine lock."""
        t = self.clock() if now is None else now
        with self._lock:
            table = [(slo, st) for slo, st in self._slos.values()]
        results: Dict[str, dict] = {}
        gauge_writes = []
        for slo, st in table:
            fast = self._burn(slo, slo.fast_window_s, now)
            slow = self._burn(slo, slo.slow_window_s, now)

            def _cond(threshold):
                return (fast is not None and slow is not None
                        and fast >= threshold and slow >= threshold)

            target = PAGE if _cond(slo.page_burn) \
                else WARN if _cond(slo.warn_burn) else OK
            with self._lock:
                # the registration may have been swapped mid-evaluation;
                # only advance the state object still in the table
                cur = self._slos.get(slo.name)
                if cur is None or cur[1] is not st:
                    continue
                st.evals += 1
                lvl, cur_lvl = STATE_LEVELS[target], STATE_LEVELS[st.state]
                if lvl > cur_lvl:
                    st.up_streak += 1
                    st.down_streak = 0
                    if st.up_streak >= slo.raise_after:
                        st.transitions.append(
                            {"t": t, "from": st.state, "to": target,
                             "burn_fast": fast, "burn_slow": slow})
                        del st.transitions[:-self.MAX_TRANSITIONS]
                        st.state = target
                        st.up_streak = st.down_streak = 0
                elif lvl < cur_lvl:
                    st.down_streak += 1
                    st.up_streak = 0
                    if st.down_streak >= slo.clear_after:
                        st.transitions.append(
                            {"t": t, "from": st.state, "to": target,
                             "burn_fast": fast, "burn_slow": slow})
                        del st.transitions[:-self.MAX_TRANSITIONS]
                        st.state = target
                        st.up_streak = st.down_streak = 0
                else:
                    st.up_streak = st.down_streak = 0
                if STATE_LEVELS[st.state] > STATE_LEVELS[st.peak]:
                    st.peak = st.state
                st.last = {
                    "t": t, "burn_fast": fast, "burn_slow": slow,
                    "condition": target, "state": st.state,
                }
                results[slo.name] = dict(st.last)
                state_now = st.state
            gauge_writes.append((slo.name, fast, slow, state_now))
        for name, fast, slow, state_now in gauge_writes:
            self.registry.set_gauge(f"slo.{name}.burn_fast",
                                    fast if fast is not None else 0.0)
            self.registry.set_gauge(f"slo.{name}.burn_slow",
                                    slow if slow is not None else 0.0)
            self.registry.set_gauge(f"slo.{name}.state",
                                    float(STATE_LEVELS[state_now]))
        with self._lock:
            self._last_eval_t = t
        return results

    # -- reporting ----------------------------------------------------------

    def status(self) -> dict:
        """The ``GET /slo`` document: every objective's declaration, live
        burns, state, peak and recent transitions. Reads the LAST
        evaluation — scrapes never advance the hysteresis clocks."""
        with self._lock:
            table = [(slo, st) for slo, st in self._slos.values()]
            last_t = self._last_eval_t
        slos = {}
        worst = OK
        for slo, st in table:
            with self._lock:
                last = dict(st.last) if st.last else None
                transitions = [dict(x) for x in st.transitions[-16:]]
                state, peak, evals = st.state, st.peak, st.evals
            if STATE_LEVELS[state] > STATE_LEVELS[worst]:
                worst = state
            slos[slo.name] = {
                "kind": slo.kind,
                "objective": slo.objective,
                "budget": slo.budget,
                **({"histogram": slo.histogram,
                    "threshold_s": slo.threshold_s}
                   if slo.kind in _HISTOGRAM_KINDS else
                   {"good_keys": list(slo.good_keys),
                    "bad_keys": list(slo.bad_keys)}),
                "windows_s": {"fast": slo.fast_window_s,
                              "slow": slo.slow_window_s},
                "burn_thresholds": {"warn": slo.warn_burn,
                                    "page": slo.page_burn},
                "hysteresis": {"raise_after": slo.raise_after,
                               "clear_after": slo.clear_after},
                "labels": dict(slo.labels),
                "state": state,
                "peak_state": peak,
                "evaluations": evals,
                "last": last,
                "transitions": transitions,
            }
        return {"worst_state": worst, "last_eval_t": last_t,
                "slos": slos}

    def health_block(self) -> dict:
        """Compact block for /healthz: worst state + which SLOs are
        paging/warning. ``evaluated`` False = no evaluation has run yet
        (sampler not started) — health routing must not trust it."""
        with self._lock:
            states = {name: st.state for name, (_s, st) in self._slos.items()}
            evaluated = self._last_eval_t is not None
        worst = OK
        for s in states.values():
            if STATE_LEVELS[s] > STATE_LEVELS[worst]:
                worst = s
        return {"worst_state": worst,
                "paging": sorted(n for n, s in states.items() if s == PAGE),
                "warning": sorted(n for n, s in states.items() if s == WARN),
                "evaluated": evaluated}


# the process-wide engine over the process-wide ring; serving and the
# daemon register objectives here, GET /slo and /healthz read it
ENGINE = SLOEngine()
