"""Failure detection + elastic restart — the fault-tolerance story.

The reference's fault tolerance is thin by design (SURVEY.md §5): MIX
clients reconnect dead channels on the next send (MixClient.java:134-137),
server sessions expire by TTL, cancel messages retract a failed task's
contributions (AbstractPredictionModel.java:88-118), and everything else is
delegated to Hadoop task retry — a failed mapper is simply rerun and the
surviving tasks' model rows are what the final ensemble averages.

Under synchronous SPMD the failure unit is the JOB, not a task: a dead
process breaks the collectives, the step errors, and recovery is
restart-from-checkpoint on whatever topology survives. That is strictly
stronger than the reference's story (which loses the failed mapper's entire
contribution since its close() never runs): here the periodic checkpoint of
the MIXED model preserves every replica's averaged-in work up to the last
mix. The cancel machinery is unnecessary — a checkpoint never contains a
partial, retractable contribution.

Elastic checkpoints cover EVERY trainer family, not just the data-parallel
MixTrainer: the on-disk form is always the COLLAPSED, stripe-free model (a
final_state() result) plus a manifest recording the striping metadata the
run had (family, dims, dims_padded, n_shards, stripe, rule/hyper, step) and
a sha256 digest over the payload (io/checkpoint.save_elastic). Resume
re-stripes N→M through core.striping.restripe — unpad at the old
``stripe*N`` grid, re-pad at the new mesh's ``stripe'*M``, re-place with
NamedSharding — so a run checkpointed on 4 devices resumes bit-compatibly
on 2 or 8.

Usage (manual driver loop):

    trainer, state = elastic_resume(AROW, {"r": 0.1}, dims, "ckpt.npz",
                                    family="sharded", mesh=mesh)
    while blocks:
        state, loss = trainer.step(state, *next_blocks)
        if step % k == 0:
            checkpoint(trainer, state, "ckpt.npz")

Or let ``run_elastic`` drive: it catches distributed step failure (a worker
vanishing kills the job under synchronous SPMD), rebuilds the mesh over the
surviving devices, resumes from the last valid checkpoint, and replays the
steps since — zero mixed work lost since the last checkpoint. Restarts are
visible in Perfetto: each resume runs under a ``recovery.restore`` span and
the fault harness stamps ``fault.injected`` instants (docs/
elastic_training.md).

# graftcheck: serving-module
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
import warnings
from dataclasses import asdict, is_dataclass
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from ..core.engine import Rule
from ..io.checkpoint import (PREV_SUFFIX, load_elastic, load_linear_state,
                             pack_linear_state, save_elastic,
                             unpack_linear_state)
from ..parallel.mesh import make_mesh
from ..parallel.mix import MixConfig, MixTrainer
from . import faults
from .tracing import TRACER

FAMILIES = ("mix", "sharded", "sharded_2d", "fm_sharded", "ffm_sharded")

# Linear backoff between elastic restarts (sleep = backoff * restarts,
# capped at 1 s): a persistently failing step must not burn the whole
# max_restarts budget in microseconds or hammer a failing device at CPU
# speed (graftcheck G031).
RESTART_BACKOFF_S = 0.02


def _hyper_jsonable(hyper) -> object:
    """Best-effort record of the run's hyperparameters for the manifest —
    documentation, not the resume source (the caller re-supplies rule/hyper
    exactly as elastic_resume always required)."""
    if is_dataclass(hyper) and not isinstance(hyper, type):
        hyper = asdict(hyper)
    try:
        json.dumps(hyper)
        return hyper
    except TypeError:  # graftcheck: disable=G028 (hyper is documentation: repr is the documented conversion)
        if isinstance(hyper, dict):
            return {k: v if _is_jsonable(v) else repr(v)
                    for k, v in hyper.items()}
        return repr(hyper)


def _is_jsonable(v) -> bool:
    try:
        json.dumps(v)
        return True
    except TypeError:
        return False


# --- family adapters ---------------------------------------------------------
# One (collapse+pack, unpack+resume) pair per trainer family. The pack side
# always goes through the trainer's OWN final_state() so the on-disk form is
# the same collapsed model a cold export would produce; the resume side goes
# through the trainer's init(from_state=...) which re-stripes via
# core.striping.restripe.


def _family_of(trainer) -> str:
    from ..parallel.sharded_train import (FFMShardedTrainer, FMShardedTrainer,
                                          Sharded2DTrainer, ShardedTrainer)

    if isinstance(trainer, MixTrainer):
        return "mix"
    if isinstance(trainer, ShardedTrainer):
        return "sharded"
    if isinstance(trainer, Sharded2DTrainer):
        return "sharded_2d"
    if isinstance(trainer, FMShardedTrainer):
        return "fm_sharded"
    if isinstance(trainer, FFMShardedTrainer):
        return "ffm_sharded"
    raise TypeError(f"no elastic-checkpoint support for {type(trainer)}")


def _pack_fm_state(host) -> dict:
    from ..io.checkpoint import np_saveable

    return {
        "w0": np.asarray(host.w0), "w": np_saveable(host.w),
        "v": np_saveable(host.v),
        "lambda_w0": np.asarray(host.lambda_w0),
        "lambda_w": np.asarray(host.lambda_w),
        "lambda_v": np.asarray(host.lambda_v),
        "touched": np.asarray(host.touched), "step": np.asarray(host.step),
    }


def _unpack_fm_state(arrays):
    import jax.numpy as jnp

    from ..models.fm import FMState

    f32 = jnp.float32
    return FMState(
        w0=jnp.asarray(arrays["w0"], f32), w=jnp.asarray(arrays["w"], f32),
        v=jnp.asarray(arrays["v"], f32),
        lambda_w0=jnp.asarray(arrays["lambda_w0"], f32),
        lambda_w=jnp.asarray(arrays["lambda_w"], f32),
        lambda_v=jnp.asarray(arrays["lambda_v"], f32),
        touched=jnp.asarray(arrays["touched"], jnp.int8),
        step=jnp.asarray(arrays["step"], jnp.int32),
    )


def _pack_ffm_state(host) -> dict:
    from ..io.checkpoint import np_saveable

    return {
        "w0": np.asarray(host.w0), "w": np_saveable(host.w),
        "z": np.asarray(host.z), "n": np.asarray(host.n),
        "v": np_saveable(host.v), "v_gg": np.asarray(host.v_gg),
        "touched": np.asarray(host.touched), "step": np.asarray(host.step),
    }


def _unpack_ffm_state(arrays):
    import jax.numpy as jnp

    from ..models.ffm import FFMState

    f32 = jnp.float32
    return FFMState(
        w0=jnp.asarray(arrays["w0"], f32), w=jnp.asarray(arrays["w"], f32),
        z=jnp.asarray(arrays["z"], f32), n=jnp.asarray(arrays["n"], f32),
        v=jnp.asarray(arrays["v"], f32),
        v_gg=jnp.asarray(arrays["v_gg"], f32),
        touched=jnp.asarray(arrays["touched"], jnp.int8),
        step=jnp.asarray(arrays["step"], jnp.int32),
    )


def _striping_manifest(trainer, family: str) -> dict:
    """The re-stripe metadata block: what grid the run was on. Resume does
    NOT need it to rebuild (the new trainer derives its own grid from the
    new mesh) — it needs it to validate dims and to make a degraded round
    attributable from the artifact alone."""
    m = {"family": family}
    for attr in ("dims", "dims_padded", "stripe", "n_shards", "n_replicas",
                 "stripe_w", "stripe_v", "nf_padded", "dv_padded"):
        if hasattr(trainer, attr):
            m[attr] = int(getattr(trainer, attr))
    if hasattr(trainer, "mesh"):
        m["n_devices"] = int(trainer.mesh.devices.size)
    if family == "sharded":
        m["n_shards"] = int(trainer.mesh.devices.size)
    if family == "mix":
        m["n_replicas"] = int(trainer.n_dev)
    rule = getattr(trainer, "rule", None)
    if rule is not None:
        m["rule"] = getattr(rule, "name", repr(rule))
    m["hyper"] = _hyper_jsonable(getattr(trainer, "hyper", None))
    return m


def checkpoint(trainer, state, path: str,
               block_step: Optional[int] = None) -> dict:
    """Atomically persist the COLLAPSED (mixed, replica-free, stripe-free)
    model — the form any future mesh size can resume from — plus a manifest
    with striping metadata and a payload digest (io/checkpoint.save_elastic:
    write-then-rename, previous checkpoint rotated to ``.prev``). Covers
    every trainer family: MixTrainer, ShardedTrainer, Sharded2DTrainer,
    FMShardedTrainer, FFMShardedTrainer. ``block_step`` is the driver's
    completed-step count — run_elastic resumes its data stream there.

    Under multi-process jax (mix family) this is a COLLECTIVE: every
    process must call it (the global state is not addressable from one
    process; an allgather brings it to every host), and only process 0
    writes the file."""
    import jax

    family = _family_of(trainer)
    manifest = _striping_manifest(trainer, family)
    if block_step is not None:
        manifest["block_step"] = int(block_step)

    if family == "mix" and jax.process_count() > 1:
        from jax.experimental import multihost_utils

        host = multihost_utils.process_allgather(state, tiled=True)
        if jax.process_index() == 0:
            merged = trainer.collapse_host(host)
            manifest["step"] = int(np.asarray(merged.step))
            manifest = save_elastic(path, pack_linear_state(merged), manifest)
        # trailing barrier: no process may act on "checkpoint written"
        # (e.g. tear the job down for an elastic downscale) until the
        # write+rename actually completed on process 0
        multihost_utils.sync_global_devices("hivemall_tpu_checkpoint")
        return manifest

    merged = trainer.final_state(state)
    # the COLLAPSED model's step counter (a resumed replicated run's
    # per-replica counters each carry the seeded base; the collapse strips
    # it and restores it once — summing raw leaves would over-count)
    manifest["step"] = int(np.asarray(merged.step))
    if family in ("mix", "sharded", "sharded_2d"):
        arrays = pack_linear_state(merged)
    elif family == "fm_sharded":
        arrays = _pack_fm_state(merged)
    else:
        arrays = _pack_ffm_state(merged)
    return save_elastic(path, arrays, manifest)


def peek_manifest(path: str) -> Optional[dict]:
    """The newest valid checkpoint's manifest (falling back to ``.prev``
    like the resume path does), or None when no usable checkpoint exists."""
    try:
        _, manifest = load_elastic(path)
        return manifest
    except Exception:  # graftcheck: disable=G028 (peek probe: None is the documented no-usable-checkpoint answer)
        return None


def _load_for_resume(path: str, family: str):
    """(state, manifest) from the newest valid checkpoint, or (None, None)
    when no checkpoint exists yet (cold start). Legacy pre-manifest
    checkpoints (a bare save_linear_state npz) still resume for the linear
    families. A valid checkpoint whose manifest names a different family
    or dims is a hard error — resuming an FM run into a linear trainer
    silently would be worse than crashing."""
    from ..io.checkpoint import NotElasticCheckpoint

    if not (os.path.exists(path) or os.path.exists(path + ".prev")):
        return None, None
    try:
        arrays, manifest = load_elastic(path)
    except NotElasticCheckpoint:
        # legacy format: a bare save_linear_state npz, no embedded
        # manifest. The NotElasticCheckpoint may have surfaced from the
        # ``.prev`` half of load_elastic's fallback (corrupt elastic
        # newest rotated over a legacy previous) — so the newest itself
        # can still be unreadable: fall back to the legacy .prev, loudly.
        if family not in ("mix", "sharded", "sharded_2d"):
            raise
        try:
            return load_linear_state(path), None
        except Exception as e:
            prev = path + PREV_SUFFIX
            if not os.path.exists(prev):
                raise
            warnings.warn(
                f"elastic checkpoint {path} is unusable ({e}); falling "
                f"back to the previous legacy checkpoint {prev} — work "
                "since that checkpoint will be replayed", RuntimeWarning,
                stacklevel=3)
            return load_linear_state(prev), None
    except FileNotFoundError:
        return None, None
    ck_family = manifest.get("family")
    linear = ("mix", "sharded", "sharded_2d")
    compatible = (ck_family == family
                  or (ck_family in linear and family in linear))
    if not compatible:
        raise ValueError(f"checkpoint {path} holds a {ck_family!r}-family "
                         f"model; cannot resume it as {family!r}")
    if family in linear:
        return unpack_linear_state(arrays), manifest
    if family == "fm_sharded":
        return _unpack_fm_state(arrays), manifest
    return _unpack_ffm_state(arrays), manifest


def elastic_resume(rule: Optional[Rule], hyper, dims: int, path: str,
                   mesh=None, config: MixConfig = MixConfig(),
                   mode: str = "minibatch", family: str = "mix",
                   **trainer_kwargs) -> Tuple[object, object]:
    """Build a trainer of ``family`` over the CURRENT mesh (whatever
    jax.devices() — or the passed mesh — says survives) and seed it from
    the checkpoint at ``path`` if a valid one exists, else from zeros.
    Returns (trainer, state).

    Families: ``mix`` (data-parallel MixTrainer — rule/hyper/dims/config),
    ``sharded`` (feature-striped ShardedTrainer), ``sharded_2d`` (replicas
    x stripes — pass a 2-D mesh or n_replicas/n_shards kwargs),
    ``fm_sharded`` (hyper is an FMHyper; rule ignored), ``ffm_sharded``
    (hyper is an FFMHyper; rule and dims ignored). The sharded families
    re-stripe the checkpoint N→M for whatever device count the new mesh
    has, including non-divisible dims (the stripe grid re-pads)."""
    if family not in FAMILIES:
        raise ValueError(f"unknown family {family!r}; one of {FAMILIES}")
    state, manifest = _load_for_resume(path, family)
    if manifest is not None and "dims" in manifest \
            and family != "ffm_sharded" and int(manifest["dims"]) != dims:
        raise ValueError(
            f"checkpoint {path} was trained at dims {manifest['dims']} != "
            f"requested {dims}; resume with the dims the model was trained "
            "at")

    if family == "mix":
        trainer = MixTrainer(rule, hyper, dims, mesh, config, mode=mode)
    else:
        from ..parallel.sharded_train import (FFMShardedTrainer,
                                              FMShardedTrainer,
                                              Sharded2DTrainer,
                                              ShardedTrainer)

        if family == "sharded":
            trainer = ShardedTrainer(rule, hyper, dims, mesh, mode=mode,
                                     **trainer_kwargs)
        elif family == "sharded_2d":
            trainer = Sharded2DTrainer(rule, hyper, dims, mesh, config=config,
                                       mode=mode, **trainer_kwargs)
        elif family == "fm_sharded":
            trainer = FMShardedTrainer(hyper, dims, mesh, mode=mode,
                                       **trainer_kwargs)
        else:
            trainer = FFMShardedTrainer(hyper, mesh, mode=mode,
                                        **trainer_kwargs)
    # the manifest this resume actually loaded (None on cold start or a
    # legacy checkpoint) — run_elastic reads it instead of re-loading and
    # re-hashing the whole payload just to learn block_step
    trainer._elastic_manifest = manifest
    return trainer, trainer.init(from_state=state)


# --- the elastic driver loop -------------------------------------------------

_PEEK = object()  # "factory did not come through elastic_resume" sentinel


def run_elastic(make_trainer: Callable[[Sequence], Tuple[object, object]],
                data_fn: Callable[[object, int], tuple], n_steps: int,
                path: str, *, checkpoint_every: int = 8,
                max_restarts: int = 4,
                devices: Optional[Sequence] = None,
                recoverable: Optional[Tuple[type, ...]] = None,
                min_devices: int = 1) -> Tuple[object, object, dict]:
    """Worker-loss-tolerant driver: run ``n_steps`` training steps with a
    checkpoint every ``checkpoint_every``, and on ANY recoverable step
    failure rebuild over the surviving devices and resume from the last
    valid checkpoint, replaying the steps since it (zero mixed work lost
    since the last checkpoint).

    - ``make_trainer(devices) -> (trainer, state)``: build the family over
      a mesh on exactly these devices and seed from ``path`` — typically a
      closure over elastic_resume(..., mesh=make_mesh(devices=devices)).
      A ``faults.WorkerLost`` shrinks the device list before the rebuild
      (the simulated fleet); any other recoverable error retries the same
      topology.
    - ``data_fn(trainer, i) -> step-args tuple`` for driver step ``i`` —
      the deterministic data stream; after a restart it is replayed from
      the checkpoint's ``block_step``.

    Recovery is traced: each rebuild runs under a ``recovery.restore``
    span (device count, resumed step in args) inside the run's
    ``recovery.run_elastic`` root, and injected faults stamp
    ``fault.injected`` instants — a restart is visible in Perfetto as a
    restore span sandwiched between step spans.

    **Preemption-aware**: for the duration of the run a SIGTERM handler is
    installed (main thread only — elsewhere the signal module refuses and
    the run proceeds without it). On SIGTERM the in-flight step finishes,
    the state checkpoints IMMEDIATELY — not at the next cadence boundary —
    and the driver returns early with ``report["preempted"] = True`` and
    ``report["preempted_at_step"]``, so a preempted pod loses zero
    completed steps and the next ``run_elastic`` on whatever hardware
    replaces it resumes from the exact step the eviction interrupted (the
    cloud-preemption half of elastic training; cadence checkpoints only
    bound the loss from UNANNOUNCED failures). The previous handler is
    restored on exit.

    Returns ``(trainer, state, report)``; the report carries restarts,
    per-restart causes, lost (replayed) steps, checkpoints written, and
    recovery seconds — the numbers scripts/bench_chaos.py publishes."""
    import jax

    if recoverable is None:
        recoverable = (faults.WorkerLost, faults.TransientStepError,
                       faults.CrashMidWrite)
    devices = list(devices if devices is not None else jax.devices())
    report = {"restarts": 0, "causes": [], "lost_steps": 0,
              "checkpoints_written": 0, "recovery_s": 0.0,
              "preempted": False,
              "initial_devices": len(devices), "final_devices": len(devices)}
    term = threading.Event()
    prev_handler = None
    if threading.current_thread() is threading.main_thread():
        try:
            prev_handler = signal.signal(
                signal.SIGTERM, lambda signum, frame: term.set())
        except ValueError:  # exotic embeddings where signal still refuses
            prev_handler = None
    try:
        return _run_elastic_loop(make_trainer, data_fn, n_steps, path,
                                 checkpoint_every, max_restarts, devices,
                                 recoverable, min_devices, report, term)
    finally:
        if prev_handler is not None:
            signal.signal(signal.SIGTERM, prev_handler)


def _run_elastic_loop(make_trainer, data_fn, n_steps, path, checkpoint_every,
                      max_restarts, devices, recoverable, min_devices,
                      report, term):
    with TRACER.span("recovery.run_elastic",
                     args={"n_steps": int(n_steps), "path": path}):
        while True:
            t0 = time.monotonic()
            with TRACER.span("recovery.restore",
                             args={"devices": len(devices)}) as sp:
                trainer, state = make_trainer(devices)
                # elastic_resume stashed the manifest it loaded; fall back
                # to a peek only for factories that build trainers some
                # other way
                manifest = getattr(trainer, "_elastic_manifest", _PEEK)
                if manifest is _PEEK:
                    manifest = peek_manifest(path)
                start = int((manifest or {}).get("block_step", 0))
                if manifest is not None and "block_step" not in manifest \
                        or manifest is None and (
                            os.path.exists(path)
                            or os.path.exists(path + PREV_SUFFIX)):
                    warnings.warn(
                        f"checkpoint at {path} carries no block_step — "
                        "run_elastic will replay the data stream from step "
                        "0 on top of the seeded state (examples applied "
                        "twice). Stamp checkpoints via run_elastic or "
                        "checkpoint(..., block_step=...) to resume the "
                        "stream where it stopped", RuntimeWarning,
                        stacklevel=2)
                if sp is not None and hasattr(sp, "args"):
                    sp.args["resumed_step"] = start
            if report["restarts"] or report["checkpoints_written"]:
                report["recovery_s"] += time.monotonic() - t0
            last_ckpt = start
            completed = start  # steps whose update landed this attempt
            try:
                for i in range(start, n_steps):
                    faults.step_hook(i)
                    with TRACER.span("train.step", args={"step": i}):
                        state, loss = trainer.step(state, *data_fn(trainer, i))
                    completed = i + 1
                    if term.is_set():
                        # SIGTERM landed: checkpoint the completed step NOW
                        # instead of waiting for the cadence, then hand
                        # control back so the process can exit inside its
                        # grace period — the next run_elastic resumes here
                        checkpoint(trainer, state, path, block_step=i + 1)
                        report["checkpoints_written"] += 1
                        report["preempted"] = True
                        report["preempted_at_step"] = i + 1
                        report["final_devices"] = len(devices)
                        TRACER.instant("recovery.preempted",
                                       args={"step": i + 1})
                        return trainer, state, report
                    if (i + 1) % checkpoint_every == 0:
                        checkpoint(trainer, state, path, block_step=i + 1)
                        report["checkpoints_written"] += 1
                        last_ckpt = i + 1
                if n_steps % checkpoint_every != 0 or n_steps == 0:
                    checkpoint(trainer, state, path, block_step=n_steps)
                    report["checkpoints_written"] += 1
                report["final_devices"] = len(devices)
                return trainer, state, report
            except recoverable as e:
                report["restarts"] += 1
                # every completed-but-not-checkpointed step gets replayed
                report["lost_steps"] += max(0, completed - last_ckpt)
                report["causes"].append(
                    {"type": type(e).__name__, "step": completed,
                     "devices": len(devices)})
                if report["restarts"] > max_restarts:
                    # supervisor give-up: drop the flight-recorder bundle
                    # next to the checkpoint before re-raising (the crash
                    # postmortem artifact; write_crash_bundle never
                    # raises, so the fatal exception stays the signal)
                    from .debug_bundle import write_crash_bundle

                    write_crash_bundle(
                        path + ".crash_bundle.json",
                        reason=(f"run_elastic gave up after "
                                f"{report['restarts']} restarts (last "
                                f"cause: {type(e).__name__}: {e}; "
                                f"devices={len(devices)}, "
                                f"step={completed})"))
                    raise
                time.sleep(min(RESTART_BACKOFF_S * report["restarts"], 1.0))
                if isinstance(e, faults.WorkerLost):
                    survivors = devices[: max(min_devices,
                                              len(devices) - e.n_lost)]
                    if len(survivors) == len(devices) \
                            and len(devices) > min_devices:
                        survivors = devices[:-1]
                    devices = survivors
                TRACER.instant("recovery.restart",
                               args={"cause": type(e).__name__,
                                     "devices": len(devices)})


def make_elastic_mesh(devices: Sequence, n_devices: Optional[int] = None):
    """The default mesh rebuild for run_elastic closures: a 1-D mesh over
    exactly the surviving devices (parallel/mesh.make_mesh)."""
    return make_mesh(n_devices=n_devices, devices=list(devices))
