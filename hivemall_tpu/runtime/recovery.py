"""Failure detection + elastic restart — the fault-tolerance story.

The reference's fault tolerance is thin by design (SURVEY.md §5): MIX
clients reconnect dead channels on the next send (MixClient.java:134-137),
server sessions expire by TTL, cancel messages retract a failed task's
contributions (AbstractPredictionModel.java:88-118), and everything else is
delegated to Hadoop task retry — a failed mapper is simply rerun and the
surviving tasks' model rows are what the final ensemble averages.

Under synchronous SPMD the failure unit is the JOB, not a task: a dead
process breaks the collectives, the step errors, and recovery is
restart-from-checkpoint on whatever topology survives. That is strictly
stronger than the reference's story (which loses the failed mapper's entire
contribution since its close() never runs): here the periodic checkpoint of
the MIXED model preserves every replica's averaged-in work up to the last
mix. The cancel machinery is unnecessary — a checkpoint never contains a
partial, retractable contribution.

Usage (the driver loop):

    trainer, state = elastic_resume(AROW, {"r": 0.1}, dims, "ckpt.npz")
    while blocks:
        state, loss = trainer.step(state, *next_blocks)
        if step % k == 0:
            checkpoint(trainer, state, "ckpt.npz")

On any distributed failure: relaunch the job on the surviving hosts; the
same elastic_resume call rebuilds the trainer over the NEW (smaller or
larger) mesh and reseeds every replica from the checkpoint.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from ..core.engine import Rule
from ..io.checkpoint import load_linear_state, save_linear_state
from ..parallel.mix import MixConfig, MixTrainer


def checkpoint(trainer: MixTrainer, state, path: str) -> None:
    """Atomically persist the COLLAPSED (mixed, replica-free) model — the
    form any future mesh size can resume from. Write-then-rename so a crash
    mid-write never corrupts the previous checkpoint.

    Under multi-process jax this is a COLLECTIVE: every process must call it
    (the global state is not addressable from one process; an allgather
    brings it to every host), and only process 0 writes the file."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        host = multihost_utils.process_allgather(state, tiled=True)
        if jax.process_index() == 0:
            merged = trainer.collapse_host(host)
            tmp = path + ".tmp.npz"
            save_linear_state(tmp, merged)
            os.replace(tmp, path)
        # trailing barrier: no process may act on "checkpoint written"
        # (e.g. tear the job down for an elastic downscale) until the
        # write+rename actually completed on process 0
        multihost_utils.sync_global_devices("hivemall_tpu_checkpoint")
        return
    merged = trainer.final_state(state)
    # .npz suffix keeps np.savez from renaming the temp file under us
    tmp = path + ".tmp.npz"
    save_linear_state(tmp, merged)
    os.replace(tmp, path)


def elastic_resume(rule: Rule, hyper: dict, dims: int, path: str,
                   mesh=None, config: MixConfig = MixConfig(),
                   mode: str = "minibatch") -> Tuple[MixTrainer, object]:
    """Build a MixTrainer over the CURRENT mesh (whatever jax.devices() — or
    the passed mesh — says survives) and seed it from the checkpoint at
    `path` if one exists, else from zeros. Returns (trainer, state)."""
    trainer = MixTrainer(rule, hyper, dims, mesh, config, mode=mode)
    from_state = load_linear_state(path) if os.path.exists(path) else None
    return trainer, trainer.init(from_state=from_state)
