"""Observability: counters, stopwatch, throughput sampling, profiler hooks.

Mirrors the reference's observability surface (SURVEY.md §5):
- StopWatch elapsed-time logging (ref: utils/datetime/StopWatch.java, used in
  model load LearnerBaseUDTF.java:217-234)
- Hadoop Reporter/Counters for progress + iteration counts
  (ref: UDTFWithOptions.java:59-88, FM iteration counter
  FactorizationMachineUDTF.java:529-543)
- the MIX server's ThroughputCounter msgs/sec sampling + JMX MBean registry
  (ref: mixserv/.../metrics/ThroughputCounter.java:34, MetricsRegistry.java)

Plus the TPU-native upgrade the reference lacks: `trace()` wraps a block in
the JAX profiler so kernels show up in xprof/TensorBoard.
"""

from __future__ import annotations

import contextlib
import logging
import re
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional


class StopWatch:
    def __init__(self, label: str = "") -> None:
        self.label = label
        self._start = time.perf_counter()

    def restart(self) -> None:
        self._start = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self._start

    def __str__(self) -> str:
        return f"{self.label} {self.elapsed() * 1000:.1f} ms"


class Counter:
    """A named monotonic counter (Hadoop Counter analog)."""

    def __init__(self, group: str, name: str) -> None:
        self.group = group
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def increment(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class ThroughputCounter:
    """Events/sec sampled over a sliding window (ThroughputCounter analog)."""

    def __init__(self, window_sec: float = 5.0) -> None:
        self.window = window_sec
        self._events: list = []
        self._lock = threading.Lock()
        self.last_reads_per_sec = 0.0

    def record(self, n: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            self._events.append((now, n))
            cutoff = now - self.window
            while self._events and self._events[0][0] < cutoff:
                self._events.pop(0)
            span = max(1e-9, now - (self._events[0][0] if self._events else now))
            self.last_reads_per_sec = sum(c for _, c in self._events) / max(span, 1e-9)


class Histogram:
    """Fixed-bucket cumulative histogram (the Prometheus histogram shape).

    `buckets` are upper bounds in ascending order; an implicit +Inf bucket
    catches the tail. observe() is lock-guarded and O(len(buckets)) — cheap
    enough for per-request latency recording on the serving path
    (serving/engine.py, serving/batcher.py), and usable next to any
    existing meter (e.g. per-block step walltime).
    """

    # Latency-shaped default: 500us .. 10s, roughly log-spaced (seconds).
    DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                       0.1, 0.25, 0.5, 1.0, 2.5, 10.0)

    def __init__(self, name: str, buckets=DEFAULT_BUCKETS) -> None:
        self.name = name
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # [+Inf] is last
        self.sum = 0.0
        self.count = 0
        # bucket index -> (value, trace_id, unix_ts): the last sampled
        # observation that landed there (OpenMetrics exemplar shape) — a
        # bad p99 bucket links straight to a trace in runtime/tracing.py
        self._exemplars: Dict[int, tuple] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        v = float(value)
        i = 0
        for i, ub in enumerate(self.buckets):
            if v <= ub:
                break
        else:
            i = len(self.buckets)
        now = time.time() if trace_id is not None else 0.0
        with self._lock:
            self._counts[i] += 1
            self.sum += v
            self.count += 1
            if trace_id is not None:
                self._exemplars[i] = (v, trace_id, now)

    def exemplars(self) -> dict:
        """{bucket_upper_bound: {"value", "trace_id", "unix"}} for buckets
        that have one (the +Inf overflow keys as inf)."""
        with self._lock:
            items = dict(self._exemplars)
        bounds = self.buckets + (float("inf"),)
        return {bounds[i]: {"value": v, "trace_id": tid, "unix": ts}
                for i, (v, tid, ts) in items.items()}

    def snapshot(self) -> dict:
        """{"buckets": [(upper_bound, cumulative_count)...], "sum", "count"}
        with the trailing +Inf bucket included (cumulative == count)."""
        with self._lock:
            counts = list(self._counts)
            total, s = self.count, self.sum
        cum, out = 0, []
        for ub, c in zip(self.buckets, counts):
            cum += c
            out.append((ub, cum))
        out.append((float("inf"), total))
        return {"buckets": out, "sum": s, "count": total}

    def quantile(self, q: float) -> float:
        """Quantile estimate with LINEAR INTERPOLATION inside the holding
        bucket (the Prometheus histogram_quantile formula): the q-th rank
        is located in its cumulative bucket, then placed proportionally
        between the bucket's lower and upper bound — a p50 of values
        clustered near a bucket's floor no longer over-reports as the
        bucket's ceiling. For dashboards/logs; benches that need exact
        percentiles keep raw samples. Ranks landing in the +Inf overflow
        clamp to the largest finite bound (the histogram_quantile
        convention — and inf would break strict JSON)."""
        snap = self.snapshot()
        if not snap["count"] or not self.buckets:
            return 0.0
        rank = q * snap["count"]
        prev_cum, lo = 0, 0.0
        for ub, cum in snap["buckets"]:
            if cum >= rank:
                if ub == float("inf"):
                    return self.buckets[-1]
                in_bucket = cum - prev_cum
                if in_bucket <= 0:
                    return ub
                return lo + (ub - lo) * (rank - prev_cum) / in_bucket
            prev_cum, lo = cum, ub
        return self.buckets[-1]


class MetricsRegistry:
    """Process-wide registry (the JMX MBean registry analog); exportable as a
    plain dict for scraping."""

    def __init__(self) -> None:
        self.counters: Dict[str, Counter] = {}
        self.throughput: Dict[str, ThroughputCounter] = {}
        self.gauges: Dict[str, float] = {}
        self.histograms: Dict[str, Histogram] = {}
        # registration and snapshot share one lock: the HTTP scrape thread
        # (runtime/metrics_http.py) iterates while the training thread may
        # be registering new keys
        self._lock = threading.Lock()

    def counter(self, group: str, name: str) -> Counter:
        key = f"{group}.{name}"
        with self._lock:
            if key not in self.counters:
                self.counters[key] = Counter(group, name)
            return self.counters[key]

    def meter(self, name: str) -> ThroughputCounter:
        with self._lock:
            if name not in self.throughput:
                self.throughput[name] = ThroughputCounter()
            return self.throughput[name]

    def histogram(self, name: str, buckets=None) -> Histogram:
        with self._lock:
            if name not in self.histograms:
                self.histograms[name] = Histogram(
                    name, buckets if buckets is not None
                    else Histogram.DEFAULT_BUCKETS)
            return self.histograms[name]

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = dict(self.gauges)
            for key, c in self.counters.items():
                out[key] = float(c.value)
            for name, t in self.throughput.items():
                out[f"{name}.per_sec"] = t.last_reads_per_sec
            hists = list(self.histograms.items())
        # histogram locks are taken outside the registry lock (fixed order:
        # registry -> histogram; nothing takes them in reverse)
        for name, h in hists:
            snap = h.snapshot()
            out[f"{name}.count"] = float(snap["count"])
            out[f"{name}.sum"] = float(snap["sum"])
        return out

    def typed_snapshot(self) -> dict:
        """Snapshot keeping metric kinds apart — the Prometheus exposition
        (runtime/metrics_http.py) needs # TYPE per family."""
        with self._lock:
            counters = {k: float(c.value) for k, c in self.counters.items()}
            gauges = dict(self.gauges)
            meters = {f"{n}.per_sec": t.last_reads_per_sec
                      for n, t in self.throughput.items()}
            hists = list(self.histograms.items())
        return {
            "counters": counters,
            "gauges": gauges,
            "meters": meters,
            "histograms": {n: {**h.snapshot(), "exemplars": h.exemplars()}
                           for n, h in hists},
        }


REGISTRY = MetricsRegistry()


def _jit_cache_size(fn) -> int:
    """Compile-cache entry count of a jax.jit product (0 when unknown)."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return 0
    try:
        return int(probe())
    except Exception:  # graftcheck: disable=G028 (jax-internal probe: 0 is the documented unknown)
        return 0


# jax 0.4.x logs every XLA compile at DEBUG as "Compiling <fn> with global
# shapes and types [ShapedArray(...)]. Argument mapping: ...". The capture
# anchors on the sentence structure, NOT a bracket match — shapes like
# float32[4] contain `]`, so a lazy `\[.*?\]` truncates mid-list.
_COMPILE_LOG_RE = re.compile(
    r"Compiling (\S+) with global shapes and types (.*?)\. Argument mapping")

# the module that owns the "Compiling ..." log line; if a future jax moves
# it, attribution degrades to empty (counters are unaffected)
_COMPILE_LOGGER = "jax._src.interpreters.pxla"

# fn name -> shape signature of its LAST compile, process-wide: lets a later
# guard label a recompile as a shape delta vs a fresh-identity churn
_LAST_COMPILED_SHAPES: Dict[str, str] = {}


class _CompileLogCapture(logging.Handler):
    """DEBUG tap on the ``jax`` logger: names the function being compiled
    and the abstract shapes that missed the cache — attribution a
    cache-size probe cannot give. A fresh ``jax.jit`` wrapper built per
    call compiles every iteration while every *named* probe stays flat
    (G032's counter blind spot); the compile log still names the wrapped
    function each time."""

    def __init__(self) -> None:
        super().__init__(level=logging.DEBUG)
        self.events: list = []

    def emit(self, record: logging.LogRecord) -> None:
        try:
            m = _COMPILE_LOG_RE.search(record.getMessage())
        except Exception:  # graftcheck: disable=G028,G029 (a malformed log record must never break the guarded step; nothing to degrade to — the event is simply not attributed)
            return
        if m:
            self.events.append((m.group(1), m.group(2)))


class recompile_guard:
    """Count jit cache misses per named step function — the runtime witness
    for graftcheck's G001 recompile-hazard rule (hivemall_tpu/analysis).

    Wrap the steady-state section of a training loop::

        step = make_train_step(rule, hyper)
        with recompile_guard("arow_minibatch", step) as g:
            for block in blocks:
                state, loss = step(state, *block)
        g.compiles  # cache misses INSIDE the block; 0 after warmup

    Every exit increments the process-wide counter
    ``graftcheck.recompiles.<name>`` and sets the gauge
    ``<name>.jit_cache_entries`` to the functions' total cache size, so the
    /metrics endpoint (runtime/metrics_http.py) exposes

        hivemall_tpu_graftcheck_recompiles_<name>
        hivemall_tpu_<name>_jit_cache_entries

    and a static G001 finding can be confirmed on hardware: a step function
    recompiling per invocation shows a recompile counter growing linearly
    with steps (the recompilation-count production metric of the ads-infra
    paper, PAPERS.md). ``expect_stable=True`` raises on any miss — used by
    tests and scripts/profile_step.py to pin the steady state.

    Every guard also taps the jax compile log (``_CompileLogCapture``) and
    records one attribution per compile in ``guard.attributions``:
    ``{"fn": <jitted fn name>, "shapes": <abstract arg shapes>, "prev":
    <that fn's previous shapes or None>, "delta": <bool>}``. This closes
    the counter's blind spot — a fresh wrapper identity (G032) compiles
    per call while every named probe stays flat, but the log still names
    the function — and lets the static finding and the live counter point
    at the same line. Each attribution is also emitted as a
    ``jit_retrace_attrib`` trace instant next to ``jit_recompile``.
    """

    def __init__(self, name: str, *jitted_fns, registry: "MetricsRegistry" = None,
                 expect_stable: bool = False) -> None:
        self.name = name
        self.fns = jitted_fns
        self.registry = registry if registry is not None else REGISTRY
        self.expect_stable = expect_stable
        self.compiles = 0
        self.attributions: list = []
        self._start: list = []
        self._log_tap: Optional[_CompileLogCapture] = None
        self._prior_level = logging.NOTSET

    def __enter__(self) -> "recompile_guard":
        if self.expect_stable and self.fns and not any(
                getattr(f, "_cache_size", None) is not None
                for f in self.fns):
            # a guard that cannot observe the cache must not certify
            # stability — fail fast instead of silently reporting 0 misses
            raise RuntimeError(
                f"recompile_guard({self.name!r}, expect_stable=True): none "
                f"of the guarded functions expose a jit cache-size probe "
                f"(_cache_size) — pass jax.jit products")
        self._start = [_jit_cache_size(f) for f in self.fns]
        self._log_tap = _CompileLogCapture()
        logger = logging.getLogger(_COMPILE_LOGGER)
        self._prior_level = logger.level
        self._prior_propagate = logger.propagate
        logger.addHandler(self._log_tap)
        if logger.getEffectiveLevel() > logging.DEBUG:
            # debug logging is off: lower just the compile logger and stop
            # propagation so the capture stays silent on the console; when
            # the user already runs jax at DEBUG, touch nothing
            logger.setLevel(logging.DEBUG)
            logger.propagate = False
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        logger = logging.getLogger(_COMPILE_LOGGER)
        logger.removeHandler(self._log_tap)
        logger.setLevel(self._prior_level)
        logger.propagate = self._prior_propagate
        for fn_name, shapes in self._log_tap.events:
            prev = _LAST_COMPILED_SHAPES.get(fn_name)
            _LAST_COMPILED_SHAPES[fn_name] = shapes
            self.attributions.append({
                "fn": fn_name, "shapes": shapes, "prev": prev,
                "delta": prev is not None and prev != shapes})
        sizes = [_jit_cache_size(f) for f in self.fns]
        self.compiles = sum(max(0, now - was)
                            for was, now in zip(self._start, sizes))
        self.registry.counter("graftcheck",
                              f"recompiles.{self.name}").increment(
            self.compiles)
        if self.compiles or self.attributions:
            # a cache miss inside an active trace span shows up INSIDE the
            # request/step that paid for it (late import: tracing is a
            # leaf module; this path only runs on the cold compile)
            from .tracing import TRACER

            if self.compiles:
                TRACER.instant("jit_recompile", {"guard": self.name,
                                                 "compiles": self.compiles})
            for a in self.attributions:
                TRACER.instant("jit_retrace_attrib",
                               {"guard": self.name, "fn": a["fn"],
                                "shapes": a["shapes"],
                                "prev": a["prev"] or "",
                                "shape_delta": a["delta"]})
        self.registry.set_gauge(f"{self.name}.jit_cache_entries",
                                float(sum(sizes)))
        if exc_type is None and self.expect_stable and self.compiles:
            attrib = "; ".join(
                f"{a['fn']} {a['shapes']}"
                + (" [shape delta]" if a["delta"] else "")
                for a in self.attributions) \
                or "no compile-log attribution captured"
            raise RuntimeError(
                f"recompile_guard({self.name!r}): {self.compiles} jit cache "
                f"miss(es) in a section expected steady — a G001-class "
                f"hazard is retracing the step function ({attrib})")


@contextlib.contextmanager
def trace(name: str, log_dir: Optional[str] = None) -> Iterator[None]:
    """Wrap a block in the JAX profiler (xprof trace) when log_dir is given;
    always records wall time as a gauge."""
    sw = StopWatch(name)
    if log_dir:
        import jax

        with jax.profiler.trace(log_dir):
            yield
    else:
        yield
    REGISTRY.set_gauge(f"{name}.seconds", sw.elapsed())
