"""Observability: counters, stopwatch, throughput sampling, profiler hooks.

Mirrors the reference's observability surface (SURVEY.md §5):
- StopWatch elapsed-time logging (ref: utils/datetime/StopWatch.java, used in
  model load LearnerBaseUDTF.java:217-234)
- Hadoop Reporter/Counters for progress + iteration counts
  (ref: UDTFWithOptions.java:59-88, FM iteration counter
  FactorizationMachineUDTF.java:529-543)
- the MIX server's ThroughputCounter msgs/sec sampling + JMX MBean registry
  (ref: mixserv/.../metrics/ThroughputCounter.java:34, MetricsRegistry.java)

Plus the TPU-native upgrade the reference lacks: `trace()` wraps a block in
the JAX profiler so kernels show up in xprof/TensorBoard.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional


class StopWatch:
    def __init__(self, label: str = "") -> None:
        self.label = label
        self._start = time.perf_counter()

    def restart(self) -> None:
        self._start = time.perf_counter()

    def elapsed(self) -> float:
        return time.perf_counter() - self._start

    def __str__(self) -> str:
        return f"{self.label} {self.elapsed() * 1000:.1f} ms"


class Counter:
    """A named monotonic counter (Hadoop Counter analog)."""

    def __init__(self, group: str, name: str) -> None:
        self.group = group
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def increment(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class ThroughputCounter:
    """Events/sec sampled over a sliding window (ThroughputCounter analog)."""

    def __init__(self, window_sec: float = 5.0) -> None:
        self.window = window_sec
        self._events: list = []
        self._lock = threading.Lock()
        self.last_reads_per_sec = 0.0

    def record(self, n: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            self._events.append((now, n))
            cutoff = now - self.window
            while self._events and self._events[0][0] < cutoff:
                self._events.pop(0)
            span = max(1e-9, now - (self._events[0][0] if self._events else now))
            self.last_reads_per_sec = sum(c for _, c in self._events) / max(span, 1e-9)


class MetricsRegistry:
    """Process-wide registry (the JMX MBean registry analog); exportable as a
    plain dict for scraping."""

    def __init__(self) -> None:
        self.counters: Dict[str, Counter] = {}
        self.throughput: Dict[str, ThroughputCounter] = {}
        self.gauges: Dict[str, float] = {}
        # registration and snapshot share one lock: the HTTP scrape thread
        # (runtime/metrics_http.py) iterates while the training thread may
        # be registering new keys
        self._lock = threading.Lock()

    def counter(self, group: str, name: str) -> Counter:
        key = f"{group}.{name}"
        with self._lock:
            if key not in self.counters:
                self.counters[key] = Counter(group, name)
            return self.counters[key]

    def meter(self, name: str) -> ThroughputCounter:
        with self._lock:
            if name not in self.throughput:
                self.throughput[name] = ThroughputCounter()
            return self.throughput[name]

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = dict(self.gauges)
            for key, c in self.counters.items():
                out[key] = float(c.value)
            for name, t in self.throughput.items():
                out[f"{name}.per_sec"] = t.last_reads_per_sec
        return out


REGISTRY = MetricsRegistry()


@contextlib.contextmanager
def trace(name: str, log_dir: Optional[str] = None) -> Iterator[None]:
    """Wrap a block in the JAX profiler (xprof trace) when log_dir is given;
    always records wall time as a gauge."""
    sw = StopWatch(name)
    if log_dir:
        import jax

        with jax.profiler.trace(log_dir):
            yield
    else:
        yield
    REGISTRY.set_gauge(f"{name}.seconds", sw.elapsed())
