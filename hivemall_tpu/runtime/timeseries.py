"""In-process metrics time series: a bounded ring of periodic snapshots.

The registry (runtime/metrics.py) answers "what is the value NOW"; trend
questions — is the shed rate climbing, what was p99 over the last minute,
did freshness regress since the deploy — need history. Production fleets
park that history in Prometheus; a single-process runtime should not need
an external scrape stack to know its own recent past, so this module keeps
it in-process:

- ``TimeSeriesRing`` samples ``REGISTRY.typed_snapshot()`` (exemplars
  stripped — they are debugging payload, not trend data) on a background
  daemon thread every ``interval_s`` into a ``deque(maxlen=capacity)``:
  memory is bounded by construction, the oldest sample falls off the far
  end, and a week-long process holds exactly ``capacity`` samples.
- Queries are windowed over the trailing ``seconds``: ``delta()`` /
  ``rate()`` for counters, ``hist_delta()`` for the cumulative-bucket
  delta of a histogram (the observations INSIDE the window), and
  ``frac_over()`` / ``quantile()`` computed on that delta with the same
  linear interpolation ``Histogram.quantile`` uses — windowed p99 without
  raw samples.
- ``add_listener(fn)`` runs ``fn(t, snapshot)`` after every sample,
  outside every lock — the SLO engine (runtime/slo.py) evaluates its
  burn rates on this hook, so alert cadence equals sample cadence.

Locking discipline (graftcheck G012-G016; this module is in the
concurrency-hot scope, analysis/config.py): the ring lock guards only the
deque and the bookkeeping scalars; the registry snapshot — the expensive
part, it takes the registry and histogram locks — is taken BEFORE the
ring lock, and listeners run after it is released. ``clock`` is
injectable (tests pin window arithmetic with a fake clock); the sampler's
wait rides the stop Event, so ``stop()`` never waits out a full interval.

The sampler measures itself: ``overhead()`` reports the fraction of wall
time spent inside ``sample_once`` since ``start()`` — the <5% steady-state
pin the SLO bench gate enforces (scripts/bench_serving.py --slo).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Tuple

from .metrics import REGISTRY, MetricsRegistry

# 10 minutes at the 1 Hz default — comfortably past the SLO engine's slow
# window, ~a few MB at serving-stack registry sizes
DEFAULT_INTERVAL_S = 1.0
DEFAULT_CAPACITY = 600


class TimeSeriesRing:
    """Bounded ring of ``(t, typed_snapshot)`` samples with windowed
    queries. One instance per process is the normal shape (the module
    singleton ``RING``); tests build private rings with a fake clock."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 capacity: int = DEFAULT_CAPACITY,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.registry = registry if registry is not None else REGISTRY
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self.clock = clock
        self._ring: deque = deque(maxlen=self.capacity)
        self._listeners: List[Callable] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sample_seconds = 0.0
        self._samples = 0
        self._errors = 0
        self._started_perf: Optional[float] = None

    # -- sampling -----------------------------------------------------------

    def sample_once(self) -> float:
        """Take one snapshot now; returns its timestamp. The sampler thread
        calls this every interval; tests drive it directly with a fake
        clock. Snapshot and listeners run OUTSIDE the ring lock."""
        t0 = time.perf_counter()
        snap = self.registry.typed_snapshot()
        for h in snap["histograms"].values():
            # exemplars are debugging payload (trace links), not trend
            # data — dropping them keeps samples value-only and bounded
            h.pop("exemplars", None)
        t = self.clock()
        cost = time.perf_counter() - t0
        with self._lock:
            self._ring.append((t, snap))
            self._sample_seconds += cost
            self._samples += 1
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(t, snap)
            except Exception:  # graftcheck: disable=G029 (a listener bug must not kill the sampler; the error counter below is the LOUD degrade signal)
                with self._lock:
                    self._errors += 1
                errs = self.registry.counter("timeseries",
                                             "listener_errors")
                errs.increment()
        ov = self.overhead()
        self.registry.set_gauge("timeseries.samples", float(ov["samples"]))
        self.registry.set_gauge("timeseries.sampler.overhead_fraction",
                                ov["fraction"])
        return t

    def _run(self, stop: threading.Event) -> None:
        # Event.wait is the sleep AND the shutdown latch: stop() returns
        # without waiting out an interval (graftcheck G031: the wait is
        # bounded and event-driven, not a spin). The event arrives as an
        # argument so the loop never reads the rebindable field.
        while not stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # graftcheck: disable=G029 (the sampler thread must outlive a transient snapshot error; the error counter is the LOUD degrade signal)
                with self._lock:
                    self._errors += 1
                errs = self.registry.counter("timeseries",
                                             "sampler_errors")
                errs.increment()

    def start(self) -> "TimeSeriesRing":
        """Start the background sampler (idempotent); daemon thread, so it
        never blocks interpreter exit."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            stop = threading.Event()
            self._stop = stop
            if self._started_perf is None:
                self._started_perf = time.perf_counter()
            thread = threading.Thread(target=self._run, args=(stop,),
                                      daemon=True,
                                      name="hivemall-tpu-timeseries")
            self._thread = thread
        thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
            stop = self._stop
        stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)

    def add_listener(self, fn: Callable[[float, dict], None]) -> None:
        """Register ``fn(t, snapshot)`` to run after every sample (outside
        the ring lock). Errors are counted, never raised."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    # -- windowed queries ---------------------------------------------------

    def window(self, seconds: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, dict]]:
        """Samples inside the trailing ``seconds`` (all when None), oldest
        first. ``now`` overrides the clock (deterministic tests)."""
        with self._lock:
            out = list(self._ring)
        if seconds is None:
            return out
        cutoff = (self.clock() if now is None else now) - float(seconds)
        return [s for s in out if s[0] >= cutoff]

    @staticmethod
    def _value(snap: dict, key: str) -> Optional[float]:
        for kind in ("counters", "gauges", "meters"):
            if key in snap[kind]:
                return float(snap[kind][key])
        # histogram scalar fields address as "<name>.count" / "<name>.sum"
        name, _, field = key.rpartition(".")
        h = snap["histograms"].get(name)
        if h is not None and field in ("count", "sum"):
            return float(h[field])
        return None

    def delta(self, key: str, seconds: Optional[float] = None,
              now: Optional[float] = None) -> float:
        """last - first of ``key`` over the window (0.0 when the window
        holds < 2 samples or the key is absent). Meaningful for counters
        and histogram ``.count``/``.sum`` fields."""
        w = self.window(seconds, now=now)
        if len(w) < 2:
            return 0.0
        a = self._value(w[0][1], key)
        b = self._value(w[-1][1], key)
        if a is None or b is None:
            return 0.0
        return b - a

    def rate(self, key: str, seconds: Optional[float] = None,
             now: Optional[float] = None) -> float:
        """delta / actual-window-span, per second (0.0 when the window
        spans no time)."""
        w = self.window(seconds, now=now)
        if len(w) < 2:
            return 0.0
        span = w[-1][0] - w[0][0]
        if span <= 0:
            return 0.0
        a = self._value(w[0][1], key)
        b = self._value(w[-1][1], key)
        if a is None or b is None:
            return 0.0
        return (b - a) / span

    def hist_delta(self, name: str, seconds: Optional[float] = None,
                   now: Optional[float] = None) -> Optional[dict]:
        """Cumulative-bucket delta of histogram ``name`` over the window:
        the observations that happened INSIDE it, in Histogram.snapshot
        shape plus ``span_s``. None when the window holds < 2 samples or
        the histogram never appeared; a histogram born mid-window deltas
        against an implicit zero baseline."""
        w = self.window(seconds, now=now)
        if len(w) < 2:
            return None
        h1 = w[-1][1]["histograms"].get(name)
        if h1 is None:
            return None
        h0 = w[0][1]["histograms"].get(name)
        span = w[-1][0] - w[0][0]
        if h0 is None:
            return {"buckets": [tuple(b) for b in h1["buckets"]],
                    "count": h1["count"], "sum": h1["sum"], "span_s": span}
        return {"buckets": [(ub, c1 - c0)
                            for (ub, c1), (_ub, c0)
                            in zip(h1["buckets"], h0["buckets"])],
                "count": h1["count"] - h0["count"],
                "sum": h1["sum"] - h0["sum"], "span_s": span}

    def frac_over(self, name: str, threshold: float,
                  seconds: Optional[float] = None,
                  now: Optional[float] = None) -> Optional[float]:
        """Fraction of the window's observations ABOVE ``threshold`` —
        the error fraction of a latency/freshness SLO. The cumulative
        count at the threshold is linearly interpolated inside its bucket
        (the histogram_quantile inverse), so a threshold mid-bucket does
        not round a near-miss to a full bucket of misses. None = no
        observations in the window (no evidence either way)."""
        d = self.hist_delta(name, seconds, now=now)
        if d is None or d["count"] <= 0:
            return None
        t = float(threshold)
        prev_cum, lo = 0.0, 0.0
        cum_at = float(d["count"])  # threshold past every finite bound
        for ub, cum in d["buckets"]:
            if t <= ub:
                if ub == float("inf"):
                    # inside the overflow: everything there is "over"
                    cum_at = prev_cum
                elif ub == lo:
                    cum_at = float(cum)
                else:
                    cum_at = prev_cum + (cum - prev_cum) * (t - lo) / (ub - lo)
                break
            prev_cum, lo = float(cum), float(ub)
        frac = 1.0 - cum_at / float(d["count"])
        return min(1.0, max(0.0, frac))

    def quantile(self, name: str, q: float,
                 seconds: Optional[float] = None,
                 now: Optional[float] = None) -> Optional[float]:
        """Windowed quantile of histogram ``name`` over the trailing
        window (linear interpolation inside the holding bucket, +Inf
        clamps to the largest finite bound — Histogram.quantile on the
        window's delta). None = no observations in the window."""
        d = self.hist_delta(name, seconds, now=now)
        if d is None or d["count"] <= 0:
            return None
        bounds = [ub for ub, _ in d["buckets"] if ub != float("inf")]
        if not bounds:
            return None
        rank = q * d["count"]
        prev_cum, lo = 0.0, 0.0
        for ub, cum in d["buckets"]:
            if cum >= rank:
                if ub == float("inf"):
                    return bounds[-1]
                in_bucket = cum - prev_cum
                if in_bucket <= 0:
                    return float(ub)
                return lo + (ub - lo) * (rank - prev_cum) / in_bucket
            prev_cum, lo = float(cum), float(ub)
        return bounds[-1]

    # -- introspection ------------------------------------------------------

    def overhead(self) -> dict:
        """Sampler self-accounting: cumulative seconds spent sampling,
        elapsed wall seconds since start(), and their ratio — the
        steady-state overhead the SLO bench pins under 5%."""
        with self._lock:
            samples, cost = self._samples, self._sample_seconds
            errors, t0 = self._errors, self._started_perf
        elapsed = (time.perf_counter() - t0) if t0 is not None else 0.0
        return {"samples": samples, "sample_seconds": round(cost, 6),
                "elapsed_s": round(elapsed, 6), "errors": errors,
                "fraction": (cost / elapsed) if elapsed > 0 else 0.0}

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def history(self, seconds: Optional[float] = None,
                max_samples: Optional[int] = None) -> dict:
        """The ring as a JSON-shaped block (the flight recorder's
        time-series section, runtime/debug_bundle.py). ``max_samples``
        subsamples evenly, keeping the newest — a bundle stays bounded
        even at high sample rates."""
        w = self.window(seconds)
        if max_samples is not None and len(w) > int(max_samples):
            n = int(max_samples)
            stride = len(w) / float(n)
            w = [w[min(len(w) - 1, int((i + 1) * stride) - 1)]
                 for i in range(n)]
        return {"interval_s": self.interval_s, "capacity": self.capacity,
                "overhead": self.overhead(),
                "samples": [{"t": t, **snap} for t, snap in w]}


# the process-wide ring (not started by default — serve()/bench/daemon
# opt in; tests build private rings)
RING = TimeSeriesRing()
