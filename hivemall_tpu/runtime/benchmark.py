"""Honest device-timing helpers for every throughput benchmark.

Motivation (round 4, measured): on a relay-attached TPU,
`jax.block_until_ready` on an output buffer can return before the producing
execution has actually finished, so the classic
"dispatch N times, block once at the end" loop can measure *enqueue* rate
rather than execution rate — by orders of magnitude (bench_ffm once
reported 0.015 ms for a step whose scatter traffic alone lower-bounds it
at ~0.17 ms of HBM time). The only sync a runtime cannot fake is a value
round-trip: fetching a scalar **computed from the carried state** must
wait for the real result.

`honest_timed_loop` therefore times auto-ranged chunks of work, ending
every chunk with a `device_get` of a probe scalar (and verifying a
monotone step counter when the caller provides one), and includes those
syncs in the measured wall — so the reported rate can never exceed what
the device actually sustained.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Tuple


def honest_timed_loop(
    run_once: Callable[[Any], Any],
    state: Any,
    probe: Callable[[Any], float],
    budget_s: float = 6.0,
    max_chunk: int = 512,
    grow_below_s: float = 0.25,
    expect_probe_delta: Optional[float] = None,
) -> Tuple[int, float, Any]:
    """Run `state = run_once(state)` repeatedly for ~`budget_s` seconds of
    *verified* wall time; return (iterations, elapsed_s, state).

    - `probe(state)` must fetch a scalar derived from the carried state
      (e.g. `lambda s: float(s.step)`); it runs after every chunk and its
      cost is INCLUDED in elapsed, so async-dispatch artifacts cannot
      inflate the rate. Chunks auto-double (up to `max_chunk`) while a
      chunk completes in under `grow_below_s` sec, keeping sync overhead
      under ~1% for fast backends while a slow backend stays at chunk=1.
    - With `expect_probe_delta`, the probe value must advance by
      `expect_probe_delta * chunk` each chunk (e.g. the engine's step
      counter: blocks_per_epoch * batch); a mismatch raises — catching a
      runtime that silently skipped executions. The engine's counters are
      int32, so the loop also returns early before the cumulative count
      could reach 2^31 and wrap (a fast backend can get there inside the
      budget).
    """
    chunk = 1
    iters = 0
    last = probe(state)  # also forces any warmup stragglers to finish
    counter_cap = (float(2 ** 31 - 1) - last) if expect_probe_delta else None
    t0 = time.perf_counter()
    while True:
        if counter_cap is not None and \
                (iters + chunk) * expect_probe_delta >= counter_cap:
            return iters, time.perf_counter() - t0, state
        c0 = time.perf_counter()
        for _ in range(chunk):
            state = run_once(state)
        val = probe(state)
        c1 = time.perf_counter()
        if expect_probe_delta is not None:
            want = last + expect_probe_delta * chunk
            if abs(val - want) > 0.5:
                raise RuntimeError(
                    f"probe counter mismatch: expected {want}, got {val} "
                    f"after {chunk} iteration(s) — executions were dropped?")
        last = val
        iters += chunk
        if c1 - t0 >= budget_s:
            return iters, c1 - t0, state
        if (c1 - c0) < grow_below_s and chunk < max_chunk:
            chunk *= 2
