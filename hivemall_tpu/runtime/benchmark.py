"""Honest device-timing helpers for every throughput benchmark.

Motivation (round 4, measured): on a relay-attached TPU,
`jax.block_until_ready` on an output buffer can return before the producing
execution has actually finished, so the classic
"dispatch N times, block once at the end" loop can measure *enqueue* rate
rather than execution rate — by orders of magnitude (bench_ffm once
reported 0.015 ms for a step whose scatter traffic alone lower-bounds it
at ~0.17 ms of HBM time). The only sync a runtime cannot fake is a value
round-trip: fetching a scalar **computed from the carried state** must
wait for the real result.

`honest_timed_loop` therefore times auto-ranged chunks of work, ending
every chunk with a `device_get` of a probe scalar (and verifying a
monotone step counter when the caller provides one), and includes those
syncs in the measured wall — so the reported rate can never exceed what
the device actually sustained.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Tuple

_PERMS: dict = {}


def make_workload_ids(rng, shape, dims: int):
    """Benchmark feature ids: log-uniform (heavy-tailed) FREQUENCY with
    hash-UNIFORM placement — the north-star workload shape shared by
    bench.py, every scripts/bench_*.py, and diag_scan_perf.py (same id
    distribution as the e2e generator's hashed CTR traffic).

    Two deliberate properties, both measured to matter (round 4):
    - Frequency: zipf(1.3) (rounds 1-3) is TOO head-heavy — 2M draws touch
      so few distinct features that the C anchor's whole working set stays
      cache-resident. Log-uniform over [1, dims) gives a realistic
      distinct-feature count per epoch.
    - Placement: raw samples concentrate hot ids in the table's first
      cache lines — a contiguity gift real murmur-hashed features never
      give. A fixed permutation spreads them uniformly, preserving the
      duplicate multiset (same TPU scatter collisions; TPU measured
      placement-insensitive — diag micro uniform-placed rows in
      PERF_TPU_r04.jsonl)."""
    import numpy as np

    if dims not in _PERMS:
        _PERMS[dims] = np.random.RandomState(12345).permutation(
            dims).astype(np.int32)
    u = rng.random_sample(shape)
    ids = np.exp(u * np.log(float(dims))).astype(np.int64) % dims
    return _PERMS[dims][ids]


def honest_timed_loop(
    run_once: Callable[[Any], Any],
    state: Any,
    probe: Callable[[Any], float],
    budget_s: float = 6.0,
    max_chunk: int = 512,
    grow_below_s: float = 0.25,
    expect_probe_delta: Optional[float] = None,
) -> Tuple[int, float, Any]:
    """Run `state = run_once(state)` repeatedly for ~`budget_s` seconds of
    *verified* wall time; return (iterations, elapsed_s, state).

    - `probe(state)` must fetch a scalar derived from the carried state
      (e.g. `lambda s: float(s.step)`); it runs after every chunk and its
      cost is INCLUDED in elapsed, so async-dispatch artifacts cannot
      inflate the rate. Chunks auto-double (up to `max_chunk`) while a
      chunk completes in under `grow_below_s` sec, keeping sync overhead
      under ~1% for fast backends while a slow backend stays at chunk=1.
    - With `expect_probe_delta`, the probe value must advance by
      `expect_probe_delta * chunk` each chunk (e.g. the engine's step
      counter: blocks_per_epoch * batch); a mismatch raises — catching a
      runtime that silently skipped executions. The engine's counters are
      int32, so the loop also returns early before the cumulative count
      could reach 2^31 and wrap (a fast backend can get there inside the
      budget).
    """
    chunk = 1
    iters = 0
    last = probe(state)  # also forces any warmup stragglers to finish
    counter_cap = (float(2 ** 31 - 1) - last) \
        if (expect_probe_delta is not None and expect_probe_delta > 0) else None
    t0 = time.perf_counter()
    while True:
        if counter_cap is not None and \
                (iters + chunk) * expect_probe_delta >= counter_cap:
            if iters == 0:
                raise RuntimeError(
                    f"probe counter {last} already within one chunk of int32 "
                    "wrap — reset the state before timing")
            return iters, time.perf_counter() - t0, state
        c0 = time.perf_counter()
        for _ in range(chunk):
            state = run_once(state)
        val = probe(state)
        c1 = time.perf_counter()
        if expect_probe_delta is not None:
            want = last + expect_probe_delta * chunk
            if abs(val - want) > 0.5:
                raise RuntimeError(
                    f"probe counter mismatch: expected {want}, got {val} "
                    f"after {chunk} iteration(s) — executions were dropped?")
        last = val
        iters += chunk
        if c1 - t0 >= budget_s:
            return iters, c1 - t0, state
        if (c1 - c0) < grow_below_s and chunk < max_chunk:
            chunk *= 2


def measure_reference_rowloops(idx, val, lab, dims: int, k: int = 5,
                               budget_s: float = 2.0) -> dict:
    """Time the C transliterations of the reference's per-row hot loops
    (native hm_arow_reference_rowloop / hm_fm_reference_rowloop) on the
    given host arrays — the measured vs_baseline anchor denominators shared
    by bench.py and scripts/bench_ctr_e2e.py. Parse/boxing costs are
    excluded (flatters the reference). Returns {} when the native library
    is missing or predates the anchor symbols (a probe call returning None
    — never time no-op calls)."""
    from .. import native

    out: dict = {}
    if not native.available():
        return out
    n = len(lab)
    # ONE closure per family, used for both the probe and the timed loop,
    # so the probe can never validate a different code path than the one
    # being timed
    for name, rowloop in (
        ("arow", lambda i, v, l, s: native.arow_reference_rowloop(
            i, v, l, dims, state=s)),
        ("fm", lambda i, v, l, s: native.fm_reference_rowloop(
            i, v, l, dims, k=k, state=s)),
    ):
        st: dict = {}
        # probe on st itself: detects missing symbols AND warms the model
        # table allocation so it never lands inside the timed window
        if rowloop(idx[:2048], val[:2048], lab[:2048], st) is None:
            continue
        t0 = time.perf_counter()
        done = 0
        while time.perf_counter() - t0 < budget_s:
            rowloop(idx, val, lab, st)
            done += n
        out[f"{name}_rows_per_sec"] = round(
            done / (time.perf_counter() - t0), 1)
    return out
