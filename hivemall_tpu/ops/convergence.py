"""Iteration convergence checking.

Mirrors hivemall.common.ConversionState (ref: core/.../common/ConversionState.java:23-127):
training converges when the relative loss change `(prev - cur) / prev` stays
below `convergence_rate` for TWO consecutive iterations. A loss increase
resets the ready flag. Used by the multi-epoch trainers (FM, MF, epoch-replay
linear learners).

This is host-side control flow between epochs — the per-epoch cumulative loss
is a device scalar pulled once per epoch, so it never blocks the jitted step.
"""

from __future__ import annotations

import math


class ConversionState:
    def __init__(self, conversion_check: bool = True, convergence_rate: float = 0.005):
        self.conversion_check = conversion_check
        self.convergence_rate = convergence_rate
        self.ready_to_finish = False
        self.total_errors = 0.0
        self.curr_losses = 0.0
        self.prev_losses = math.inf
        self.cur_iter = 0

    def incr_loss(self, loss: float) -> None:
        self.curr_losses += float(loss)

    def multiply_loss(self, multi: float) -> None:
        self.curr_losses *= multi

    @property
    def cumulative_loss(self) -> float:
        return self.curr_losses

    @property
    def previous_loss(self) -> float:
        return self.prev_losses

    def is_loss_increased(self) -> bool:
        return self.curr_losses > self.prev_losses

    def is_converged(self, observed_examples: int = 0) -> bool:
        self.cur_iter += 1
        if not self.conversion_check:
            self.prev_losses = self.curr_losses
            self.curr_losses = 0.0
            return False
        if self.curr_losses > self.prev_losses:
            self.prev_losses = self.curr_losses
            self.curr_losses = 0.0
            self.ready_to_finish = False
            return False
        change_rate = (self.prev_losses - self.curr_losses) / self.prev_losses
        if change_rate < self.convergence_rate:
            if self.ready_to_finish:
                return True
            self.ready_to_finish = True
        else:
            self.ready_to_finish = False
        self.prev_losses = self.curr_losses
        self.curr_losses = 0.0
        return False


class OnlineVariance:
    """Welford online mean/variance (ref: common/OnlineVariance.java:24)."""

    def __init__(self) -> None:
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0

    def handle(self, x: float) -> None:
        self.n += 1
        delta = x - self.mean
        self.mean += delta / self.n
        self.m2 += delta * (x - self.mean)

    @property
    def variance(self) -> float:
        return self.m2 / (self.n - 1) if self.n > 1 else 0.0

    @property
    def stddev(self) -> float:
        return math.sqrt(self.variance)
