"""Learning-rate schedules.

Mirrors hivemall.common.EtaEstimator (ref: core/.../common/EtaEstimator.java:31-160):
fixed, simple (eta0 / (1 + t/total)), inverse-scaling (eta0 / t^power_t), and
the bold-driver "adjusting" estimator from Gemulla et al. KDD'11.

Schedules are pure functions of the global step `t` so they trace cleanly
under jit; `t` is carried in the model state. The factory `get_eta` mirrors
the reference's CLI resolution order (EtaEstimator.get, :128-160).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp


@dataclass(frozen=True)
class EtaEstimator:
    kind: str  # fixed | simple | invscaling | adjusting
    eta0: float = 0.1
    total_steps: float = 10000.0
    power_t: float = 0.1

    def eta(self, t):
        """eta(t) with t the 1-based example counter. Traceable under jit."""
        t = jnp.asarray(t, dtype=jnp.float32)
        if self.kind == "fixed":
            return jnp.full_like(t, self.eta0)
        if self.kind == "simple":
            # literals pinned to the schedule dtype so x64/np-scalar mixing
            # cannot promote the eta feeding every weight update
            # (graftcheck G003; bf16 storage policy in models/base.py)
            eta0 = jnp.asarray(self.eta0, t.dtype)
            return jnp.where(
                t > self.total_steps,
                eta0 / 2,
                eta0 / (1 + t / self.total_steps),
            )
        if self.kind == "invscaling":
            return self.eta0 / jnp.power(jnp.maximum(t, 1.0), self.power_t)
        if self.kind == "adjusting":
            # Bold driver adjusts from the loss trajectory at iteration
            # boundaries (host-side, see models/base.py); eta(t) is flat within
            # an iteration (ref: EtaEstimator.java:99-122).
            return jnp.full_like(t, self.eta0)
        raise ValueError(f"unknown eta kind {self.kind}")


def fixed(eta: float) -> EtaEstimator:
    return EtaEstimator("fixed", eta0=eta)


def simple(eta0: float, total_steps: int) -> EtaEstimator:
    return EtaEstimator("simple", eta0=eta0, total_steps=float(total_steps))


def invscaling(eta0: float, power_t: float) -> EtaEstimator:
    return EtaEstimator("invscaling", eta0=eta0, power_t=power_t)


def get_eta(cl=None, default_eta0: float = 0.1) -> EtaEstimator:
    """Resolve schedule from parsed options, mirroring EtaEstimator.get
    (ref: EtaEstimator.java:128-160). `cl` is a utils.options.CommandLine."""
    if cl is None:
        return invscaling(default_eta0, 0.1)
    if cl.has("boldDriver"):
        eta = cl.get_float("eta", 0.3)
        return EtaEstimator("adjusting", eta0=eta)
    if cl.has("eta"):
        return fixed(cl.get_float("eta"))
    eta0 = cl.get_float("eta0", default_eta0)
    if cl.has("t"):
        return simple(eta0, cl.get_int("t"))
    power_t = cl.get_float("power_t", 0.1)
    return invscaling(eta0, power_t)
