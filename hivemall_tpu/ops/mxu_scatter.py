"""Sorted-window MXU gather/scatter: random model-table access as matmuls.

The engine's single-chip floor is XLA's scalar gather/scatter engine: the
verified v5e cost model (PERF.md, diag micros) puts one 524288-id gather at
~13 ms (~38M ids/s) and one scatter-add at ~7 ms (~70M updates/s) — both
latency-bound serial loops ~20x off the HBM roofline, and together they ARE
the AROW/FM step time (reference hot loop being beaten:
core/src/main/java/hivemall/model/DenseModel.java:193-201 — get/set by
feature index). This module re-expresses both ops as MXU work:

1. `lax.sort` the block's flat feature ids ONCE, carrying payloads through
   the sort network (positions for gather un-sorting, update columns for
   scatter) — bitonic sort is data-parallel vector ops, so payloads ride
   ~free where a permutation gather would hit the same 38M/s scalar engine.
2. The [E, c] table is viewed as [R, 128] lane tiles (c power-of-two entry
   columns interleave within a tile, 128//c entries per row). A chunk of C
   consecutive *sorted* ids spans a short contiguous row range (ids are
   hash-uniform over E — see runtime/benchmark.make_workload_ids), so each
   chunk touches one `dynamic_slice` window of W rows.
3. Within a chunk, gather = one-hot row matrix [C, W] @ window [W, 128]
   (MXU) followed by a cheap lane select (VPU); scatter-add = the transpose
   matmul [W, C] @ lane-spread updates [C, 128] accumulated into the window
   via `dynamic_update_slice`. A `lax.scan` threads the table through the
   chunks, so overlapping windows read-modify-write sequentially and
   duplicate ids accumulate inside the matmul — f32 sums, same value set as
   XLA's scatter-add up to addition order (which a duplicate scatter leaves
   unspecified anyway).

Total MXU volume is N * W * 128 MACs per pass — ~1-3 ms at the bench shape
(N=2^19, W=512) against the ~20 ms the scalar engine charges, and every
stage is dense vector/matrix work.

Correctness is unconditional: ids that land outside their chunk's window
(possible only for adversarially sparse/clustered ids — never for hashed
features) are counted, and a `lax.cond` routes JUST those through the
ordinary XLA gather/scatter as a residual pass, so the fast path's window
parameter is a performance knob, not a semantics knob. Out-of-range ids
follow the engine protocol: gather fills 0.0, scatter drops.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

LANES = 128

# The MXU's fast path multiplies in bf16; under the default precision XLA
# would round the gathered/scattered f32 TABLE values to 8 mantissa bits on
# TPU (CPU ignores precision — the parity suite would never see it).
# HIGHEST keeps every one-hot product exact in f32 (the default here).
# HIGH (TPU: 3-pass bf16) halves the MXU passes at <= 1-ulp f32 error —
# the one-hot operand is exact in bf16, so only the table side splits; the
# diag mxu_ group A/Bs both so the hardware window prices the trade.
PRECISION = jax.lax.Precision.HIGHEST


def _resolve_precision(precision):
    if precision is None:
        return PRECISION
    if isinstance(precision, str):
        return {"high": jax.lax.Precision.HIGH,
                "highest": jax.lax.Precision.HIGHEST}[precision]
    return precision


class WindowPlan(NamedTuple):
    """One block's sorted-id structure, shared by gathers and scatters.

    Invalid ids — negative OR >= n_entries — are mapped to the sentinel
    `n_entries` (gather fills 0.0, scatter drops). NOTE this deliberately
    differs from `.at[ids].get/add`, which wrap negative indices Python-style:
    the engine's padding protocol only ever produces ids in [0, dims] (parsers
    floor-mod, pad lanes use dims), so wrapping would just turn a caller bug
    into silent corruption of entry E-1."""

    sid: jnp.ndarray        # [Np] int32 sorted ids; invalid ids -> E (tail)
    spos: jnp.ndarray       # [Np] int32 original position of each sorted slot
    n: int                  # original (unpadded) id count
    n_entries: int          # E: table entry count the plan was built for
    chunk: int              # C: sorted ids per window


def _pad_to(x: jnp.ndarray, m: int, fill) -> jnp.ndarray:
    n = x.shape[0]
    if n % m == 0:
        return x
    return jnp.concatenate(
        [x, jnp.full((m - n % m,) + x.shape[1:], fill, x.dtype)])


def make_plan(ids_flat: jnp.ndarray, n_entries: int,
              *, chunk: int = 1024) -> WindowPlan:
    """Sort the block's flat ids once. `ids_flat` [N] int32; anything outside
    [0, n_entries) is mapped to the sentinel `n_entries` (sorts to the tail,
    gathers 0, scatters dropped)."""
    ids_flat = jnp.asarray(ids_flat, jnp.int32).reshape(-1)
    n = ids_flat.shape[0]
    ids_m = jnp.where((ids_flat >= 0) & (ids_flat < n_entries), ids_flat,
                      n_entries)
    pos = jnp.arange(n, dtype=jnp.int32)
    sid, spos = jax.lax.sort((ids_m, pos), num_keys=1)
    sid = _pad_to(sid, chunk, n_entries)
    if spos.shape[0] != sid.shape[0]:
        # pad positions with DISTINCT values >= n so the un-sorting sort in
        # gather() sends pad slots to the tail instead of colliding with
        # real position 0
        extra = jnp.arange(n, sid.shape[0] - spos.shape[0] + n,
                           dtype=jnp.int32)
        spos = jnp.concatenate([spos, extra])
    return WindowPlan(sid=sid, spos=spos, n=n, n_entries=n_entries,
                      chunk=chunk)


def _auto_window(plan: WindowPlan, rows: int) -> int:
    """Window rows per chunk: 4x the expected span of `chunk` consecutive
    sorted ids (hash-uniform ids make span concentration tight; anything
    past the window goes through the exact residual pass), power-of-two,
    floored at 128 rows so the dynamic-slice stays tile-aligned and the
    matmul K-dim stays MXU-worthy."""
    expected = max(1, rows * plan.chunk // max(1, plan.sid.shape[0]))
    w = 128
    while w < 4 * expected:
        w *= 2
    return min(w, rows)


def pad_cols(n: int) -> int:
    """Smallest power-of-two column count >= n — THE lane-protocol helper:
    tables fed to gather/scatter_add must have power-of-two columns so
    entries tile the 128-lane rows evenly (_table_geometry)."""
    c = 1
    while c < n:
        c *= 2
    return c


def _table_geometry(n_entries: int, cols: int, window_rows: int):
    if cols & (cols - 1) or cols > LANES:
        raise ValueError(f"cols must be a power of two <= {LANES}: {cols}")
    ipr = LANES // cols                      # entries per 128-lane row
    rows = max((n_entries + ipr - 1) // ipr, window_rows)
    return ipr, rows


def _tiles_of(table: jnp.ndarray, rows: int) -> jnp.ndarray:
    flat = table.reshape(-1)
    want = rows * LANES
    if flat.shape[0] < want:
        flat = jnp.concatenate(
            [flat, jnp.zeros((want - flat.shape[0],), flat.dtype)])
    return flat.reshape(rows, LANES)


def _chunk_meta(plan: WindowPlan, ipr: int, rows: int, w: int):
    """Per-chunk window starts + per-id window-relative geometry."""
    c = plan.chunk
    sid = plan.sid
    srow = jnp.minimum(sid, plan.n_entries - 1) // ipr  # valid ids only matter
    n_chunks = sid.shape[0] // c
    starts = jnp.minimum(srow.reshape(n_chunks, c)[:, 0], rows - w)
    rel = srow.reshape(n_chunks, c) - starts[:, None]           # [nc, C]
    valid = (sid < plan.n_entries).reshape(n_chunks, c)
    in_win = valid & (rel >= 0) & (rel < w)
    group = (jnp.minimum(sid, plan.n_entries - 1) % ipr).reshape(n_chunks, c)
    return starts, rel, group, valid, in_win


def gather(table: jnp.ndarray, plan: WindowPlan,
           window_rows: int | None = None,
           precision=None) -> jnp.ndarray:
    """`table.at[ids].get(mode="fill", fill_value=0.0)` over the plan's ids,
    returned in ORIGINAL id order. `table` is [E] or [E, c] (c a power of two
    <= 128); result is [N] or [N, c] f32."""
    squeeze = table.ndim == 1
    t2 = table[:, None] if squeeze else table
    e, c = t2.shape
    if e != plan.n_entries:
        raise ValueError(f"plan built for E={plan.n_entries}, table has {e}")
    prec = _resolve_precision(precision)
    ipr, rows = _table_geometry(e, c, 128)
    w = window_rows or _auto_window(plan, rows)
    ipr, rows = _table_geometry(e, c, w)
    tiles = _tiles_of(t2.astype(jnp.float32), rows)
    starts, rel, group, valid, in_win = _chunk_meta(plan, ipr, rows, w)
    cch = plan.chunk
    iota_w = jnp.arange(w, dtype=jnp.int32)
    iota_g = jnp.arange(ipr, dtype=jnp.int32)

    def body(_, xs):
        start, rel_c, grp_c, inw_c = xs
        win = jax.lax.dynamic_slice(tiles, (start, 0), (w, LANES))
        oh_row = ((rel_c[:, None] == iota_w[None, :]) & inw_c[:, None]) \
            .astype(jnp.float32)                                  # [C, W]
        picked = jnp.matmul(oh_row, win, precision=prec)     # [C, 128]
        oh_g = (grp_c[:, None] == iota_g[None, :]).astype(jnp.float32)
        vals = jnp.einsum("cg,cgk->ck", oh_g,
                          picked.reshape(cch, ipr, c),
                          precision=prec)                    # [C, c]
        return None, vals

    _, vals = jax.lax.scan(body, None, (starts, rel, group, in_win))
    vals = vals.reshape(-1, c)                                    # sorted order

    # residual pass: ids whose row fell outside their chunk's window
    res = valid & ~in_win
    any_res = jnp.any(res)

    def with_residual(v):
        rid = jnp.where(res.reshape(-1), plan.sid, e)
        rv = t2.astype(jnp.float32).at[rid].get(mode="fill", fill_value=0.0)
        return v + rv

    vals = jax.lax.cond(any_res, with_residual, lambda v: v, vals)

    # un-sort: one more payload-carrying sort, keyed by original position
    outs = jax.lax.sort((plan.spos,) + tuple(vals[:, j] for j in range(c)),
                        num_keys=1)
    out = jnp.stack(outs[1:], axis=-1)[: plan.n]
    return out[:, 0] if squeeze else out


def scatter_add(table: jnp.ndarray, ids_flat: jnp.ndarray,
                upd: jnp.ndarray, plan: WindowPlan,
                window_rows: int | None = None,
                precision=None) -> jnp.ndarray:
    """`table.at[ids].add(upd, mode="drop")` with the update columns carried
    through one id-keyed sort and accumulated window-by-window on the MXU.
    `table` [E] or [E, c]; `upd` [N] or [N, kl] with kl <= c (original id
    order; rides the sort; missing columns scatter nothing — the padded-lane
    protocol of scatter_rows_flat). Returns the updated table in its original
    shape/dtype. Sum order within a duplicated id differs from XLA's scatter
    (both are unspecified); values match to f32 tolerance."""
    squeeze = table.ndim == 1
    t2 = table[:, None] if squeeze else table
    u2 = upd[:, None] if upd.ndim == 1 else upd
    e, c = t2.shape
    if e != plan.n_entries:
        raise ValueError(f"plan built for E={plan.n_entries}, table has {e}")
    prec = _resolve_precision(precision)
    ipr, rows = _table_geometry(e, c, 128)
    w = window_rows or _auto_window(plan, rows)
    ipr, rows = _table_geometry(e, c, w)
    tiles = _tiles_of(t2.astype(jnp.float32), rows)

    # sort the updates into id order (stable sort == plan's order; equal keys
    # commute under addition anyway). Only the kl real columns ride the sort;
    # pad columns (kl < c) materialize as zeros afterwards.
    kl = u2.shape[-1]
    ids_flat = jnp.asarray(ids_flat, jnp.int32).reshape(-1)
    ids_m = jnp.where((ids_flat >= 0) & (ids_flat < e), ids_flat, e)
    sorted_ops = jax.lax.sort(
        (ids_m,) + tuple(u2[:, j].astype(jnp.float32) for j in range(kl)),
        num_keys=1)
    su = jnp.stack(sorted_ops[1:], axis=-1)                        # [N, kl]
    if kl < c:
        su = jnp.concatenate(
            [su, jnp.zeros(su.shape[:-1] + (c - kl,), su.dtype)], axis=-1)
    su = _pad_to(su, plan.chunk, 0.0)

    starts, rel, group, valid, in_win = _chunk_meta(plan, ipr, rows, w)
    cch = plan.chunk
    iota_w = jnp.arange(w, dtype=jnp.int32)
    iota_g = jnp.arange(ipr, dtype=jnp.int32)
    su3 = su.reshape(-1, cch, c)

    def body(tiles, xs):
        start, rel_c, grp_c, inw_c, u_c = xs
        win = jax.lax.dynamic_slice(tiles, (start, 0), (w, LANES))
        oh_row = ((rel_c[:, None] == iota_w[None, :]) & inw_c[:, None]) \
            .astype(jnp.float32)                                  # [C, W]
        oh_g = (grp_c[:, None] == iota_g[None, :]).astype(jnp.float32)
        spread = jnp.einsum("cg,ck->cgk", oh_g, u_c,
                            precision=prec).reshape(cch, LANES)
        win = win + jnp.matmul(oh_row.T, spread, precision=prec)
        return jax.lax.dynamic_update_slice(tiles, win, (start, 0)), None

    tiles, _ = jax.lax.scan(body, tiles,
                            (starts, rel, group, in_win, su3))

    res = valid & ~in_win
    any_res = jnp.any(res)

    def with_residual(t):
        rid = jnp.where(res.reshape(-1), plan.sid, e)
        flat = t.reshape(-1)
        # scatter the residual (sorted-order) updates through the flat view
        base = jnp.minimum(rid, e - 1) * c
        lanes = jnp.arange(c, dtype=jnp.int32)
        f = jnp.where(rid[:, None] < e, base[:, None] + lanes[None, :],
                      t.size)
        return flat.at[f].add(su, mode="drop").reshape(t.shape)

    tiles = jax.lax.cond(any_res, with_residual, lambda t: t, tiles)
    out = tiles.reshape(-1)[: e * c].reshape(e, c).astype(table.dtype)
    return out[:, 0] if squeeze else out
