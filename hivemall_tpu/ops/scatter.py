"""Duplicate-free scatter: sort -> segment-reduce -> unique-index scatter.

The engine's hot write is `table.at[idx].add(upd)` with HEAVILY duplicated
indices (hashed CTR ids are zipf-like: a 16384x32 block has ~524k update
lanes over far fewer unique features). XLA lowers a duplicate-index
scatter-add conservatively (updates must be applied one-at-a-time to
preserve determinism-agnostic semantics), which on TPU serializes the op;
round-4 relay measurements put the fully-synced AROW step at ~34 ms —
consistent with serial scatter, and ~100x the step's HBM traffic bound.

This module turns one duplicated scatter into:

    order = argsort(idx)            # parallel bitonic sort
    seg   = prefix-sum of boundaries
    sums  = segment_sum(upd[order]) # parallel tree reduction
    table.at[rep].add(sums, unique_indices=True, indices_are_sorted=True)

— every stage is data-parallel, and the final scatter's unique+sorted
promise lets XLA emit the vectorized path. The plan (sort + segments) is
built ONCE per block and reused by every table the step writes (weights,
covars, optimizer slots, touched, delta counts), so the sort cost is
amortized over all of them; per-feature update counts (the reference's
FloatAccumulator denominator, RegressionBaseUDTF.java:281-295) fall out of
the same segment reduction for free — replacing the zeros+scatter+gather
counts pattern of the direct path.

Semantics: identical sums up to float reduction order (a duplicate-index
scatter-add has no defined application order either); exactness tests pin
integer counts and tolerance-pin float tables.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class DedupPlan(NamedTuple):
    """Reusable sort/segment structure for one block of scatter indices."""

    order: jnp.ndarray  # [N] int32 — permutation sorting the flat indices
    seg: jnp.ndarray  # [N] int32 — segment id of each sorted element
    rep: jnp.ndarray  # [N] — ascending slot->feature index; empty slots get
    # distinct out-of-range values so `mode="drop"` discards them and the
    # unique/sorted promises stay true


def make_dedup_plan(idx_flat: jnp.ndarray, dims: int) -> DedupPlan:
    """`idx_flat` [N] int32; out-of-range ids (the engine's padding protocol
    uses idx == dims) sort to the tail and land in dropped slots."""
    n = idx_flat.shape[0]
    order = jnp.argsort(idx_flat)
    si = idx_flat[order]
    head = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), si[1:] != si[:-1]])
    seg = jnp.cumsum(head.astype(jnp.int32)) - 1
    rep = jax.ops.segment_min(si, seg, num_segments=n)
    # segment_min fills empty segments with the dtype max; replace with
    # distinct ascending out-of-range ids (keeps `indices_are_sorted` and
    # `unique_indices` promises honest even among dropped entries)
    empty = rep >= jnp.asarray(jnp.iinfo(si.dtype).max, si.dtype)
    rep = jnp.where(empty, dims + jnp.arange(n, dtype=si.dtype), rep)
    return DedupPlan(order=order, seg=seg, rep=rep)


def segment_totals(plan: DedupPlan, upd_flat: jnp.ndarray) -> jnp.ndarray:
    """Per-slot sums of `upd_flat` ([N] or [N, k]) under the plan."""
    return jax.ops.segment_sum(upd_flat[plan.order], plan.seg,
                               num_segments=plan.order.shape[0])


def dedup_scatter_add(table: jnp.ndarray, plan: DedupPlan,
                      upd_flat: jnp.ndarray,
                      denom: jnp.ndarray | None = None) -> jnp.ndarray:
    """`table.at[idx].add(upd)` with duplicates pre-reduced; `denom` [N]
    (per-slot counts) divides the sums first — the mini-batch averaged
    application."""
    sums = segment_totals(plan, upd_flat)
    if denom is not None:
        d = jnp.maximum(denom, 1.0)
        sums = sums / (d[:, None] if sums.ndim == 2 else d)
    return table.at[plan.rep].add(sums.astype(table.dtype), mode="drop",
                                  unique_indices=True,
                                  indices_are_sorted=True)


def dedup_counts(plan: DedupPlan, fired_flat: jnp.ndarray) -> jnp.ndarray:
    """Per-slot update counts (float) — the FloatAccumulator denominator."""
    return segment_totals(plan, fired_flat)


def dedup_touch_max(table: jnp.ndarray, plan: DedupPlan,
                    fired_flat: jnp.ndarray) -> jnp.ndarray:
    """`touched.at[idx].max(fired)` via the plan (int8 table)."""
    hits = segment_totals(plan, fired_flat)
    return table.at[plan.rep].max((hits > 0).astype(table.dtype),
                                  mode="drop", unique_indices=True,
                                  indices_are_sorted=True)


def dedup_scatter_set_uniform(table: jnp.ndarray, plan: DedupPlan,
                              val_flat: jnp.ndarray,
                              keep_flat: jnp.ndarray) -> jnp.ndarray:
    """`table.at[idx].set(val)` where duplicate lanes of a feature carry the
    SAME value (the engine's derive_w path: values are a pure function of
    the post-update slot tables, so duplicates agree — gather-after-scatter
    determinism). `keep_flat` [N] bool keeps the old table value where no
    lane fired."""
    vs = val_flat[plan.order]
    ks = keep_flat[plan.order].astype(vs.dtype)
    # all lanes of a slot agree, so max over the segment = the value; lanes
    # with keep=0 (no update) are excluded by pushing them to -inf
    neg = jnp.asarray(-jnp.inf, vs.dtype)
    picked = jax.ops.segment_max(jnp.where(ks > 0, vs, neg), plan.seg,
                                 num_segments=plan.order.shape[0])
    # NB: segment_totals permutes its input itself — pass the UNSORTED mask
    fired = segment_totals(plan, keep_flat.astype(vs.dtype)) > 0
    old = table.at[plan.rep].get(mode="fill", fill_value=0.0)
    out = jnp.where(fired, picked, old)
    return table.at[plan.rep].set(out.astype(table.dtype), mode="drop",
                                  unique_indices=True,
                                  indices_are_sorted=True)


def scatter_rows_flat(table: jnp.ndarray, keys: jnp.ndarray,
                      upd: jnp.ndarray,
                      _flat_limit: int = 2**31) -> jnp.ndarray:
    """Row scatter-add via the flat scalar view.

    A [N,k]-row scatter into [E,k] measured ~2x slower on v5e than the same
    updates scattered as scalars into the flat [E*k] view (diag micro2
    scatter_v5_flat 36.9ms vs scatter_v5_rows 71.2ms per 512k rows; 8-lane
    padding does NOT rescue the row form — v8pad 69.1ms). `upd`'s last dim
    may carry fewer lanes than the table (k_logical <= k, e.g. FM's padded
    V): only those lanes are scattered, so pad lanes stay untouched. Drop
    semantics are preserved: pad keys (>= E) flatten to >= E*k.

    Falls back to the row form when E*k would overflow the int32 flat-index
    space (the flat product wraps negative and mode="drop" would silently
    discard every update). `_flat_limit` exists so tests can exercise the
    fallback branch at small table sizes.
    """
    e, k = table.shape
    kl = upd.shape[-1]
    if e * k < _flat_limit:
        fidx = keys[..., None] * k + jnp.arange(kl)
        return table.reshape(-1).at[fidx].add(upd, mode="drop").reshape(e, k)
    if kl != k:
        upd = jnp.concatenate(
            [upd, jnp.zeros(upd.shape[:-1] + (k - kl,), upd.dtype)], axis=-1)
    return table.at[keys].add(upd, mode="drop")
