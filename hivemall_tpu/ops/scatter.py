"""Duplicate-free scatter: sort -> segment-reduce -> unique-index scatter.

The engine's hot write is `table.at[idx].add(upd)` with HEAVILY duplicated
indices (hashed CTR ids are zipf-like: a 16384x32 block has ~524k update
lanes over far fewer unique features). XLA lowers a duplicate-index
scatter-add conservatively (updates must be applied one-at-a-time to
preserve determinism-agnostic semantics), which on TPU serializes the op;
round-4 relay measurements put the fully-synced AROW step at ~34 ms —
consistent with serial scatter, and ~100x the step's HBM traffic bound.

This module turns one duplicated scatter into:

    order = argsort(idx)            # parallel bitonic sort
    seg   = prefix-sum of boundaries
    sums  = segment_sum(upd[order]) # parallel tree reduction
    table.at[rep].add(sums, unique_indices=True, indices_are_sorted=True)

— every stage is data-parallel, and the final scatter's unique+sorted
promise lets XLA emit the vectorized path. The plan (sort + segments) is
built ONCE per block and reused by every table the step writes (weights,
covars, optimizer slots, touched, delta counts), so the sort cost is
amortized over all of them; per-feature update counts (the reference's
FloatAccumulator denominator, RegressionBaseUDTF.java:281-295) fall out of
the same segment reduction for free — replacing the zeros+scatter+gather
counts pattern of the direct path.

Semantics: identical sums up to float reduction order (a duplicate-index
scatter-add has no defined application order either); exactness tests pin
integer counts and tolerance-pin float tables.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class DedupPlan(NamedTuple):
    """Reusable sort/segment structure for one block of scatter indices."""

    order: jnp.ndarray  # [N] int32 — permutation sorting the flat indices
    seg: jnp.ndarray  # [N] int32 — segment id of each sorted element
    rep: jnp.ndarray  # [N] — ascending slot->feature index; empty slots get
    # distinct out-of-range values so `mode="drop"` discards them and the
    # unique/sorted promises stay true


def make_dedup_plan(idx_flat: jnp.ndarray, dims: int) -> DedupPlan:
    """`idx_flat` [N] int32; out-of-range ids (the engine's padding protocol
    uses idx == dims) sort to the tail and land in dropped slots."""
    n = idx_flat.shape[0]
    order = jnp.argsort(idx_flat)
    si = idx_flat[order]
    head = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), si[1:] != si[:-1]])
    seg = jnp.cumsum(head.astype(jnp.int32)) - 1
    rep = jax.ops.segment_min(si, seg, num_segments=n)
    # segment_min fills empty segments with the dtype max; replace with
    # distinct ascending out-of-range ids (keeps `indices_are_sorted` and
    # `unique_indices` promises honest even among dropped entries)
    empty = rep >= jnp.asarray(jnp.iinfo(si.dtype).max, si.dtype)
    rep = jnp.where(empty, dims + jnp.arange(n, dtype=si.dtype), rep)
    return DedupPlan(order=order, seg=seg, rep=rep)


def segment_totals(plan: DedupPlan, upd_flat: jnp.ndarray) -> jnp.ndarray:
    """Per-slot sums of `upd_flat` ([N] or [N, k]) under the plan."""
    return jax.ops.segment_sum(upd_flat[plan.order], plan.seg,
                               num_segments=plan.order.shape[0])


def dedup_scatter_add(table: jnp.ndarray, plan: DedupPlan,
                      upd_flat: jnp.ndarray,
                      denom: jnp.ndarray | None = None) -> jnp.ndarray:
    """`table.at[idx].add(upd)` with duplicates pre-reduced; `denom` [N]
    (per-slot counts) divides the sums first — the mini-batch averaged
    application."""
    sums = segment_totals(plan, upd_flat)
    if denom is not None:
        d = jnp.maximum(denom, 1.0)
        sums = sums / (d[:, None] if sums.ndim == 2 else d)
    return table.at[plan.rep].add(sums.astype(table.dtype), mode="drop",
                                  unique_indices=True,
                                  indices_are_sorted=True)


def dedup_counts(plan: DedupPlan, fired_flat: jnp.ndarray) -> jnp.ndarray:
    """Per-slot update counts (float) — the FloatAccumulator denominator."""
    return segment_totals(plan, fired_flat)


def dedup_touch_max(table: jnp.ndarray, plan: DedupPlan,
                    fired_flat: jnp.ndarray) -> jnp.ndarray:
    """`touched.at[idx].max(fired)` via the plan (int8 table)."""
    hits = segment_totals(plan, fired_flat)
    return table.at[plan.rep].max((hits > 0).astype(table.dtype),
                                  mode="drop", unique_indices=True,
                                  indices_are_sorted=True)


def dedup_scatter_set_uniform(table: jnp.ndarray, plan: DedupPlan,
                              val_flat: jnp.ndarray,
                              keep_flat: jnp.ndarray) -> jnp.ndarray:
    """`table.at[idx].set(val)` where duplicate lanes of a feature carry the
    SAME value (the engine's derive_w path: values are a pure function of
    the post-update slot tables, so duplicates agree — gather-after-scatter
    determinism). `keep_flat` [N] bool keeps the old table value where no
    lane fired."""
    vs = val_flat[plan.order]
    ks = keep_flat[plan.order].astype(vs.dtype)
    # all lanes of a slot agree, so max over the segment = the value; lanes
    # with keep=0 (no update) are excluded by pushing them to -inf
    neg = jnp.asarray(-jnp.inf, vs.dtype)
    picked = jax.ops.segment_max(jnp.where(ks > 0, vs, neg), plan.seg,
                                 num_segments=plan.order.shape[0])
    # NB: segment_totals permutes its input itself — pass the UNSORTED mask
    fired = segment_totals(plan, keep_flat.astype(vs.dtype)) > 0
    old = table.at[plan.rep].get(mode="fill", fill_value=0.0)
    out = jnp.where(fired, picked, old)
    return table.at[plan.rep].set(out.astype(table.dtype), mode="drop",
                                  unique_indices=True,
                                  indices_are_sorted=True)


# --------------------------------------------------------------------------
# Staged plans: the sort moved to staging time, the scatter shrunk to the
# unique slots.
#
# The jit-built DedupPlan above still pays two costs that XLA:CPU cannot
# hide: the argsort runs INSIDE the step (measured 193 ms per 512k-lane
# block on this host — XLA's comparator sort, vs 50 ms for numpy's radix
# argsort on the same data), and the final scatter still carries one lane
# per UPDATE (scatter is the one primitive XLA:CPU executes element-at-a-
# time, ~15 M elt/s here, while gathers/takes run 400-800 M elt/s). Both
# are structural, not tuning: the sort is a pure function of the block's
# feature ids, and the scatter only needs one lane per UNIQUE feature.
#
# A StagedDedupPlan therefore moves both out of the hot path:
#
# - built ON THE HOST (numpy) at block-staging time, next to the existing
#   pack_rows staging — it rides into HBM with the block and is replayed
#   every epoch for free (the kernels/linear_scan.py chunking discipline:
#   host-side shaping once, device replay after);
# - the slot axis is COMPACT: [U] unique features (U bucketed so jit
#   shapes stay bounded), so every table write scatters U lanes instead
#   of B*K — on zipf-like CTR ids that is a 2-3x cut before the
#   unique+sorted promises even apply;
# - segment totals come from ONE f32 cumsum over the sorted lanes plus
#   two boundary gathers (cumsum runs at ~200 M elt/s here vs 22 M for
#   segment_sum, which XLA lowers back to a scatter). The cumsum is
#   chunk-local (<= B*K lanes), so its prefix error stays bounded; the
#   0/1 update-count column is EXACT in f32 for any chunk under 2^24
#   lanes (all partial sums are representable integers).
# --------------------------------------------------------------------------


class StagedDedupPlan(NamedTuple):
    """Host-built sort/segment structure for one chunk of B rows.

    All arrays are plain numpy at build time; they become device arrays
    when staged. `N = B*K` flat lanes, `U` = bucketed unique-slot count.
    """

    order: "jnp.ndarray"  # [N] int32 — permutation sorting the flat ids
    lane_seg: "jnp.ndarray"  # [N] int32 — slot id of each ORIGINAL lane
    rep: "jnp.ndarray"  # [U] int32 — ascending unique feature ids; pad
    # slots get distinct out-of-range ids (drop-mode + honest promises)
    starts: "jnp.ndarray"  # [U] int32 — inclusive start in sorted order
    ends: "jnp.ndarray"  # [U] int32 — exclusive end (== start on pads)


def plan_slot_bucket(n_unique: int, min_slots: int = 256) -> int:
    """Round a unique-slot count up to 8 buckets per octave (<= 12.5%
    scatter-lane waste, bounded distinct jit shapes — the pad_to_bucket
    discipline, finer-grained because scatter lanes are the cost)."""
    n = max(int(n_unique), 1)
    if n <= min_slots:
        return min_slots
    step = max(1 << (max(n.bit_length() - 1, 3) - 3), min_slots // 8)
    return -(-n // step) * step


def build_staged_plan(idx_flat, dims: int, slots: int | None = None
                      ) -> StagedDedupPlan:
    """Numpy plan builder (staging time, host side).

    `idx_flat` [N] — a chunk's flat feature ids; the padding protocol's
    out-of-range ids (== dims) sort to the tail and become dropped slots.
    `slots` pins the U bucket (callers stacking several chunks into one
    scan pass the max bucket over the chunks).
    """
    import numpy as np

    flat = np.asarray(idx_flat, dtype=np.int64).reshape(-1)
    n = flat.shape[0]
    order = np.argsort(flat, kind="stable")
    si = flat[order]
    head = np.empty(n, np.bool_)
    head[0] = True
    np.not_equal(si[1:], si[:-1], out=head[1:])
    lane_seg = np.empty(n, np.int32)
    lane_seg[order] = (np.cumsum(head) - 1).astype(np.int32)
    # every segment gets a slot, INCLUDING the pad-id segments (ids >=
    # dims): their reps are naturally out-of-range so the table ops drop
    # them, but their lanes still broadcast a well-defined fill value and
    # their counts never leak into a live feature's denominator
    uniq = si[head]
    n_seg = uniq.shape[0]
    ends_all = np.append(np.flatnonzero(head[1:]) + 1, n).astype(np.int32)
    u = slots if slots is not None else plan_slot_bucket(n_seg)
    if n_seg > u:
        raise ValueError(f"plan bucket {u} < {n_seg} unique ids")
    # unused tail slots take distinct ascending out-of-range ids past any
    # real segment's, keeping the unique_indices/indices_are_sorted
    # promises honest among the drops
    pad_base = max(int(uniq[-1]) + 1 if n_seg else dims, dims)
    rep = np.concatenate([
        uniq.astype(np.int64),
        pad_base + np.arange(u - n_seg, dtype=np.int64)])
    starts = np.zeros(u, np.int32)
    ends = np.zeros(u, np.int32)
    starts[1:n_seg] = ends_all[: n_seg - 1]
    ends[:n_seg] = ends_all
    starts[n_seg:] = n
    ends[n_seg:] = n
    return StagedDedupPlan(order=order.astype(np.int32), lane_seg=lane_seg,
                           rep=rep.astype(np.int32), starts=starts,
                           ends=ends)


# --------------------------------------------------------------------------
# Plan ctypes ABI (FROZEN, v1) — the contract for plans crossing into
# native/hivemall_native.cpp (hm_batch_apply_block, the -native_apply
# backend):
#
#   field     dtype  shape            meaning
#   order     int32  [N] / [nb, N]    permutation sorting the flat lane ids
#   lane_seg  int32  [N] / [nb, N]    slot id of each ORIGINAL lane
#   rep       int32  [U] / [nb, U]    ascending unique feature ids; pads
#                                     carry distinct ids >= dims (dropped)
#   starts    int32  [U] / [nb, U]    inclusive start in sorted lane order
#   ends      int32  [U] / [nb, U]    exclusive end (== start on pads)
#
# All arrays C-contiguous host numpy; N = chunk_rows * width. The stacked
# ([nb, ...]) form is BlockPlans.main — chunk c lives at flat offset c*N /
# c*U, which is what C contiguity guarantees. Changing any dtype, field
# order, pad convention, or the ascending-rep promise is an ABI break:
# bump PLAN_ABI_VERSION and the .so together (scripts/build_native.sh
# --if-stale re-probes the symbol so a stale library can't run silently).
# --------------------------------------------------------------------------

PLAN_ABI_VERSION = 1


def plan_abi_arrays(plan: StagedDedupPlan, stacked: bool = False):
    """Validate `plan` against the frozen ctypes ABI above and return its
    arrays as host numpy in field order. Raises TypeError/ValueError on any
    dtype, contiguity, or rank violation — a plan that came back from
    device (jnp) or was built with the wrong dtype must fail HERE, not
    corrupt memory inside the native call."""
    import numpy as np

    ndim = 2 if stacked else 1
    out = []
    for f in StagedDedupPlan._fields:
        a = getattr(plan, f)
        if not isinstance(a, np.ndarray):
            raise TypeError(
                f"plan.{f} is {type(a).__name__}, not host numpy — the "
                "native ABI takes staging-time plans (device plans have "
                "no stable buffer address)")
        if a.dtype != np.int32:
            raise TypeError(f"plan.{f} dtype {a.dtype} != int32 (ABI v"
                            f"{PLAN_ABI_VERSION})")
        if a.ndim != ndim:
            raise ValueError(f"plan.{f} rank {a.ndim} != {ndim} "
                             f"({'stacked' if stacked else 'single-chunk'} "
                             "form)")
        if not a.flags["C_CONTIGUOUS"]:
            raise ValueError(f"plan.{f} is not C-contiguous (ABI v"
                             f"{PLAN_ABI_VERSION})")
        out.append(a)
    return tuple(out)


def pad_plan(plan: StagedDedupPlan, slots: int, dims: int
             ) -> StagedDedupPlan:
    """Widen a host-built plan to a larger U bucket (chunks scanned
    together must share one shape). Extra slots are empty drops: distinct
    ascending out-of-range reps, start == end == N."""
    import numpy as np

    u0 = plan.rep.shape[0]
    if slots == u0:
        return plan
    if slots < u0:
        raise ValueError(f"cannot shrink plan bucket {u0} -> {slots}")
    n = plan.order.shape[0]
    extra = slots - u0
    pad_base = max(int(plan.rep[-1]) + 1, dims)
    rep = np.concatenate([
        np.asarray(plan.rep, np.int64),
        pad_base + np.arange(extra, dtype=np.int64)]).astype(np.int32)
    fill = np.full(extra, n, np.int32)
    return StagedDedupPlan(
        order=plan.order, lane_seg=plan.lane_seg, rep=rep,
        starts=np.concatenate([plan.starts, fill]),
        ends=np.concatenate([plan.ends, fill]))


def staged_gather(table: jnp.ndarray, plan: StagedDedupPlan,
                  fill: float = 0.0) -> jnp.ndarray:
    """[U] — each unique feature's row read ONCE (ascending ids, so the
    table walk is sequential; pad slots read the fill)."""
    return table.at[plan.rep].get(mode="fill", fill_value=fill)


def broadcast_lanes(uniq_vals: jnp.ndarray,
                    plan: StagedDedupPlan) -> jnp.ndarray:
    """[N] — unique-slot values fanned back out to the original lanes."""
    return uniq_vals[plan.lane_seg]


def staged_segment_totals(plan: StagedDedupPlan,
                          cols: jnp.ndarray) -> jnp.ndarray:
    """Per-slot sums of `cols` ([N] or [N, k] lane-ordered, f32) — one
    permute + one chunk-local cumsum + two boundary gathers; no scatter."""
    csort = cols[plan.order]
    zero = jnp.zeros((1,) + csort.shape[1:], csort.dtype)
    csum = jnp.concatenate([zero, jnp.cumsum(csort, axis=0)])
    return csum[plan.ends] - csum[plan.starts]


def staged_scatter_add(table: jnp.ndarray, plan: StagedDedupPlan,
                       sums: jnp.ndarray,
                       denom: jnp.ndarray | None = None) -> jnp.ndarray:
    """Apply per-slot sums [U] (pre-reduced, optionally count-averaged):
    the only scatter left, and it is unique+sorted+compact."""
    if denom is not None:
        sums = sums / jnp.maximum(denom, 1.0)
    return table.at[plan.rep].add(sums.astype(table.dtype), mode="drop",
                                  unique_indices=True,
                                  indices_are_sorted=True)


def staged_scatter_set(table: jnp.ndarray, plan: StagedDedupPlan,
                       vals: jnp.ndarray,
                       keep: jnp.ndarray) -> jnp.ndarray:
    """`table.at[rep].set(vals)` where `keep` [U] (bool) falls back to the
    slot's current value — the derive_w write, computed per UNIQUE slot so
    no gather-after-scatter round trip is needed."""
    old = staged_gather(table, plan)
    out = jnp.where(keep, vals.astype(table.dtype), old)
    return table.at[plan.rep].set(out, mode="drop", unique_indices=True,
                                  indices_are_sorted=True)


def staged_touch_max(table: jnp.ndarray, plan: StagedDedupPlan,
                     counts: jnp.ndarray) -> jnp.ndarray:
    """`touched.at[idx].max(fired)` — int8, U lanes."""
    return table.at[plan.rep].max((counts > 0).astype(table.dtype),
                                  mode="drop", unique_indices=True,
                                  indices_are_sorted=True)


def scatter_rows_flat(table: jnp.ndarray, keys: jnp.ndarray,
                      upd: jnp.ndarray,
                      _flat_limit: int = 2**31) -> jnp.ndarray:
    """Row scatter-add via the flat scalar view.

    A [N,k]-row scatter into [E,k] measured ~2x slower on v5e than the same
    updates scattered as scalars into the flat [E*k] view (diag micro2
    scatter_v5_flat 36.9ms vs scatter_v5_rows 71.2ms per 512k rows; 8-lane
    padding does NOT rescue the row form — v8pad 69.1ms). `upd`'s last dim
    may carry fewer lanes than the table (k_logical <= k, e.g. FM's padded
    V): only those lanes are scattered, so pad lanes stay untouched. Drop
    semantics are preserved: pad keys (>= E) flatten to >= E*k.

    Falls back to the row form when E*k would overflow the int32 flat-index
    space (the flat product wraps negative and mode="drop" would silently
    discard every update). `_flat_limit` exists so tests can exercise the
    fallback branch at small table sizes.
    """
    e, k = table.shape
    kl = upd.shape[-1]
    if e * k < _flat_limit:
        fidx = keys[..., None] * k + jnp.arange(kl)
        return table.reshape(-1).at[fidx].add(upd, mode="drop").reshape(e, k)
    if kl != k:
        upd = jnp.concatenate(
            [upd, jnp.zeros(upd.shape[:-1] + (k - kl,), upd.dtype)], axis=-1)
    return table.at[keys].add(upd, mode="drop")
