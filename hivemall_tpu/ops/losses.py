"""Loss functions as pure jnp element-wise ops.

Mirrors hivemall.common.LossFunctions (ref: core/.../common/LossFunctions.java:26-379):
SquaredLoss, LogLoss, HingeLoss, SquaredHingeLoss, QuantileLoss,
EpsilonInsensitiveLoss — each with `loss(p, y)` and `dloss(p, y)`.

All functions are vectorized over arrays (the reference computes them per-row;
on TPU they fuse into the batched update kernels). Binary losses take y in
{-1, +1}.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax.numpy as jnp


class LossFunction(NamedTuple):
    name: str
    loss: Callable
    dloss: Callable
    is_binary: bool


def _pin(value, like):
    """Pin a literal to the operand's dtype (graftcheck G003): under
    jax_enable_x64 or numpy-scalar mixing a bare float literal can promote
    the whole update expression, silently upcasting the bf16-above-2^24
    storage policy of models/base.py. Dtype-matched, the constant follows
    the data — identical numerics under the default config. Non-float
    operands (int labels through the public loss API) pin to the default
    float dtype instead, matching weak-literal promotion."""
    dt = jnp.result_type(like)
    if not jnp.issubdtype(dt, jnp.floating):
        dt = jnp.result_type(float)
    return jnp.asarray(value, dt)


def _squared_loss(p, y):
    z = p - y
    return _pin(0.5, z) * z * z


def _squared_dloss(p, y):
    return p - y


def _log_loss(p, y):
    # log(1 + exp(-y*p)), numerically stable (ref: LossFunctions.java LogLoss.loss,
    # which branches at |z| > 18; softplus(-z) is the branch-free equivalent).
    z = y * p
    return jnp.logaddexp(0.0, -z)


def _log_dloss(p, y):
    z = y * p
    return -y / (jnp.exp(z) + _pin(1.0, z))


def _hinge_loss(p, y, threshold=1.0):
    return jnp.maximum(0.0, threshold - y * p)


def _hinge_dloss(p, y, threshold=1.0):
    return jnp.where(threshold - y * p > 0.0, -y, 0.0)


def _squared_hinge_loss(p, y):
    d = jnp.maximum(0.0, _pin(1.0, p) - y * p)
    return d * d


def _squared_hinge_dloss(p, y):
    d = _pin(1.0, p) - y * p
    return jnp.where(d > 0.0, _pin(-2.0, d) * d * y, 0.0)


def _quantile_loss(p, y, tau=0.5):
    e = y - p
    return jnp.where(e > 0.0, tau * e, -(_pin(1.0, e) - tau) * e)


def _quantile_dloss(p, y, tau=0.5):
    e = y - p
    return jnp.where(e == 0.0, 0.0, jnp.where(e > 0.0, -tau, _pin(1.0, e) - tau))


def _eps_insensitive_loss(p, y, epsilon=0.1):
    return jnp.maximum(0.0, jnp.abs(y - p) - epsilon)


def _eps_insensitive_dloss(p, y, epsilon=0.1):
    return jnp.where(y - p > epsilon, -1.0, jnp.where(p - y > epsilon, 1.0, 0.0))


SquaredLoss = LossFunction("SquaredLoss", _squared_loss, _squared_dloss, False)
LogLoss = LossFunction("LogLoss", _log_loss, _log_dloss, True)
HingeLoss = LossFunction("HingeLoss", _hinge_loss, _hinge_dloss, True)
SquaredHingeLoss = LossFunction("SquaredHingeLoss", _squared_hinge_loss, _squared_hinge_dloss, True)
QuantileLoss = LossFunction("QuantileLoss", _quantile_loss, _quantile_dloss, False)
EpsilonInsensitiveLoss = LossFunction(
    "EpsilonInsensitiveLoss", _eps_insensitive_loss, _eps_insensitive_dloss, False
)

_REGISTRY = {
    f.name.lower(): f
    for f in (SquaredLoss, LogLoss, HingeLoss, SquaredHingeLoss, QuantileLoss,
              EpsilonInsensitiveLoss)
}


def get_loss_function(name: str) -> LossFunction:
    """By-name lookup (ref: LossFunctions.getLossFunction, LossFunctions.java:33-46)."""
    f = _REGISTRY.get(name.lower())
    if f is None:
        raise ValueError(f"Unsupported loss type: {name}")
    return f


def logistic_loss(target, predicted):
    """logisticLoss(target, predicted) for probability targets
    (ref: LossFunctions.java:381-392)."""
    one = _pin(1.0, predicted)
    return jnp.where(
        predicted > -100.0,
        target - one / (one + jnp.exp(-predicted)),
        target,
    )
