"""Data-parallel FM training with collective mixing.

The north-star workload trains AROW *and* FM across workers (BASELINE.json).
For FM the mixable state is (w0, w[D], V[D,k]): replicas train on their data
shards and mix every k blocks —

- w: delta-weighted average over per-feature update counts (every FM row
  updates all its features, so counts = touch counts), like PartialAverage;
- V: averaged with the same per-feature weights broadcast over factors;
- w0: plain mean (every row updates it);
- AdaGrad-style slots are NOT mixed (device-local, like the reference where
  optimizer state never crossed the MIX wire — only weights did,
  ref: MixMessage carries weight/covar only, mix/MixMessage.java:26-95).

Mix cadence is MixConfig.mix_every, uniform with MixTrainer: the default (1)
mixes after every block; pass mix_every=k to train k blocks locally between
collectives (the syncThreshold analog, MixServerHandler.java:142-148).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models.fm import FMHyper, FMState, init_fm_state, make_fm_step
from .mesh import WORKER_AXIS, make_mesh
from .mix import MixConfig, grouped_mix_scan, replicate_state
from ..runtime.jax_compat import pcast, shard_map


class FMMixTrainer:
    def __init__(self, hyper: FMHyper, dims: int, mesh: Optional[Mesh] = None,
                 mode: str = "minibatch", config: MixConfig = MixConfig(),
                 mini_batch_average: bool = True):
        self.hyper = hyper
        self.dims = dims
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_dev = self.mesh.devices.size
        self.config = config
        self.axis = config.axis_name

        # mini_batch_average passes through to the local step (sum/count
        # averaged application vs raw sums — see make_fm_step), same knob the
        # sharded trainers expose
        local_step = make_fm_step(hyper, mode,
                                  mini_batch_average=mini_batch_average)
        # make_fm_step returns a jitted fn; jitted fns compose fine inside
        # shard_map (they inline at trace time)

        def mix(st: FMState) -> FMState:
            counts = st.touched.astype(jnp.float32)
            total = jax.lax.psum(counts, self.axis)
            w = jnp.where(total > 0,
                          jax.lax.psum(st.w * counts, self.axis)
                          / jnp.maximum(total, 1.0), st.w)
            v = jnp.where(total[:, None] > 0,
                          jax.lax.psum(st.v * counts[:, None], self.axis)
                          / jnp.maximum(total, 1.0)[:, None], st.v)
            # pcast re-tags the device-invariant pmean result as mesh-varying
            # so the grouped-scan carry type stays consistent
            w0 = pcast(jax.lax.pmean(st.w0, self.axis), self.axis, to="varying")
            return st.replace(w=w, v=v, w0=w0)

        def device_step(state: FMState, indices, values, labels, va):
            st = jax.tree.map(lambda x: x[0], state)

            def body(s, blk):
                s, loss = local_step(s, *blk)
                return s, loss

            st, loss = grouped_mix_scan(
                body, mix, st, (indices[0], values[0], labels[0], va[0]),
                config.mix_every)
            return jax.tree.map(lambda x: x[None], st), jax.lax.psum(
                loss, self.axis)

        spec_state = jax.tree.map(lambda _: P(self.axis),
                                  jax.eval_shape(lambda: init_fm_state(dims, hyper)))
        self._step = jax.jit(
            shard_map(
                device_step,
                mesh=self.mesh,
                in_specs=(spec_state, P(self.axis), P(self.axis), P(self.axis),
                          P(self.axis)),
                out_specs=(spec_state, P()),
            ),
            donate_argnums=(0,),
        )

    def init(self) -> FMState:
        return replicate_state(init_fm_state(self.dims, self.hyper),
                               self.n_dev, self.mesh, axis=self.axis)

    def step(self, state: FMState, indices, values, labels, va=None):
        """indices/values/labels: [n_dev, k, B, ...]."""
        if va is None:
            va = np.zeros(labels.shape, np.float32)
        return self._step(state, indices, values, labels, va)

    def final_state(self, state: FMState) -> FMState:
        """Collapse the device axis: w0/w/v are identical across replicas
        after the trailing mix; touched unions; the adaptive-regularization
        lambdas (data-derived scalars, ref: FactorizationMachineModel
        updateLambda* :253-300) average across replicas."""
        host = jax.device_get(state)
        merged = jax.tree.map(lambda x: x[0], host)
        step_all = np.asarray(host.step)
        return merged.replace(
            touched=np.max(np.asarray(host.touched), axis=0),
            lambda_w0=np.asarray(host.lambda_w0).mean(axis=0),
            lambda_w=np.asarray(host.lambda_w).mean(axis=0),
            lambda_v=np.asarray(host.lambda_v).mean(axis=0),
            step=step_all.sum().astype(step_all.dtype),
        )
