"""Feature-dimension sharded TRAINING — model-parallel linear learners.

The reference trains against a parameter store sharded across MIX servers by
feature hash: every update routes to `hash(feature) mod numNodes`
(ref: mix/client/MixRequestRouter.java:56-60), so no single node holds the
whole 2^24-dim model. TPU-native, the same capability is the model pytree
sharded along the feature dimension over the mesh: each device holds a [D/n]
stripe of weights / covars / optimizer slots, and a training step is

    gather:  each device gathers its stripe's hits (lanes it does not own are
             masked to zero),
    reduce:  per-row score / squared-norm / variance partials psum over ICI —
             after the psum every device knows the full-row scalars,
    update:  the rule's closed form runs lane-wise on every device with the
             *global* scalars, and deltas scatter into the local stripe only.

The step body is the ordinary engine step built with
`make_train_fn(..., feature_shard=(axis, stripe))` (core/engine.py) — one
copy of the update-application logic, sharded or not. Parity vs the
single-device engine is exact up to psum summation order
(tests/test_sharded_train.py).

Arbitrary dims: when dims is not divisible by the stripe count the tables
pad up to `stripe * n_shards`. The padding slots are safe by the engine's
own protocol: data pad lanes carry value 0, every linear rule's lane deltas
are proportional to the lane value (so they vanish), and the only writes that
can land in a padding slot are the touched/delta-count marks — slots past
`dims` that no predict or export ever reads (final states slice back to
[:dims]).

Two trainers:
- `ShardedTrainer` — 1-D mesh, ONE model too big for one chip's HBM (e.g.
  covariance + optimizer slots at 2^24+ dims); blocks replicated.
- `Sharded2DTrainer` — 2-D (replicas x stripes) mesh: each replica holds a
  feature-sharded model and trains its own data shard; every `mix_every`
  blocks the replicas delta-weighted-average along the replica axis. This is
  the reference's actual production topology: N mapper clients training
  concurrently against M feature-sharded MIX servers
  (ref: MixRequestRouter.java:56-60 + MixServerHandler.java:118-158,
  MixServerTest.java:122-151 five concurrent clients).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..core.engine import DELTA_SLOT, Rule, make_train_fn
from ..core.state import LinearState, init_linear_state
from ..core.striping import restripe
from .mesh import SHARD_AXIS, WORKER_AXIS, make_mesh, make_mesh_2d
from .mix import (MixConfig, add_replica_base, collapse_linear_replicas,
                  grouped_mix_scan, make_linear_mix, replicate_state,
                  split_replica_blocks, strip_replica_base)
from .sharded import stripe_score
from ..runtime.jax_compat import shard_map
from ..runtime.tracing import TRACER


def _resolve_1d_mesh(mesh: Optional[Mesh], who: str):
    """Shared striping scaffold: validate/construct the 1-D mesh and return
    (mesh, axis_name, n_devices)."""
    mesh = mesh if mesh is not None else make_mesh()
    if len(mesh.axis_names) != 1:
        raise ValueError(f"{who} needs a 1-D mesh, got axes {mesh.axis_names}")
    return mesh, mesh.axis_names[0], mesh.devices.size


def _born_sharded(init_fn, mesh: Mesh, specs):
    """jit the state constructor with out_shardings so the full tables are
    never materialized on one device (sharded trainers exist because they
    wouldn't fit)."""
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
    return jax.jit(init_fn, out_shardings=shardings)()


def _unpad_state(host, dims: int, dims_padded: int, specs, axis_name: str):
    """Slice the dims padding back off every leaf — shared final_state tail
    of all sharded trainers. The padded axis is read from each leaf's
    PartitionSpec (the position named `axis_name`), never guessed from
    sizes, so a non-feature axis that coincidentally equals dims_padded can
    not be mis-sliced."""
    if dims == dims_padded:
        return host

    def unpad(x, spec):
        if getattr(x, "ndim", 0) >= 1:
            for ax, name in enumerate(tuple(spec)):
                if name == axis_name:
                    sl = [slice(None)] * x.ndim
                    sl[ax] = slice(0, dims)
                    return x[tuple(sl)]
        return x

    return jax.tree.map(unpad, host, specs)


def _align_linear_host(host: LinearState, dims: int, use_covariance: bool,
                       slot_names: tuple, global_names: tuple) -> LinearState:
    """Normalize a checkpointed host LinearState to THIS trainer's field
    structure before re-striping: slots/globals the rule expects but the
    checkpoint lacks fill with zeros (e.g. the 2-D trainer's mix delta
    counter resuming from a plain sharded checkpoint); extras drop; a
    covariance learner resuming a covariance-free checkpoint starts its
    covariance at the init value 1.0. This is what makes resume
    cross-family: any collapsed linear checkpoint seeds any linear
    trainer."""
    host = jax.device_get(host)
    slots = dict(host.slots or {})
    covars = host.covars
    if use_covariance and covars is None:
        covars = np.ones(dims, np.asarray(host.weights).dtype)
    elif not use_covariance:
        covars = None
    return host.replace(
        covars=covars,
        slots={name: np.asarray(slots[name]) if name in slots
               else np.zeros(dims, np.float32) for name in slot_names},
        globals={name: np.asarray((host.globals or {}).get(name, 0.0),
                                  np.float32) for name in global_names},
    )


def _pad_initial(arr, dims_padded, fill=0.0):
    """Pad a user-provided [dims] warm-start array up to the sharded table
    size. Weights pad with 0; covariances pad with 1.0 (their init value) —
    the argminKLD mix reads 1/cov on every slot, so a zero-padded covariance
    would put inf/NaN in the padding lanes."""
    arr = np.asarray(arr)
    if arr.shape[0] == dims_padded:
        return arr
    return np.pad(arr, (0, dims_padded - arr.shape[0]),
                  constant_values=fill)


class ShardedTrainer:
    """Train a single feature-sharded model across the mesh.

    The state returned by `init()` / threaded through `step()` is a
    padded-dims LinearState whose [D] leaves carry a NamedSharding along the
    feature dim — each device materializes only its [D/n] stripe in HBM.
    Blocks are replicated (every device sees every row; the model, not the
    data, is what doesn't fit).
    """

    def __init__(self, rule: Rule, hyper: dict, dims: int,
                 mesh: Optional[Mesh] = None, mode: str = "minibatch",
                 mini_batch_average: bool = True, dtype=None):
        self.rule = rule
        self.hyper = hyper
        self.dims = dims
        self.mesh, self.axis, n = _resolve_1d_mesh(mesh, "ShardedTrainer")
        self.stripe = -(-dims // n)  # ceil: arbitrary dims pad up
        self.dims_padded = self.stripe * n
        # SpaceEfficientDenseModel analog, same policy as models/base.py
        # fit_linear: above the reference's default 2^24 dims, tables store
        # bf16 (ref: LearnerBaseUDTF.java:172-175 switches to half-float
        # there); pass dtype=jnp.float32 for the -disable_halffloat analog
        if dtype is None:
            dtype = jnp.bfloat16 if dims > (1 << 24) else jnp.float32
        self.dtype = dtype

        body_fn = make_train_fn(rule, hyper, mode=mode,
                                mini_batch_average=mini_batch_average,
                                feature_shard=(self.axis, self.stripe))
        state_shape = jax.eval_shape(self._init_one)
        # [D] leaves stripe along the feature dim; scalars replicate
        specs = jax.tree.map(
            lambda leaf: P(self.axis) if leaf.ndim == 1 else P(), state_shape)
        self._specs = specs
        self._step = jax.jit(
            shard_map(
                body_fn,
                mesh=self.mesh,
                in_specs=(specs, P(), P(), P()),
                out_specs=(specs, P()),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )

    def _init_one(self, **kwargs) -> LinearState:
        kwargs.setdefault("dtype", self.dtype)
        return init_linear_state(
            self.dims_padded,
            use_covariance=self.rule.use_covariance,
            slot_names=tuple(self.rule.slot_names),
            global_names=self.rule.global_names,
            **kwargs,
        )

    def init(self, from_state: Optional[LinearState] = None,
             **kwargs) -> LinearState:
        """Initial state with [D] leaves placed feature-sharded on the mesh —
        each device allocates only its stripe. kwargs pass through to
        init_linear_state (initial_weights/initial_covars = -loadmodel warm
        start, ref: LearnerBaseUDTF.java:215-333); [dims] arrays pad up to
        the sharded table size.

        ``from_state`` is the elastic-resume path: a COLLAPSED host
        LinearState (a final_state() / checkpoint load) re-stripes onto
        THIS mesh through core.striping.restripe — unpad at the old grid,
        re-pad at this mesh's ``stripe * n``, place with NamedSharding —
        so a run checkpointed under N devices resumes under M≠N with the
        full optimizer state (slots, step, Welford globals) intact."""
        if from_state is not None:
            if kwargs:
                raise ValueError("pass either from_state or init kwargs")
            host = _align_linear_host(from_state, self.dims,
                                      self.rule.use_covariance,
                                      tuple(self.rule.slot_names),
                                      tuple(self.rule.global_names))
            return restripe(host, self._specs, self.mesh, self.axis,
                            self.dims, self.dims_padded,
                            fills={"covars": 1.0})
        if not kwargs:
            return _born_sharded(self._init_one, self.mesh, self._specs)
        for key, fill in (("initial_weights", 0.0), ("initial_covars", 1.0)):
            if kwargs.get(key) is not None:
                kwargs[key] = _pad_initial(kwargs[key], self.dims_padded, fill)
        state = self._init_one(**kwargs)
        return jax.tree.map(
            lambda leaf, spec: jax.device_put(
                leaf, NamedSharding(self.mesh, spec)), state, self._specs)

    def step(self, state: LinearState, indices, values, labels):
        """One sharded train step. indices/values: [B, K]; labels: [B]
        (replicated to every device — the model is what's sharded). The
        dispatch runs under a ``train.compiled_step`` span: inside a
        driver's ``tracing.step_span`` it becomes the per-step timeline's
        compiled-step stage (data-prep and sync are the caller's stages —
        see runtime/tracing.py)."""
        with TRACER.span("train.compiled_step",
                         args={"trainer": "sharded_1d"}):
            return self._step(state, indices, values, labels)

    def final_state(self, state: LinearState) -> LinearState:
        """Host-side copy with the padding sliced back off — a plain [dims]
        model for export / warm start / init_linear_state round trips."""
        with TRACER.span("train.sync", args={"trainer": "sharded_1d"}):
            host = jax.device_get(state)
        return _unpad_state(host, self.dims,
                            self.dims_padded, self._specs, self.axis)

    def make_predict(self):
        """Jitted scoring that consumes the TRAINED sharded state directly —
        same mesh, same stripe placement, same stripe_score body as
        parallel/sharded.make_sharded_predict, so a model trained sharded
        serves sharded with no re-placement step."""
        fn = shard_map(
            stripe_score(self.axis, self.stripe),
            mesh=self.mesh,
            in_specs=(P(self.axis), P(), P()),
            out_specs=P(),
        )
        jfn = jax.jit(fn)

        def predict(state: LinearState, indices, values):
            return jfn(state.weights, indices, values)

        return predict


class FMShardedTrainer:
    """Feature-dim sharded FM training — the V table is the framework's
    largest model state ([2^24, k] + optimizer does not fit one chip), so w
    and V stripe [D/S] / [D/S, k] across the mesh exactly like the linear
    ShardedTrainer: per row, each device gathers its owned lanes, the three
    prediction partials (linear, sumVfX, sumV2X2) psum over ICI, and lane
    updates scatter locally (models/fm.py make_fm_step feature_shard).
    Blocks replicate (the model, not the data, is what doesn't fit).
    Arbitrary dims pad up to stripe * n_devices."""

    def __init__(self, hyper, dims: int, mesh: Optional[Mesh] = None,
                 mode: str = "minibatch", mini_batch_average: bool = True):
        from ..models.fm import FMHyper, init_fm_state, make_fm_step

        assert isinstance(hyper, FMHyper)
        self.hyper = hyper
        self.dims = dims
        self.mesh, self.axis, n = _resolve_1d_mesh(mesh, "FMShardedTrainer")
        self.stripe = -(-dims // n)
        self.dims_padded = self.stripe * n
        self._init_fn = lambda: init_fm_state(self.dims_padded, hyper)

        body = make_fm_step(hyper, mode, mini_batch_average=mini_batch_average,
                            feature_shard=(self.axis, self.stripe))
        state_shape = jax.eval_shape(self._init_fn)
        dp = self.dims_padded
        specs = jax.tree.map(
            lambda leaf: P(*((self.axis,) + (None,) * (leaf.ndim - 1)))
            if leaf.ndim >= 1 and leaf.shape[0] == dp else P(), state_shape)
        self._specs = specs
        self._step = jax.jit(
            shard_map(
                body,
                mesh=self.mesh,
                in_specs=(specs, P(), P(), P(), P()),
                out_specs=(specs, P()),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )

    def init(self, from_state=None):
        """Default: born sharded (fresh V draw at the padded shape). With
        ``from_state`` — a collapsed host FMState from final_state() or an
        elastic checkpoint — every table re-stripes onto THIS mesh
        (core.striping.restripe): w/touched unpad+re-pad along dim 0, the
        [D, k] V table re-pads its row axis (pad rows are never gathered —
        no data id reaches a slot past dims — so zero-fill is exact), and
        scalars replicate. A 4-device run resumes on 2 or 8."""
        if from_state is None:
            return _born_sharded(self._init_fn, self.mesh, self._specs)
        return restripe(from_state, self._specs, self.mesh, self.axis,
                        self.dims, self.dims_padded)

    def step(self, state, indices, values, labels, va=None):
        """indices/values: [B, K]; labels: [B] (replicated)."""
        if va is None:
            # np.shape reads the .shape attribute — no device->host copy of
            # the labels block on the per-step path (graftcheck G002)
            va = np.zeros(np.shape(labels), np.float32)
        with TRACER.span("train.compiled_step",
                         args={"trainer": "fm_sharded"}):
            return self._step(state, indices, values, labels, va)

    def final_state(self, state):
        """Host-side copy with the padding sliced back off."""
        with TRACER.span("train.sync", args={"trainer": "fm_sharded"}):
            host = jax.device_get(state)
        return _unpad_state(host, self.dims,
                            self.dims_padded, self._specs, self.axis)

    def make_predict(self):
        """Serve the trained sharded state directly: the SAME
        sharded_gather_predict body the train step uses (models/fm.py), so
        train-time and serve-time predictions cannot drift."""
        from ..models.fm import sharded_gather_predict

        stripe, axis = self.stripe, self.axis

        def local_scores(w, v, w0, idx, val):
            _, _, _, _, p, _ = sharded_gather_predict(
                w, v, w0, idx, val, axis, stripe)
            return p

        fn = shard_map(
            local_scores,
            mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis, None), P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        jfn = jax.jit(fn)

        def predict(state, indices, values):
            return jfn(state.w, state.v, state.w0, indices, values)

        return predict


class FFMShardedTrainer:
    """Feature-dim sharded FFM training: the linear tables ([num_features])
    and the hashed pairwise V tables ([v_dims, k] + gg) stripe across the
    mesh with independent stripe sizes. A row's [K, K, k] pairwise block is
    reconstructed on every device with one psum of the owner-gathered
    entries (models/ffm.py make_ffm_step feature_shard), updates scatter
    back owned entries only, and keys hash with the ORIGINAL v_dims so the
    sharded model computes the same function as the unsharded one. Supports
    row_chunk tiling on top (the two compose: the psum moves [C, K, K, k]
    per chunk). Blocks replicate; both tables pad to their stripe grids.

    `init(from_state=...)` seeds from an (unsharded) host FFMState — the
    parity/warm-start path; the default init draws V ~ N(0, sigma) at the
    padded shape (same distribution as unsharded, different draw)."""

    def __init__(self, hyper, mesh: Optional[Mesh] = None,
                 mode: str = "minibatch", row_chunk: Optional[int] = None):
        from ..models.ffm import FFMHyper, FFMState, make_ffm_step

        assert isinstance(hyper, FFMHyper)
        self.hyper = hyper
        self.mesh, self.axis, n = _resolve_1d_mesh(mesh, "FFMShardedTrainer")
        self.stripe_w = -(-hyper.num_features // n)
        self.stripe_v = -(-hyper.v_dims // n)
        self.nf_padded = self.stripe_w * n
        self.dv_padded = self.stripe_v * n

        def init_one() -> FFMState:
            key = jax.random.PRNGKey(hyper.seed)
            return FFMState(
                w0=jnp.zeros(()),
                w=jnp.zeros((self.nf_padded,)),
                z=jnp.zeros((self.nf_padded,)),
                n=jnp.zeros((self.nf_padded,)),
                v=jax.random.normal(key, (self.dv_padded, hyper.factors))
                * hyper.sigma,
                v_gg=jnp.zeros((self.dv_padded,)),
                touched=jnp.zeros((self.nf_padded,), jnp.int8),
                step=jnp.zeros((), jnp.int32),
            )

        self._init_fn = init_one
        body = make_ffm_step(hyper, mode, row_chunk=row_chunk,
                             feature_shard=(self.axis, self.stripe_w,
                                            self.stripe_v))
        state_shape = jax.eval_shape(init_one)
        striped = {self.nf_padded, self.dv_padded}
        specs = jax.tree.map(
            lambda leaf: P(*((self.axis,) + (None,) * (leaf.ndim - 1)))
            if leaf.ndim >= 1 and leaf.shape[0] in striped else P(),
            state_shape)
        self._specs = specs
        self._step = jax.jit(
            shard_map(
                body,
                mesh=self.mesh,
                in_specs=(specs, P(), P(), P(), P()),
                out_specs=(specs, P()),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )

    def init(self, from_state=None):
        if from_state is None:
            return _born_sharded(self._init_fn, self.mesh, self._specs)
        host = jax.device_get(from_state)
        nf, dv = self.hyper.num_features, self.hyper.v_dims
        padded = host.replace(
            w=_pad_initial(np.asarray(host.w), self.nf_padded),
            z=_pad_initial(np.asarray(host.z), self.nf_padded),
            n=_pad_initial(np.asarray(host.n), self.nf_padded),
            v=np.pad(np.asarray(host.v),
                     ((0, self.dv_padded - dv), (0, 0))),
            v_gg=_pad_initial(np.asarray(host.v_gg), self.dv_padded),
            touched=np.pad(np.asarray(host.touched),
                           (0, self.nf_padded - nf)),
        )
        return jax.tree.map(
            lambda leaf, spec: jax.device_put(
                leaf, NamedSharding(self.mesh, spec)), padded, self._specs)

    def step(self, state, indices, values, fields, labels):
        """indices/values/fields: [B, K]; labels: [B] (replicated)."""
        with TRACER.span("train.compiled_step",
                         args={"trainer": "ffm_sharded"}):
            return self._step(state, indices, values, fields, labels)

    def make_predict(self):
        """Serve the trained sharded state directly — the SAME
        sharded_ffm_gather body the train step uses, vmapped over the
        batch, so serving never materializes the full V table."""
        from ..models.ffm import sharded_ffm_gather

        hyper, axis = self.hyper, self.axis
        sw, sv = self.stripe_w, self.stripe_v

        def local_scores(st, idx, val, fld):
            def one(i, v, f):
                p, *_ = sharded_ffm_gather(st, i, v, f, hyper, axis, sw, sv)
                return p

            return jax.vmap(one)(idx, val, fld)

        fn = shard_map(
            local_scores,
            mesh=self.mesh,
            in_specs=(self._specs, P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        jfn = jax.jit(fn)

        def predict(state, indices, values, fields):
            return jfn(state, indices, values, fields)

        return predict

    def final_state(self, state):
        """Host-side copy with both paddings sliced back off. FFM carries
        TWO independently padded table families (linear at num_features, V
        at v_dims), so the unpad is field-wise rather than the shared
        spec-driven helper (which assumes one padded extent)."""
        with TRACER.span("train.sync", args={"trainer": "ffm_sharded"}):
            host = jax.device_get(state)
        nf, dv = self.hyper.num_features, self.hyper.v_dims
        return host.replace(
            w=np.asarray(host.w)[: nf],
            z=np.asarray(host.z)[: nf],
            n=np.asarray(host.n)[: nf],
            touched=np.asarray(host.touched)[: nf],
            v=np.asarray(host.v)[: dv],
            v_gg=np.asarray(host.v_gg)[: dv],
        )


class MCShardedTrainer:
    """Feature-dim sharded MULTICLASS training: the stacked [L, D] weight
    (and covariance) tensor stripes along the feature dim — [L, D/S] per
    device. Per row, the per-label score/variance partials psum over the
    stripe axis (models/multiclass.py _row_quantities_sharded), the margin
    and closed-form alpha/beta are computed from the global scalars, and
    the correct/missed row updates scatter into the local stripe. An
    L-label covariance model at 2^24 dims is 2L full tables — this is what
    makes it fit. Blocks replicate; arbitrary dims pad up."""

    def __init__(self, rule, hyper: dict, num_labels: int, dims: int,
                 mesh: Optional[Mesh] = None, mode: str = "minibatch"):
        from ..models.multiclass import (MCRule, MulticlassState,
                                         make_mc_train_step)

        assert isinstance(rule, MCRule)
        self.rule = rule
        self.num_labels = num_labels
        self.dims = dims
        self.mesh, self.axis, n = _resolve_1d_mesh(mesh, "MCShardedTrainer")
        self.stripe = -(-dims // n)
        self.dims_padded = self.stripe * n
        dp = self.dims_padded
        L = num_labels

        def init_one() -> MulticlassState:
            return MulticlassState(
                weights=jnp.zeros((L, dp), jnp.float32),
                covars=jnp.ones((L, dp), jnp.float32)
                if rule.use_covariance else None,
                touched=jnp.zeros((L, dp), jnp.int8),
                step=jnp.zeros((), jnp.int32),
            )

        self._init_fn = init_one
        mc_body = make_mc_train_step(rule, hyper, mode,
                                     feature_shard=(self.axis, self.stripe))

        def body(state, indices, values, labels):
            # labels cast on device (no host round trip on the hot path)
            return mc_body(state, indices, values, labels.astype(jnp.int32))
        state_shape = jax.eval_shape(init_one)
        specs = jax.tree.map(
            lambda leaf: P(None, self.axis)
            if leaf.ndim == 2 and leaf.shape[-1] == dp else P(), state_shape)
        self._specs = specs
        self._step = jax.jit(
            shard_map(
                body,
                mesh=self.mesh,
                in_specs=(specs, P(), P(), P()),
                out_specs=(specs, P()),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )

    def init(self):
        return _born_sharded(self._init_fn, self.mesh, self._specs)

    def step(self, state, indices, values, labels):
        """indices/values: [B, K]; labels: [B] int (replicated)."""
        with TRACER.span("train.compiled_step",
                         args={"trainer": "mc_sharded"}):
            return self._step(state, indices, values, labels)

    def final_state(self, state):
        """Host-side copy with the padding sliced back off."""
        with TRACER.span("train.sync", args={"trainer": "mc_sharded"}):
            host = jax.device_get(state)
        return _unpad_state(host, self.dims,
                            self.dims_padded, self._specs, self.axis)

    def make_predict(self):
        """Per-label scores from the sharded state: local [L, K] gather +
        one psum over the stripe axis."""
        stripe, axis = self.stripe, self.axis

        from ..core.striping import translate_to_stripe

        def local_scores(weights, idx, val):
            lidx, vmask = translate_to_stripe(idx, val, axis, stripe)
            W = jnp.take(weights, lidx, axis=1, mode="fill",
                         fill_value=0.0)  # [L, B, K]
            return jax.lax.psum(jnp.einsum("lbk,bk->bl", W, vmask), axis)

        fn = shard_map(
            local_scores,
            mesh=self.mesh,
            in_specs=(P(None, self.axis), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        jfn = jax.jit(fn)

        def predict(state, indices, values):
            return jfn(state.weights, indices, values)

        return predict


class Sharded2DTrainer:
    """Replicas x feature stripes: R data-parallel model replicas, each
    feature-sharded over S devices. Per-row score/norm/variance partials
    psum along the stripe axis (every device of a replica sees the global
    row scalars); every `config.mix_every` blocks the replicas mix along the
    replica axis with the delta-weighted average / argminKLD reduction —
    stripe-local, no cross-stripe traffic.

    Blocks: [R, k, B, K] — replica r trains its own k blocks (data
    parallelism), every stripe of a replica sees all of that replica's rows.

    Cadence note: for covariance learners the argminKLD mix SHRINKS the
    mixed covariance (1/sum(1/cov)) every time it fires — mixing after every
    block freezes the learner early. The reference gates server replies at
    syncThreshold=30 clock ticks (MixServerHandler.java:142-148); pick
    mix_every accordingly (tens of blocks), not 1.
    """

    def __init__(self, rule: Rule, hyper: dict, dims: int,
                 mesh: Optional[Mesh] = None,
                 n_replicas: Optional[int] = None,
                 n_shards: Optional[int] = None,
                 config: MixConfig = MixConfig(), mode: str = "minibatch",
                 mini_batch_average: bool = True):
        self.rule = rule
        self.hyper = hyper
        self.dims = dims
        if mesh is None:
            if n_replicas is None or n_shards is None:
                raise ValueError(
                    "pass either a 2-D mesh or both n_replicas and n_shards")
            mesh = make_mesh_2d(n_replicas, n_shards)
        if len(mesh.axis_names) != 2:
            raise ValueError(
                f"Sharded2DTrainer needs a 2-D mesh, got axes {mesh.axis_names}")
        self.mesh = mesh
        self.replica_axis, self.shard_axis = mesh.axis_names
        self.n_replicas = mesh.shape[self.replica_axis]
        self.n_shards = mesh.shape[self.shard_axis]
        self.config = config
        self.stripe = -(-dims // self.n_shards)
        self.dims_padded = self.stripe * self.n_shards
        self._resume_base = None  # set by init(from_state=...) on warm restart
        reduction = config.reduction
        if reduction == "auto":
            reduction = "argmin_kld" if rule.use_covariance else "average"
        self.reduction = reduction

        local_fn = make_train_fn(rule, hyper, mode=mode,
                                 mini_batch_average=mini_batch_average,
                                 track_deltas=True,
                                 feature_shard=(self.shard_axis, self.stripe))
        mix = make_linear_mix(self.reduction, self.replica_axis)
        mix_every = config.mix_every

        def device_step(state: LinearState, indices, values, labels):
            # leaves carry a leading [1] replica axis inside shard_map
            st = jax.tree.map(lambda x: x[0], state)

            def body(s, blk):
                s, loss = local_fn(s, *blk)
                return s, loss

            st, loss = grouped_mix_scan(
                body, mix, st, (indices[0], values[0], labels[0]), mix_every)
            # loss is identical on every stripe (computed from psummed row
            # scalars); sum it over the replicas
            loss_sum = jax.lax.psum(loss, self.replica_axis)
            return jax.tree.map(lambda x: x[None], st), loss_sum

        state_shape = jax.eval_shape(self._init_one)
        # replica axis leads every leaf; [D] leaves additionally stripe
        specs = jax.tree.map(
            lambda leaf: P(self.replica_axis, self.shard_axis)
            if leaf.ndim == 1 else P(self.replica_axis), state_shape)
        self._specs = specs
        blk = P(self.replica_axis)
        self._step = jax.jit(
            shard_map(
                device_step,
                mesh=self.mesh,
                in_specs=(specs, blk, blk, blk),
                out_specs=(specs, P()),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )

    def _init_one(self, **kwargs) -> LinearState:
        return init_linear_state(
            self.dims_padded,
            use_covariance=self.rule.use_covariance,
            slot_names=tuple(self.rule.slot_names) + (DELTA_SLOT,),
            global_names=self.rule.global_names,
            **kwargs,
        )

    def init(self, from_state: Optional[LinearState] = None,
             **kwargs) -> LinearState:
        """Replicated-then-striped initial state: every leaf gains a leading
        [R] replica axis; [D] leaves additionally shard into [D/S] stripes —
        each device allocates [1, stripe].

        ``from_state`` seeds every replica from a collapsed checkpoint (the
        elastic-restart path over BOTH mesh axes at once: the table
        re-stripes to this mesh's stripe grid AND re-replicates to its
        replica count). Exactly like MixTrainer, the seeded base is
        remembered so final_state() strips it from each replica's ADDITIVE
        statistics (step, sum-kind slots, Welford globals) before the
        collapse and restores it once after — nothing is counted
        n_replicas times, no matter how many checkpoint/resume cycles
        stack."""
        self._resume_base = None
        if from_state is not None:
            if kwargs:
                raise ValueError("pass either from_state or init kwargs")
            host = _align_linear_host(
                from_state, self.dims, self.rule.use_covariance,
                tuple(self.rule.slot_names) + (DELTA_SLOT,),
                tuple(self.rule.global_names))
            dp = self.dims_padded
            padded = host.replace(
                weights=_pad_initial(np.asarray(host.weights), dp),
                covars=_pad_initial(np.asarray(host.covars), dp, 1.0)
                if host.covars is not None else None,
                slots={k: _pad_initial(np.asarray(v), dp)
                       for k, v in host.slots.items()},
                touched=_pad_initial(np.asarray(host.touched), dp),
            )
            self._resume_base = padded
            return replicate_state(padded, self.n_replicas, self.mesh,
                                   specs=self._specs, axis=self.replica_axis)
        for key, fill in (("initial_weights", 0.0), ("initial_covars", 1.0)):
            if kwargs.get(key) is not None:
                kwargs[key] = _pad_initial(kwargs[key], self.dims_padded, fill)
        return replicate_state(self._init_one(**kwargs), self.n_replicas,
                               self.mesh, specs=self._specs,
                               axis=self.replica_axis)

    def step(self, state: LinearState, indices, values, labels):
        """indices/values: [R, k, B, K]; labels: [R, k, B] — replica r's k
        blocks. Each group of mix_every blocks trains locally, then the
        replicas mix."""
        with TRACER.span("train.compiled_step",
                         args={"trainer": "sharded_2d"}):
            return self._step(state, indices, values, labels)

    def shard_blocks(self, indices, values, labels):
        """Host helper: split [R * k, B, ...] blocks into [R, k, B, ...]."""
        with TRACER.span("train.data_prep", args={"trainer": "sharded_2d"}):
            return split_replica_blocks(self.n_replicas, indices, values,
                                        labels)

    def final_state(self, state: LinearState) -> LinearState:
        """Collapse the replica axis (collapse_linear_replicas: trailing-mix
        weights, touched union, slot merge, Welford merge) and slice the
        padding back off, returning a plain [dims] model. A warm-started
        run (init(from_state=...)) strips the seeded base from each
        replica's additive statistics before the merge and restores it
        once after — see strip_replica_base/add_replica_base."""
        with TRACER.span("train.sync", args={"trainer": "sharded_2d"}):
            host = jax.device_get(state)
        kinds = dict(self.rule.slot_merge)
        base = self._resume_base
        if base is not None:
            host = strip_replica_base(host, base, kinds)
        merged = collapse_linear_replicas(host, kinds)
        if base is not None:
            merged = add_replica_base(merged, base, kinds)
        # collapsed leaves lost the leading replica axis: strip it from the
        # specs too, then slice the stripe axis they name
        collapsed_specs = jax.tree.map(lambda s: P(*tuple(s)[1:]), self._specs)
        return _unpad_state(merged, self.dims, self.dims_padded,
                            collapsed_specs, self.shard_axis)

    def make_predict(self):
        """Serve the trained 2-D state without re-placement: replica 0's
        stripes already lay [D/S] per device; score with the shared
        stripe_score body, psum over the stripe axis."""
        def local_score(w_local, indices, values):
            # w_local: [1, stripe] (replica-axis leading)
            return stripe_score(self.shard_axis, self.stripe)(
                w_local[0], indices, values)

        fn = shard_map(
            local_score,
            mesh=self.mesh,
            in_specs=(P(self.replica_axis, self.shard_axis), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        jfn = jax.jit(fn)

        def predict(state: LinearState, indices, values):
            return jfn(state.weights, indices, values)

        return predict
