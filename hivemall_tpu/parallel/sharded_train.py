"""Feature-dimension sharded TRAINING — model-parallel linear learners.

The reference trains against a parameter store sharded across MIX servers by
feature hash: every update routes to `hash(feature) mod numNodes`
(ref: mix/client/MixRequestRouter.java:56-60), so no single node holds the
whole 2^24-dim model. TPU-native, the same capability is the model pytree
sharded along the feature dimension over the mesh: each device holds a [D/n]
stripe of weights / covars / optimizer slots, and a training step is

    gather:  each device gathers its stripe's hits (lanes it does not own are
             masked to zero),
    reduce:  per-row score / squared-norm / variance partials psum over ICI —
             after the psum every device knows the full-row scalars,
    update:  the rule's closed form runs lane-wise on every device with the
             *global* scalars, and deltas scatter into the local stripe only.

The step body is the ordinary engine step built with
`make_train_fn(..., feature_shard=(axis, stripe))` (core/engine.py) — one
copy of the update-application logic, sharded or not. Parity vs the
single-device engine is exact up to psum summation order
(tests/test_sharded_train.py).

Unlike the data-parallel MixTrainer (full replica per device, periodic
averaging), this path trains ONE model too big for one chip's HBM — e.g.
covariance + optimizer slots at 2^24+ dims — the TP analog this workload
admits (SURVEY.md §2.18 "feature-sharded servers → model-dim sharding").
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..core.engine import Rule, make_train_fn
from ..core.state import LinearState, init_linear_state
from .mesh import make_mesh


class ShardedTrainer:
    """Train a single feature-sharded model across the mesh.

    The state returned by `init()` / threaded through `step()` is a full-dims
    LinearState whose [D] leaves carry a NamedSharding along the feature dim —
    each device materializes only its [D/n] stripe in HBM. Blocks are
    replicated (every device sees every row; the model, not the data, is what
    doesn't fit).
    """

    def __init__(self, rule: Rule, hyper: dict, dims: int,
                 mesh: Optional[Mesh] = None, mode: str = "minibatch",
                 mini_batch_average: bool = True):
        self.rule = rule
        self.hyper = hyper
        self.dims = dims
        self.mesh = mesh if mesh is not None else make_mesh()
        if len(self.mesh.axis_names) != 1:
            raise ValueError(
                f"ShardedTrainer needs a 1-D mesh, got axes {self.mesh.axis_names}")
        self.axis = self.mesh.axis_names[0]
        n = self.mesh.devices.size
        if dims % n != 0:
            raise ValueError(f"dims {dims} not divisible by {n} devices")
        self.stripe = dims // n

        body_fn = make_train_fn(rule, hyper, mode=mode,
                                mini_batch_average=mini_batch_average,
                                feature_shard=(self.axis, self.stripe))
        state_shape = jax.eval_shape(self._init_one)
        # [D] leaves stripe along the feature dim; scalars replicate
        specs = jax.tree.map(
            lambda leaf: P(self.axis) if leaf.ndim == 1 else P(), state_shape)
        self._specs = specs
        self._step = jax.jit(
            jax.shard_map(
                body_fn,
                mesh=self.mesh,
                in_specs=(specs, P(), P(), P()),
                out_specs=(specs, P()),
                check_vma=False,
            ),
            donate_argnums=(0,),
        )

    def _init_one(self, **kwargs) -> LinearState:
        return init_linear_state(
            self.dims,
            use_covariance=self.rule.use_covariance,
            slot_names=tuple(self.rule.slot_names),
            global_names=self.rule.global_names,
            **kwargs,
        )

    def init(self, **kwargs) -> LinearState:
        """Initial state with [D] leaves placed feature-sharded on the mesh —
        each device allocates only its stripe. kwargs pass through to
        init_linear_state (initial_weights/initial_covars = -loadmodel warm
        start, ref: LearnerBaseUDTF.java:215-333)."""
        state = self._init_one(**kwargs)
        return jax.tree.map(
            lambda leaf, spec: jax.device_put(
                leaf, NamedSharding(self.mesh, spec)),
            state, self._specs)

    def step(self, state: LinearState, indices, values, labels):
        """One sharded train step. indices/values: [B, K]; labels: [B]
        (replicated to every device — the model is what's sharded)."""
        return self._step(state, indices, values, labels)
