"""Data-parallel multiclass training with collective mixing.

The reference mixes multiclass learners per label: each label's model joins
MIX group `jobId + '-' + label` (ref: LearnerBaseUDTF.java:202-204), so the
fleet averages L independent feature-sharded groups. TPU-native the stacked
[L, D] tensor mixes in ONE collective — the label axis just rides along:

- average:     w̄[l, d] = sum_dev(w * touched) / sum_dev(touched)
- argmin_kld:  per (l, d) precision-weighted mean with covariance shrink
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models.multiclass import (MCRule, MulticlassState, make_mc_train_step)
from .mesh import WORKER_AXIS, make_mesh
from .mix import (MixConfig, grouped_mix_scan, merge_slot_arrays,
                  replicate_state)
from ..runtime.jax_compat import shard_map


class MulticlassMixTrainer:
    def __init__(self, rule: MCRule, hyper: dict, num_labels: int, dims: int,
                 mesh: Optional[Mesh] = None, mode: str = "minibatch",
                 config: MixConfig = MixConfig()):
        self.rule = rule
        self.num_labels = num_labels
        self.dims = dims
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_dev = self.mesh.devices.size
        self.config = config
        self.axis = config.axis_name
        reduction = config.reduction
        if reduction == "auto":
            reduction = "argmin_kld" if rule.use_covariance else "average"
        self.reduction = reduction

        local_step = make_mc_train_step(rule, hyper, mode)

        def mix(st: MulticlassState) -> MulticlassState:
            counts = st.touched.astype(jnp.float32)  # [L, D]
            total = jax.lax.psum(counts, self.axis)
            if self.reduction == "argmin_kld":
                inv = 1.0 / st.covars
                sum_inv = jax.lax.psum(inv, self.axis)
                w = jnp.where(total > 0,
                              jax.lax.psum(st.weights * inv, self.axis) / sum_inv,
                              st.weights)
                cov = jnp.where(total > 0, 1.0 / sum_inv, st.covars)
                return st.replace(weights=w, covars=cov)
            w = jnp.where(total > 0,
                          jax.lax.psum(st.weights * counts, self.axis)
                          / jnp.maximum(total, 1.0), st.weights)
            return st.replace(weights=w)

        def device_step(state: MulticlassState, indices, values, labels):
            st = jax.tree.map(lambda x: x[0], state)

            def body(s, blk):
                s, loss = local_step(s, blk[0], blk[1], blk[2].astype(jnp.int32))
                return s, loss

            st, loss = grouped_mix_scan(
                body, mix, st, (indices[0], values[0], labels[0]),
                config.mix_every)
            return jax.tree.map(lambda x: x[None], st), jax.lax.psum(
                loss, self.axis)

        def init_one() -> MulticlassState:
            L = num_labels
            return MulticlassState(
                weights=jnp.zeros((L, dims), jnp.float32),
                covars=jnp.ones((L, dims), jnp.float32) if rule.use_covariance else None,
                touched=jnp.zeros((L, dims), jnp.int8),
                step=jnp.zeros((), jnp.int32),
            )

        self._init_one = init_one
        spec_state = jax.tree.map(lambda _: P(self.axis), jax.eval_shape(init_one))
        self._step = jax.jit(
            shard_map(
                device_step,
                mesh=self.mesh,
                in_specs=(spec_state, P(self.axis), P(self.axis), P(self.axis)),
                out_specs=(spec_state, P()),
            ),
            donate_argnums=(0,),
        )

    def init(self) -> MulticlassState:
        return replicate_state(self._init_one(), self.n_dev, self.mesh,
                               axis=self.axis)

    def step(self, state, indices, values, labels):
        return self._step(state, indices, values, labels)

    def final_state(self, state) -> MulticlassState:
        """Collapse the device axis: weights/covars are identical across
        replicas after the trailing mix; touched unions; any populated
        optimizer slots merge per MCRule.slot_merge through the same
        machinery as linear/FFM (merge_slot_arrays) rather than silently
        keeping replica 0's. (No current MC rule produces slots during
        training — this guards the collapse itself.)"""
        host = jax.device_get(state)
        merged = jax.tree.map(lambda x: x[0], host)
        touched_all = np.asarray(host.touched)  # [n_dev, L, D]
        step_all = np.asarray(host.step)
        merged = merged.replace(
            touched=np.max(touched_all, axis=0),
            step=step_all.sum().astype(step_all.dtype),
        )
        if host.slots:
            merged = merged.replace(slots=merge_slot_arrays(
                host.slots, touched_all, dict(self.rule.slot_merge)))
        return merged
