"""Multi-process (multi-host) random-forest training.

The reference trains forests across the cluster by letting EACH Hive mapper
train its own trees on its data partition and emitting per-tree model rows;
prediction then majority-votes over all emitted trees with rf_ensemble
(ref: smile/classification/RandomForestClassifierUDTF.java:343-351,
smile/tools/RandomForestEnsembleUDAF.java:34). TPU-first the same topology
holds: each jax process (host) grows its shard of the forest with
`grow_forest`'s batched device kernels on its local rows, and the exported
model rows — opcode/json programs evaluating on RAW feature units — merge
process-agnostically, exactly like the reference's model-table rows.

This module is the glue: tree-count sharding, disjoint global model ids,
decorrelated per-process seeds, a consistent global class-index space, and
the row-level ensemble evaluator used to predict from merged rows
(model rows are the 6-tuples forest.model_rows() emits:
(model_id, model_type, model, var_importance, oob_errors, oob_tests)).
"""

from __future__ import annotations

import shlex
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..ensemble import rf_ensemble
from ..models.trees.forest import (TrainedForest, train_randomforest_classifier,
                                   train_randomforest_regr)
from ..models.trees.predict import compile_tree


def shard_tree_counts(total_trees: int, process_count: int) -> List[int]:
    """Near-even split of the forest across processes (first shards take the
    remainder — the same arithmetic Hadoop uses for map splits)."""
    base, rem = divmod(total_trees, process_count)
    return [base + (1 if p < rem else 0) for p in range(process_count)]


def _resolve_process(process_index: Optional[int], process_count: Optional[int]
                     ) -> Tuple[int, int]:
    if process_index is not None and process_count is not None:
        return process_index, process_count
    import jax

    return jax.process_index(), jax.process_count()


def _split_opt(options: str) -> Tuple[int, int, List[str]]:
    """Pull -trees and -seed out of an option string (shlex-tokenized like
    Options.parse, dash-insensitive like its option matching), keep the rest
    verbatim."""
    kept: List[str] = []
    toks = shlex.split(options or "")
    i = 0
    trees, seed = 50, -1
    while i < len(toks):
        t = toks[i]
        bare = t.lstrip("-") if t.startswith("-") else ""
        if bare in ("trees", "num_trees", "seed"):
            if i + 1 >= len(toks):
                raise ValueError(f"option {t} requires a value")
            if bare == "seed":
                seed = int(toks[i + 1])
            else:
                trees = int(toks[i + 1])
            i += 2
        else:
            kept.append(t)
            i += 1
    return trees, seed, kept


def train_randomforest_sharded(
    X, y, options: str = "", *, classification: bool = True,
    classes=None, process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> TrainedForest:
    """Train THIS process's shard of the forest on its local (X, y) partition.

    `-trees N` in `options` is the GLOBAL forest size; this process grows its
    `shard_tree_counts` share with a seed decorrelated by process index
    (`-seed` omitted stays nondeterministic, like the trainers) and model ids
    offset so rows from all processes merge without collision — the
    in-framework equivalent of one mapper's emission.

    `classes`: the GLOBAL label list. Pass it whenever partitions may miss a
    class — each shard's trees then vote in the same class-index space. When
    None, the global labels are taken from the LOCAL partition (safe only if
    every partition contains every class)."""
    if classes is not None and not classification:
        raise ValueError("`classes` only applies to classification forests")
    p, P = _resolve_process(process_index, process_count)
    total, seed, kept = _split_opt(options)
    counts = shard_tree_counts(total, P)
    local = counts[p]
    offset = sum(counts[:p])
    if local == 0:
        return TrainedForest([], classification,
                             0 if classes is None else len(np.unique(classes)),
                             [], [])
    opt_parts = [shlex.quote(t) for t in kept] + [f"-trees {local}"]
    if seed >= 0:
        opt_parts.append(f"-seed {seed * 7919 + p}")
    opt = " ".join(opt_parts)
    if classification:
        forest = train_randomforest_classifier(X, y, opt, classes=classes)
    else:
        forest = train_randomforest_regr(X, y, opt)
    for t in forest.trees:
        t.model_id += offset
    return forest


def train_gbt_data_parallel(X, y, options: str = "", mesh=None):
    """Data-parallel gradient tree boosting over a device mesh.

    Boosting rounds are inherently sequential, so the reference's per-tree
    thread pool buys GBT nothing (SmileTaskExecutor parallelizes across
    trees; a round's tree depends on the previous round's output). The
    device-scalable axis is WITHIN each round: the [S, F, B, C] histogram
    build over all N rows. Here rows shard across the mesh, each device
    scatter-adds its partial histogram, and one psum per tree level
    reduces them (models/trees/grow.py::_sharded_hist_fn); the split
    search and all growth decisions then run on the replicated global
    histogram, identical to single-device growth up to float reduction
    order. Same trick the sharded RF path gets for free via grow_forest's
    row_shard."""
    from ..models.trees.forest import train_gradient_tree_boosting_classifier
    from .mesh import make_mesh

    mesh = mesh if mesh is not None else make_mesh()
    if len(mesh.axis_names) != 1:
        raise ValueError("train_gbt_data_parallel needs a 1-D mesh, got "
                         f"axes {mesh.axis_names}")
    return train_gradient_tree_boosting_classifier(
        X, y, options, row_shard=(mesh, mesh.axis_names[0]))


def ensemble_predict_rows(model_rows: Sequence[Tuple], X,
                          classification: bool = True,
                          classes=None) -> np.ndarray:
    """Predict from MERGED per-tree model rows (any mix of processes):
    evaluate each exported tree program on raw features and rf_ensemble the
    votes — the reference's tree_predict + rf_ensemble SQL plan. Programs are
    compiled once (predict.compile_tree), not per row. `classes`
    (classification): map the voted class indices back to original labels."""
    if not model_rows:
        raise ValueError("no model rows to ensemble")
    X = np.asarray(X, dtype=np.float64)
    leaf_vals = _eval_rows_native(model_rows, X)
    if leaf_vals is None:  # mixed formats or no native library: Python VM
        evals = [compile_tree(row[1], row[2]) for row in model_rows]
        leaf_vals = np.stack([[ev(x) for x in X] for ev in evals])  # [T, N]
    if classification:
        out = np.array([rf_ensemble(int(v) for v in leaf_vals[:, r])[0]
                        for r in range(X.shape[0])], dtype=np.float64)
        if classes is not None:
            return np.unique(np.asarray(classes))[out.astype(int)]
        return out
    return leaf_vals.mean(axis=0)


def _eval_rows_native(model_rows: Sequence[Tuple], X) -> Optional[np.ndarray]:
    """All-opcode row sets evaluate in ONE native pass (C++ hm_forest_eval
    over the compiled programs) -> [T, N] leaf values, else None."""
    if not all(row[1].lower() in ("opscode", "vm") for row in model_rows):
        return None
    from .. import native
    from ..models.trees.vm import compile_script_arrays

    if not native.available():
        return None
    progs = [compile_script_arrays(row[2]) for row in model_rows]
    return native.forest_eval(progs, X)
