"""Device mesh helpers.

The reference scales out over Hadoop mappers + a Netty parameter-server fleet
(ref: SURVEY.md §2.18). TPU-native, the workers are devices in a
jax.sharding.Mesh and synchronization is XLA collectives over ICI (single
slice) / DCN (multi-slice) — no TCP path exists.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

WORKER_AXIS = "workers"
SHARD_AXIS = "shards"


def make_mesh(n_devices: Optional[int] = None, axis_name: str = WORKER_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D data-parallel mesh over the available devices.

    Multi-host note: jax.devices() returns the global device list under
    multi-process JAX, so the same code scales from 1 chip to a multi-host pod
    with DCN collectives inserted by XLA automatically.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def make_mesh_2d(n_replicas: int, n_shards: int,
                 replica_axis: str = WORKER_AXIS, shard_axis: str = SHARD_AXIS,
                 devices: Optional[Sequence] = None) -> Mesh:
    """A 2-D mesh: `n_replicas` data-parallel replicas x `n_shards` feature
    stripes — the reference's actual topology of N mapper clients training
    against M feature-sharded MIX servers (ref: MixRequestRouter.java:56-60
    routing under multiple concurrent clients, MixServerHandler.java:118-158).
    Lay the shard axis innermost so the per-row psums ride the fastest ICI
    links."""
    if devices is None:
        devices = jax.devices()
    need = n_replicas * n_shards
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.asarray(devices[:need]).reshape(n_replicas, n_shards)
    return Mesh(grid, (replica_axis, shard_axis))
