"""Data-parallel FFM training with collective mixing.

Same contract as fm_mix.py: replicas train on shards, weights cross the
"wire", optimizer state stays local. Mixable FFM state: w0 (pmean), w
(touch-weighted average), V (plain pmean — the hashed (feature,field) table
has no per-entry touch mask; entries untouched everywhere are identical
across replicas so the mean is a no-op for them). FTRL z/n and AdaGrad gg
stay device-local.

Mix cadence is MixConfig.mix_every, uniform with MixTrainer: the default (1)
mixes after every block; mix_every=k trains k blocks locally per collective.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models.ffm import FFMHyper, FFMState, init_ffm_state, make_ffm_step
from .mesh import WORKER_AXIS, make_mesh
from .mix import MixConfig, grouped_mix_scan, replicate_state
from ..runtime.jax_compat import pcast, shard_map


class FFMMixTrainer:
    def __init__(self, hyper: FFMHyper, mesh: Optional[Mesh] = None,
                 mode: str = "minibatch", config: MixConfig = MixConfig()):
        self.hyper = hyper
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_dev = self.mesh.devices.size
        self.config = config
        self.axis = config.axis_name
        local_step = make_ffm_step(hyper, mode)

        def mix(st: FFMState) -> FFMState:
            counts = st.touched.astype(jnp.float32)
            total = jax.lax.psum(counts, self.axis)

            def touch_avg(x):
                return jnp.where(total > 0,
                                 jax.lax.psum(x * counts, self.axis)
                                 / jnp.maximum(total, 1.0), x)

            # FTRL derives w from the duals at the next update of a feature
            # (w_updates in models/ffm.py), so mixing w alone would be
            # overwritten — the duals z/n mix with the same touch-weighted
            # average, keeping the mixed linear term effective. w is mixed
            # too: it is read directly by predict for features not updated
            # again.
            # pcast re-tags device-invariant pmean results as mesh-varying so
            # the grouped-scan carry type stays consistent
            revary = lambda x: pcast(x, self.axis, to="varying")
            return st.replace(
                w=touch_avg(st.w),
                z=touch_avg(st.z),
                n=touch_avg(st.n),
                v=revary(jax.lax.pmean(st.v, self.axis)),
                w0=revary(jax.lax.pmean(st.w0, self.axis)),
            )

        def device_step(state: FFMState, indices, values, fields, labels):
            st = jax.tree.map(lambda x: x[0], state)

            def body(s, blk):
                s, loss = local_step(s, *blk)
                return s, loss

            st, loss = grouped_mix_scan(
                body, mix, st,
                (indices[0], values[0], fields[0], labels[0]),
                config.mix_every)
            return jax.tree.map(lambda x: x[None], st), jax.lax.psum(
                loss, self.axis)

        spec_state = jax.tree.map(lambda _: P(self.axis),
                                  jax.eval_shape(lambda: init_ffm_state(hyper)))
        self._step = jax.jit(
            shard_map(
                device_step,
                mesh=self.mesh,
                in_specs=(spec_state,) + (P(self.axis),) * 4,
                out_specs=(spec_state, P()),
            ),
            donate_argnums=(0,),
        )

    def init(self) -> FFMState:
        return replicate_state(init_ffm_state(self.hyper), self.n_dev,
                               self.mesh, axis=self.axis)

    def step(self, state, indices, values, fields, labels):
        return self._step(state, indices, values, fields, labels)

    def final_state(self, state) -> FFMState:
        """Collapse the device axis: w/z/n/v/w0 are identical across replicas
        after the trailing mix; touched unions; the AdaGrad-V accumulator
        v_gg — an additive sum of squared gradients over each replica's
        disjoint shard — merges by summing (the union stream's total), so a
        warm restart resumes with the full-stream curvature instead of one
        replica's."""
        host = jax.device_get(state)
        merged = jax.tree.map(lambda x: x[0], host)
        step_all = np.asarray(host.step)
        return merged.replace(
            touched=np.max(np.asarray(host.touched), axis=0),
            v_gg=np.asarray(host.v_gg).sum(axis=0),
            step=step_all.sum().astype(step_all.dtype),
        )
