"""Collective model mixing — the TPU-native replacement of the MIX subsystem.

The reference's MIX protocol (ref: SURVEY.md §2.18; mix/client/MixClient.java:48-173,
mixserv/.../MixServerHandler.java:54-158) is an asynchronous, feature-sharded
parameter server over Netty TCP: clients push (weight, covar, deltaUpdates)
when a feature's local update count crosses `mixThreshold`, servers keep
per-feature partial aggregates and push back the global mean when the clock
difference crosses `syncThreshold`.

Under synchronous SPMD on a TPU mesh the whole TCP path collapses into
collectives inside one jitted step:

- each device trains a full model replica on its data shard (the Hadoop-mapper
  analog), with per-feature update counts tracked since the last mix;
- every `mix_every` blocks, replicas are averaged over the mesh axis with one
  of the reference's two reduction operators:
    * `average`   — delta-weighted arithmetic mean
                    sum(w * delta) / sum(delta)          (ref: PartialAverage.java:43-67)
    * `argmin_kld` — precision-weighted mean
                    sum(w/cov) / sum(1/cov), cov' = 1/sum(1/cov)
                                                        (ref: PartialArgminKLD.java:43-63)
- features untouched on every replica keep their local value (the server never
  saw them — exact analog of threshold-gated pushes);
- the cancel/staleness machinery (MixClient.java:145-166) is unnecessary:
  synchronous collectives cannot observe stale contributions.

ICI carries the psum on-pod; multi-slice/multi-host runs get DCN collectives
from XLA with the same program (scaling-book recipe: mesh + shardings, let XLA
insert the collectives).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..core.engine import DELTA_SLOT, Rule, make_train_fn
from ..core.state import LinearState, init_linear_state
from .mesh import WORKER_AXIS, make_mesh
from ..runtime.jax_compat import shard_map
from ..runtime.tracing import TRACER


def mix_average(weights, delta_upd, axis_name: str = WORKER_AXIS):
    """Delta-weighted arithmetic mean across the mesh axis
    (ref: PartialAverage.java getWeight = scaledSumWeights/totalUpdates)."""
    total = jax.lax.psum(delta_upd, axis_name)
    wsum = jax.lax.psum(weights * delta_upd, axis_name)
    return jnp.where(total > 0.0, wsum / jnp.maximum(total, 1.0), weights), total


def mix_argmin_kld(weights, covars, delta_upd, axis_name: str = WORKER_AXIS):
    """Precision-weighted (inverse-variance) mean across the mesh axis
    (ref: PartialArgminKLD.java:43-63, ensemble/ArgminKLDistanceUDAF.java:28-90)."""
    total = jax.lax.psum(delta_upd, axis_name)
    inv = 1.0 / covars
    sum_inv = jax.lax.psum(inv, axis_name)
    sum_wdiv = jax.lax.psum(weights * inv, axis_name)
    mixed_w = jnp.where(total > 0.0, sum_wdiv / sum_inv, weights)
    mixed_cov = jnp.where(total > 0.0, 1.0 / sum_inv, covars)
    return mixed_w, mixed_cov, total


def grouped_mix_scan(local_body, mix, state, blocks, mix_every: int):
    """Consume `blocks` (a tuple of arrays, each [k, ...]) in groups of
    `mix_every`, training locally within a group and applying `mix` once per
    group — the sync-threshold semantic shared by every mix trainer (the
    server replies with the global average only when a feature's clock
    advanced >= syncThreshold, ref: MixServerHandler.java:142-148).

    local_body: (state, block_tuple) -> (state, loss)
    mix:        state -> state
    Returns (state, total_loss).
    """
    k = jax.tree.leaves(blocks)[0].shape[0]
    if k % mix_every != 0:
        raise ValueError(
            f"{k} blocks per device not divisible by mix_every={mix_every}")
    groups = jax.tree.map(
        lambda a: a.reshape((k // mix_every, mix_every) + a.shape[1:]), blocks)

    def group_body(s, grp):
        s, losses = jax.lax.scan(local_body, s, grp)
        return mix(s), jnp.sum(losses)

    state, losses = jax.lax.scan(group_body, state, groups)
    return state, jnp.sum(losses)


def merge_slot_arrays(slots: dict, touched_all: np.ndarray, kinds: dict,
                      drop: Tuple[str, ...] = ()) -> dict:
    """Merge per-replica optimizer-slot arrays ([n_dev, ...]) into one model
    per each slot's declared kind (Rule.slot_merge): "sum" for additive
    statistics over the replicas' disjoint data shards, "mean" (default) for
    decayed/averaged ones — weighted by which replicas actually touched each
    entry. Slots named in `drop` reset to zero (pending-delta counters).
    Shared by every trainer's final_state so no trainer silently keeps
    replica 0's slots (the bug class fixed for linear/FFM in round 2)."""
    tmask = touched_all.astype(np.float32)
    n_touch = np.maximum(tmask.sum(axis=0), 1.0)
    merged = {}
    for name, arr in slots.items():
        arr = np.asarray(arr)  # [n_dev, ...]
        if name in drop:
            merged[name] = np.zeros_like(arr[0])
            continue
        mask = tmask
        denom = n_touch
        # broadcast the touch mask over trailing axes (e.g. factor dims)
        while mask.ndim < arr.ndim:
            mask = mask[..., None]
            denom = denom[..., None]
        total = (arr * mask).sum(axis=0)
        if kinds.get(name, "mean") == "sum":
            merged[name] = total
        else:
            merged[name] = total / denom
    return merged


def replicate_state(one, n_replicas: int, mesh: Mesh, specs=None,
                    axis: str = WORKER_AXIS):
    """Broadcast a single-model pytree to a leading [n_replicas] axis and
    place it on the mesh. Default placement: replica axis sharded over
    `axis`, everything else replicated; pass `specs` (a pytree of
    PartitionSpec with the leading replica dim included) to additionally
    stripe trailing dims. One copy of the broadcast-then-place init shared by
    every replicated trainer."""
    stacked = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n_replicas,) + x.shape), one)
    if specs is None:
        specs = jax.tree.map(
            lambda x: P(*((axis,) + (None,) * (x.ndim - 1))), stacked)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), stacked, specs)


def split_replica_blocks(n_replicas: int, *arrays):
    """Host helper shared by the replicated trainers: split [R * k, B, ...]
    blocks into the [R, k, B, ...] layout."""
    nk = arrays[0].shape[0]
    k = nk // n_replicas
    if k * n_replicas != nk:
        raise ValueError(f"{nk} blocks not divisible by {n_replicas} replicas")
    return tuple(a.reshape((n_replicas, k) + a.shape[1:]) for a in arrays)


def make_linear_mix(reduction: str, axis: str):
    """The collective mix applied to a LinearState replica: delta-weighted
    average or argminKLD over `axis`, then reset the pending-delta counter.
    Shared by the data-parallel MixTrainer and the replica axis of the 2-D
    (replicas x feature stripes) trainer."""

    def mix(st: LinearState) -> LinearState:
        delta = st.slots[DELTA_SLOT]
        if reduction == "argmin_kld":
            w, cov, _ = mix_argmin_kld(st.weights, st.covars, delta, axis)
            st = st.replace(weights=w, covars=cov)
        else:
            w, _ = mix_average(st.weights, delta, axis)
            st = st.replace(weights=w)
        return st.replace(slots={**st.slots, DELTA_SLOT: jnp.zeros_like(delta)})

    return mix


def _welford_sub(nc, mc, m2c, n0, mu0, m20):
    """Chan-inverse: remove the base stream (n0, mu0, m20) from a combined
    (nc, mc, m2c), returning the local remainder — exact."""
    n_l = nc - n0
    if n_l <= 0:
        return 0.0, 0.0, 0.0
    mean_l = (mc * nc - mu0 * n0) / n_l
    m2_l = m2c - m20 - (n0 * n_l / nc) * (mean_l - mu0) ** 2
    return n_l, mean_l, max(m2_l, 0.0)


def _welford_add(n_a, mu_a, m2_a, n_b, mu_b, m2_b):
    """Chan parallel merge of two streams — exact."""
    n = n_a + n_b
    if n == 0:
        return 0.0, 0.0, 0.0
    delta = mu_b - mu_a
    mean = mu_a + delta * n_b / n
    m2 = m2_a + m2_b + delta * delta * n_a * n_b / n
    return n, mean, m2


def strip_replica_base(host: LinearState, base: LinearState,
                       slot_kinds: dict) -> LinearState:
    """Remove a warm-start base (the checkpoint every replica was seeded
    with) from each replica's ADDITIVE statistics, leaving only the local
    contributions, so a subsequent collapse_linear_replicas does not count
    the base once per replica: "sum"-kind slots and the step counter
    subtract the base per replica; Welford globals chan-subtract it. Mean
    -kind (EMA) slots stay — averaging seeded EMAs is their semantics.
    add_replica_base() restores the base once after the collapse."""
    b = jax.device_get(base)
    new_slots = dict(host.slots or {})
    for name, kind in slot_kinds.items():
        if kind == "sum" and name in new_slots and name in (b.slots or {}):
            new_slots[name] = np.asarray(new_slots[name]) \
                - np.asarray(b.slots[name])[None]
    gl = dict(host.globals or {})
    if {"n", "mean", "m2"} <= set(gl) and {"n", "mean", "m2"} <= set(
            b.globals or {}):
        n0 = float(np.asarray(b.globals["n"]))
        mu0 = float(np.asarray(b.globals["mean"]))
        m20 = float(np.asarray(b.globals["m2"]))
        ns, mus, m2s = [], [], []
        for r in range(np.asarray(gl["n"]).shape[0]):
            n_l, mu_l, m2_l = _welford_sub(
                float(np.asarray(gl["n"])[r]), float(np.asarray(gl["mean"])[r]),
                float(np.asarray(gl["m2"])[r]), n0, mu0, m20)
            ns.append(n_l)
            mus.append(mu_l)
            m2s.append(m2_l)
        gl = {**gl, "n": np.asarray(ns, np.float32),
              "mean": np.asarray(mus, np.float32),
              "m2": np.asarray(m2s, np.float32)}
    return host.replace(
        slots=new_slots,
        globals=gl,
        step=np.asarray(host.step) - int(np.asarray(b.step)),
    )


def add_replica_base(merged: LinearState, base: LinearState,
                     slot_kinds: dict) -> LinearState:
    """Restore the warm-start base ONCE into a collapsed model (see
    strip_replica_base)."""
    b = jax.device_get(base)
    new_slots = dict(merged.slots or {})
    for name, kind in slot_kinds.items():
        if kind == "sum" and name in new_slots and name in (b.slots or {}):
            new_slots[name] = np.asarray(new_slots[name]) \
                + np.asarray(b.slots[name])
    gl = dict(merged.globals or {})
    if {"n", "mean", "m2"} <= set(gl) and {"n", "mean", "m2"} <= set(
            b.globals or {}):
        n, mu, m2 = _welford_add(
            float(np.asarray(gl["n"])), float(np.asarray(gl["mean"])),
            float(np.asarray(gl["m2"])),
            float(np.asarray(b.globals["n"])),
            float(np.asarray(b.globals["mean"])),
            float(np.asarray(b.globals["m2"])))
        gl = {**gl, "n": np.float32(n), "mean": np.float32(mu),
              "m2": np.float32(m2)}
    step = np.asarray(merged.step) + int(np.asarray(b.step))
    return merged.replace(slots=new_slots, globals=gl,
                          step=step.astype(np.asarray(merged.step).dtype))


def collapse_linear_replicas(host: LinearState, slot_kinds: dict) -> LinearState:
    """Collapse a host-side LinearState whose leaves carry a leading replica
    axis into one model a warm restart can resume from (the mixed analog of
    -loadmodel, ref: LearnerBaseUDTF.java:215-333).

    - weights/covars: identical across replicas after the trailing mix —
      replica 0's copy IS the mixed model;
    - touched: max (union of features any replica updated);
    - optimizer slots: merged per the rule's declared kind over the replicas
      that touched each feature (merge_slot_arrays); the delta counter resets;
    - Welford globals (n, mean, m2): exact Chan parallel merge across the
      replicas' disjoint shards (ref: common/OnlineVariance.java); other
      globals keep replica 0's value.
    """
    merged = jax.tree.map(lambda x: x[0], host)
    touched_all = np.asarray(host.touched)
    merged = merged.replace(touched=np.max(touched_all, axis=0))

    if host.slots:
        merged = merged.replace(slots=merge_slot_arrays(
            host.slots, touched_all, slot_kinds, drop=(DELTA_SLOT,)))

    gl = {k: np.asarray(v) for k, v in host.globals.items()}  # [n_dev] each
    if {"n", "mean", "m2"} <= set(gl):
        n = gl["n"].astype(np.float64)
        tot = n.sum()
        if tot > 0:
            mean = float((gl["mean"] * n).sum() / tot)
            m2 = float(gl["m2"].sum()
                       + (n * (gl["mean"] - mean) ** 2).sum())
            merged = merged.replace(globals={
                **merged.globals,
                "n": np.float32(tot),
                "mean": np.float32(mean),
                "m2": np.float32(m2),
            })
    step_all = np.asarray(host.step)
    merged = merged.replace(step=step_all.sum().astype(step_all.dtype))
    return merged


@dataclass(frozen=True)
class MixConfig:
    # Mix after this many blocks — the sync-threshold analog: the reference's
    # server replies with the global average only when a feature's clock
    # advanced >= syncThreshold since the last reply
    # (ref: mixserv/.../MixServerHandler.java:142-148). Each step() call's
    # per-device blocks are consumed in groups of `mix_every`, with one
    # collective mix after each group.
    #
    # Cadence matters for covariance learners: every argminKLD mix REPLACES
    # the covariance with the combined precision 1/sum(1/cov) — the
    # reference's own reply semantics (PartialArgminKLD.java:43-63) — so
    # mixing after every block shrinks it ~n_dev-fold per block and freezes
    # the learner early. The reference's default effective cadence is tens
    # of updates between mixes (threshold 3 x syncThreshold 30); pick
    # mix_every on that order for argminKLD runs, not 1.
    mix_every: int = 1
    reduction: str = "auto"  # average | argmin_kld | auto (covariance -> argmin_kld,
    # mirroring the reference's event selection for covariance learners)
    axis_name: str = WORKER_AXIS


class MixTrainer:
    """Data-parallel trainer: N replicas on an N-device mesh with periodic
    collective mixing. The device axis is materialized as a leading [n_dev]
    axis on every state leaf, sharded over the mesh.
    """

    def __init__(self, rule: Rule, hyper: dict, dims: int, mesh: Optional[Mesh] = None,
                 config: MixConfig = MixConfig(), mode: str = "minibatch"):
        self.rule = rule
        self.hyper = hyper
        self.dims = dims
        self.mesh = mesh if mesh is not None else make_mesh()
        self.config = config
        reduction = config.reduction
        if reduction == "auto":
            reduction = "argmin_kld" if rule.use_covariance else "average"
        self.reduction = reduction
        self.n_dev = self.mesh.devices.size
        self._resume_base = None  # set by init(from_state=...) on warm restart
        axis = config.axis_name

        local_fn = make_train_fn(rule, hyper, mode=mode, track_deltas=True)

        mix_every = config.mix_every
        mix = make_linear_mix(self.reduction, axis)

        def device_step(state: LinearState, indices, values, labels):
            # state leaves carry a leading [1] device axis inside shard_map
            st = jax.tree.map(lambda x: x[0], state)

            def body(s, blk):
                s, loss = local_fn(s, *blk)
                return s, loss

            st, loss = grouped_mix_scan(
                body, mix, st, (indices[0], values[0], labels[0]), mix_every)
            loss_sum = jax.lax.psum(loss, axis)
            return jax.tree.map(lambda x: x[None], st), loss_sum

        spec_state = jax.tree.map(lambda _: P(self.config.axis_name),
                                  jax.eval_shape(self._init_abstract))
        self._step = jax.jit(
            shard_map(
                device_step,
                mesh=self.mesh,
                in_specs=(spec_state, P(axis), P(axis), P(axis)),
                out_specs=(spec_state, P()),
            ),
            donate_argnums=(0,),
        )

    def _init_abstract(self):
        return self._init_one()

    def _init_one(self) -> LinearState:
        return init_linear_state(
            self.dims,
            use_covariance=self.rule.use_covariance,
            slot_names=tuple(self.rule.slot_names) + (DELTA_SLOT,),
            global_names=self.rule.global_names,
        )

    def init(self, from_state: Optional[LinearState] = None) -> LinearState:
        """Replicated initial state with a leading device axis, sharded over
        the mesh. `from_state` seeds every replica from a collapsed
        single-model state (a final_state() result or an
        io/checkpoint.load_linear_state) — the elastic-restart path: resume
        the same model on whatever mesh size survives. Missing optimizer
        slots (e.g. the mix delta counter) fill with zeros; each replica
        resumes at the checkpoint's step/curvature so eta schedules
        continue. collapse_host()/final_state() strip the seeded base from
        each replica's ADDITIVE statistics (step counter, sum-kind slots,
        Welford globals) before merging and restore it once after, so
        nothing is counted n_dev times no matter how many checkpoint/resume
        cycles stack (strip_replica_base/add_replica_base)."""
        one = self._init_one()
        self._resume_base = None
        if from_state is not None:
            host = jax.device_get(from_state)
            if np.asarray(host.weights).shape[0] != self.dims:
                raise ValueError(
                    f"checkpoint has dims {np.asarray(host.weights).shape[0]}"
                    f" != trainer dims {self.dims}; resume with the dims the"
                    " model was trained at")
            self._resume_base = host
            have = dict(host.slots) if host.slots else {}
            one = one.replace(
                weights=jnp.asarray(host.weights),
                covars=(jnp.asarray(host.covars)
                        if one.covars is not None and host.covars is not None
                        else one.covars),
                slots={name: (jnp.asarray(have[name]) if name in have
                              else zero)
                       for name, zero in one.slots.items()},
                touched=jnp.asarray(host.touched),
                step=jnp.asarray(host.step),
                globals={name: (jnp.asarray(np.asarray(host.globals[name]))
                                if name in (host.globals or {}) else zero)
                         for name, zero in one.globals.items()},
            )
        return replicate_state(one, self.n_dev, self.mesh,
                               axis=self.config.axis_name)

    def step(self, state: LinearState, indices, values, labels):
        """One mixed step. indices/values/labels: [n_dev, k, B, ...] — each
        device consumes k blocks then the replicas mix. The dispatch runs
        under a ``train.compiled_step`` span: inside a driver's
        ``tracing.step_span`` it becomes the per-step timeline's
        compiled-step stage (runtime/tracing.py)."""
        with TRACER.span("train.compiled_step", args={"trainer": "mix_dp"}):
            return self._step(state, indices, values, labels)

    def shard_blocks(self, indices, values, labels):
        """Host helper: split [n_dev * k, B, ...] host blocks into the
        [n_dev, k, B, ...] layout."""
        with TRACER.span("train.data_prep", args={"trainer": "mix_dp"}):
            return split_replica_blocks(self.n_dev, indices, values, labels)

    def collapse_host(self, host: LinearState) -> LinearState:
        """Collapse a host-side replicated state (see
        collapse_linear_replicas). For a warm-started run, every replica was
        seeded with the checkpoint's additive statistics (step, sum-kind
        slots, Welford globals); strip that base per replica before merging
        and restore it once after, so each statistic equals
        base + sum(local contributions) exactly."""
        kinds = dict(self.rule.slot_merge)
        base = getattr(self, "_resume_base", None)
        if base is not None:
            host = strip_replica_base(host, base, kinds)
        merged = collapse_linear_replicas(host, kinds)
        if base is not None:
            merged = add_replica_base(merged, base, kinds)
        return merged

    def final_state(self, state: LinearState) -> LinearState:
        """Collapse the device axis after the trailing mix into one model a
        warm restart can resume from — see collapse_host."""
        with TRACER.span("train.sync", args={"trainer": "mix_dp"}):
            host = jax.device_get(state)
        return self.collapse_host(host)
