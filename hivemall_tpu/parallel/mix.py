"""Collective model mixing — the TPU-native replacement of the MIX subsystem.

The reference's MIX protocol (ref: SURVEY.md §2.18; mix/client/MixClient.java:48-173,
mixserv/.../MixServerHandler.java:54-158) is an asynchronous, feature-sharded
parameter server over Netty TCP: clients push (weight, covar, deltaUpdates)
when a feature's local update count crosses `mixThreshold`, servers keep
per-feature partial aggregates and push back the global mean when the clock
difference crosses `syncThreshold`.

Under synchronous SPMD on a TPU mesh the whole TCP path collapses into
collectives inside one jitted step:

- each device trains a full model replica on its data shard (the Hadoop-mapper
  analog), with per-feature update counts tracked since the last mix;
- every `mix_every` blocks, replicas are averaged over the mesh axis with one
  of the reference's two reduction operators:
    * `average`   — delta-weighted arithmetic mean
                    sum(w * delta) / sum(delta)          (ref: PartialAverage.java:43-67)
    * `argmin_kld` — precision-weighted mean
                    sum(w/cov) / sum(1/cov), cov' = 1/sum(1/cov)
                                                        (ref: PartialArgminKLD.java:43-63)
- features untouched on every replica keep their local value (the server never
  saw them — exact analog of threshold-gated pushes);
- the cancel/staleness machinery (MixClient.java:145-166) is unnecessary:
  synchronous collectives cannot observe stale contributions.

ICI carries the psum on-pod; multi-slice/multi-host runs get DCN collectives
from XLA with the same program (scaling-book recipe: mesh + shardings, let XLA
insert the collectives).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..core.engine import DELTA_SLOT, Rule, make_train_fn
from ..core.state import LinearState, init_linear_state
from .mesh import WORKER_AXIS, make_mesh


def mix_average(weights, delta_upd, axis_name: str = WORKER_AXIS):
    """Delta-weighted arithmetic mean across the mesh axis
    (ref: PartialAverage.java getWeight = scaledSumWeights/totalUpdates)."""
    total = jax.lax.psum(delta_upd, axis_name)
    wsum = jax.lax.psum(weights * delta_upd, axis_name)
    return jnp.where(total > 0.0, wsum / jnp.maximum(total, 1.0), weights), total


def mix_argmin_kld(weights, covars, delta_upd, axis_name: str = WORKER_AXIS):
    """Precision-weighted (inverse-variance) mean across the mesh axis
    (ref: PartialArgminKLD.java:43-63, ensemble/ArgminKLDistanceUDAF.java:28-90)."""
    total = jax.lax.psum(delta_upd, axis_name)
    inv = 1.0 / covars
    sum_inv = jax.lax.psum(inv, axis_name)
    sum_wdiv = jax.lax.psum(weights * inv, axis_name)
    mixed_w = jnp.where(total > 0.0, sum_wdiv / sum_inv, weights)
    mixed_cov = jnp.where(total > 0.0, 1.0 / sum_inv, covars)
    return mixed_w, mixed_cov, total


@dataclass(frozen=True)
class MixConfig:
    mix_every: int = 1  # mix after this many blocks (clock/sync analog)
    reduction: str = "auto"  # average | argmin_kld | auto (covariance -> argmin_kld,
    # mirroring the reference's event selection for covariance learners)
    axis_name: str = WORKER_AXIS


class MixTrainer:
    """Data-parallel trainer: N replicas on an N-device mesh with periodic
    collective mixing. The device axis is materialized as a leading [n_dev]
    axis on every state leaf, sharded over the mesh.
    """

    def __init__(self, rule: Rule, hyper: dict, dims: int, mesh: Optional[Mesh] = None,
                 config: MixConfig = MixConfig(), mode: str = "minibatch"):
        self.rule = rule
        self.hyper = hyper
        self.dims = dims
        self.mesh = mesh if mesh is not None else make_mesh()
        self.config = config
        reduction = config.reduction
        if reduction == "auto":
            reduction = "argmin_kld" if rule.use_covariance else "average"
        self.reduction = reduction
        self.n_dev = self.mesh.devices.size
        axis = config.axis_name

        local_fn = make_train_fn(rule, hyper, mode=mode, track_deltas=True)

        def device_step(state: LinearState, indices, values, labels):
            # state leaves carry a leading [1] device axis inside shard_map
            st = jax.tree.map(lambda x: x[0], state)
            blocks = (indices[0], values[0], labels[0])  # [k, B, ...]

            def body(s, blk):
                s, loss = local_fn(s, *blk)
                return s, loss

            st, losses = jax.lax.scan(body, st, blocks)
            # ---- mix ----
            delta = st.slots[DELTA_SLOT]
            if self.reduction == "argmin_kld":
                w, cov, _ = mix_argmin_kld(st.weights, st.covars, delta, axis)
                st = st.replace(weights=w, covars=cov)
            else:
                w, _ = mix_average(st.weights, delta, axis)
                st = st.replace(weights=w)
            st = st.replace(slots={**st.slots, DELTA_SLOT: jnp.zeros_like(delta)})
            loss_sum = jax.lax.psum(jnp.sum(losses), axis)
            return jax.tree.map(lambda x: x[None], st), loss_sum

        spec_state = jax.tree.map(lambda _: P(self.config.axis_name),
                                  jax.eval_shape(self._init_abstract))
        self._step = jax.jit(
            jax.shard_map(
                device_step,
                mesh=self.mesh,
                in_specs=(spec_state, P(axis), P(axis), P(axis)),
                out_specs=(spec_state, P()),
            ),
            donate_argnums=(0,),
        )

    def _init_abstract(self):
        return self._init_one()

    def _init_one(self) -> LinearState:
        return init_linear_state(
            self.dims,
            use_covariance=self.rule.use_covariance,
            slot_names=tuple(self.rule.slot_names) + (DELTA_SLOT,),
            global_names=self.rule.global_names,
        )

    def init(self) -> LinearState:
        """Replicated initial state with a leading device axis, sharded over
        the mesh."""
        one = self._init_one()
        stacked = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (self.n_dev,) + x.shape), one)
        sharding = NamedSharding(self.mesh, P(self.config.axis_name))
        return jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(
                self.mesh, P(*( (self.config.axis_name,) + (None,) * (x.ndim - 1) )))),
            stacked)

    def step(self, state: LinearState, indices, values, labels):
        """One mixed step. indices/values/labels: [n_dev, k, B, ...] — each
        device consumes k blocks then the replicas mix."""
        return self._step(state, indices, values, labels)

    def shard_blocks(self, indices, values, labels):
        """Host helper: split [n_dev * k, B, ...] host blocks into the
        [n_dev, k, B, ...] layout."""
        nk = indices.shape[0]
        k = nk // self.n_dev
        if k * self.n_dev != nk:
            raise ValueError(f"{nk} blocks not divisible by {self.n_dev} devices")
        reshape = lambda a: a.reshape((self.n_dev, k) + a.shape[1:])
        return reshape(indices), reshape(values), reshape(labels)

    def final_state(self, state: LinearState) -> LinearState:
        """Collapse the device axis after the trailing mix: weights/covars are
        identical across replicas; touched/delta merge by max/sum."""
        host = jax.device_get(state)
        merged = jax.tree.map(lambda x: x[0], host)
        merged = merged.replace(touched=np.max(np.asarray(host.touched), axis=0))
        return merged
