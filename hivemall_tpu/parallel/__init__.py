from .mesh import make_mesh, make_mesh_2d  # noqa: F401
from .mix import MixConfig, MixTrainer, mix_average, mix_argmin_kld  # noqa: F401
from .sharded_train import (FFMShardedTrainer, FMShardedTrainer,  # noqa: F401
                            MCShardedTrainer, Sharded2DTrainer,
                            ShardedTrainer)
