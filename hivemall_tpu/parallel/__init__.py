from .mesh import make_mesh, make_mesh_2d  # noqa: F401
from .mix import MixConfig, MixTrainer, mix_average, mix_argmin_kld  # noqa: F401
from .sharded_train import (FMShardedTrainer, MCShardedTrainer,  # noqa: F401
                            Sharded2DTrainer, ShardedTrainer)
