from .mesh import make_mesh  # noqa: F401
from .mix import MixConfig, MixTrainer, mix_average, mix_argmin_kld  # noqa: F401
