"""Model-dimension sharding — the TP analog this workload admits.

The reference shards its 2^24-dim feature space across MIX servers by feature
hash (ref: mix/client/MixRequestRouter.java:56-60). TPU-native, the same idea
is the weight table sharded across devices along the feature dimension:
each device holds a [D/n] stripe, a batch row's gather hits every stripe, and
partial dot products reduce with one psum over ICI. Used for models too big
for one chip's HBM (e.g. covariance + optimizer slots at 2^24+ dims).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from .mesh import WORKER_AXIS
from ..runtime.jax_compat import shard_map


def shard_weights(weights, mesh: Mesh, axis_name: str = WORKER_AXIS):
    """Place a [D] table sharded along the feature dim across the mesh."""
    return jax.device_put(weights, NamedSharding(mesh, P(axis_name)))


def stripe_score(axis_name: str, stripe: int):
    """The per-device scoring body shared by sharded predict AND sharded
    training's serving path (ShardedTrainer.make_predict): translate global
    feature ids into the local [stripe] table, gather (foreign/OOB lanes
    contribute 0), psum the partial dot products over the stripe axis. One
    copy of the stripe-placement math so trained-sharded and served-sharded
    states cannot drift."""

    from ..core.striping import translate_to_stripe

    def local_score(w_local, indices, values):
        local_idx, vmask = translate_to_stripe(indices, values, axis_name,
                                               stripe)
        w = w_local.at[local_idx].get(mode="fill", fill_value=0.0)
        return jax.lax.psum(jnp.sum(w * vmask, axis=-1), axis_name)

    return local_score


def make_sharded_predict(mesh: Mesh, dims: int, axis_name: str = WORKER_AXIS):
    """Jitted scoring with the weight table feature-sharded: each device
    gathers its stripe's hits (OOB hits drop to 0) and partial scores psum
    over the mesh. Batch is replicated; output replicated."""
    n = mesh.devices.size
    shard = dims // n
    if shard * n != dims:
        raise ValueError(f"dims {dims} not divisible by {n} devices")

    fn = shard_map(
        stripe_score(axis_name, shard),
        mesh=mesh,
        in_specs=(P(axis_name), P(), P()),
        out_specs=P(),
    )
    return jax.jit(fn)
