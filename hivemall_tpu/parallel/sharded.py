"""Model-dimension sharding — the TP analog this workload admits.

The reference shards its 2^24-dim feature space across MIX servers by feature
hash (ref: mix/client/MixRequestRouter.java:56-60). TPU-native, the same idea
is the weight table sharded across devices along the feature dimension:
each device holds a [D/n] stripe, a batch row's gather hits every stripe, and
partial dot products reduce with one psum over ICI. Used for models too big
for one chip's HBM (e.g. covariance + optimizer slots at 2^24+ dims).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from .mesh import WORKER_AXIS


def shard_weights(weights, mesh: Mesh, axis_name: str = WORKER_AXIS):
    """Place a [D] table sharded along the feature dim across the mesh."""
    return jax.device_put(weights, NamedSharding(mesh, P(axis_name)))


def make_sharded_predict(mesh: Mesh, dims: int, axis_name: str = WORKER_AXIS):
    """Jitted scoring with the weight table feature-sharded: each device
    gathers its stripe's hits (OOB hits drop to 0) and partial scores psum
    over the mesh. Batch is replicated; output replicated."""
    n = mesh.devices.size
    shard = dims // n
    if shard * n != dims:
        raise ValueError(f"dims {dims} not divisible by {n} devices")

    def local_score(w_local, indices, values):
        # w_local: [D/n]; translate global ids into the local stripe
        dev = jax.lax.axis_index(axis_name)
        local_idx = indices - dev * shard
        in_range = (local_idx >= 0) & (local_idx < shard)
        local_idx = jnp.where(in_range, local_idx, shard)  # OOB -> dropped by fill
        w = w_local.at[local_idx].get(mode="fill", fill_value=0.0)
        partial_scores = jnp.sum(w * values * in_range.astype(values.dtype), axis=-1)
        return jax.lax.psum(partial_scores, axis_name)

    fn = jax.shard_map(
        local_score,
        mesh=mesh,
        in_specs=(P(axis_name), P(), P()),
        out_specs=P(),
    )
    return jax.jit(fn)
