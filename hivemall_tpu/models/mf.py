"""Matrix Factorization: train_mf_sgd / train_mf_adagrad / train_bprmf,
plus mf_predict / bprmf_predict.

Mirrors the reference MF subsystem (ref: mf/OnlineMatrixFactorizationUDTF.java:92-380,
mf/MatrixFactorizationSGDUDTF.java:33-65, mf/MatrixFactorizationAdaGradUDTF.java:34-125,
mf/BPRMatrixFactorizationUDTF.java:65-416, mf/FactorizedModel.java:45-120):

- rating model  r̂ = mu + Bu + Bi + Pu·Qi  (bias clause optional)
- SGD:      Qi += eta*(err*Pu - lambda*Qi); Pu += eta*(err*Qi - lambda*Pu)
            (both against the pre-update "probe" copies, ref: :280-296)
- AdaGrad:  per-element accumulated squared gradients with the x100 scaling
            trick, eta = eta0/sqrt(eps + G) (ref: MatrixFactorizationAdaGradUDTF.java:111-123)
- BPR:      triple (u, i, j): x_uij = (Bi + Pu·Qi) - (Bj + Pu·Qj),
            dloss in {sigmoid, logistic, lnLogistic};
            Pu += eta*(dloss*(Qi - Qj) - regU*Pu); Qi += eta*(dloss*Pu - regI*Qi);
            Qj += eta*(-dloss*Pu - regJ*Qj); item biases likewise
            (ref: BPRMatrixFactorizationUDTF.java:311-416)

TPU-first: user/item tables are dense [U, k]/[I, k] HBM embedding tables
(replacing IntOpenHashMap<Rating[]>); a training row is two row-gathers, the
update two row-scatter-adds — batched across B rows in minibatch mode. Epoch
replay re-runs staged arrays (replaces the 64KiB NIO disk spill, ref: :92,203).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..ops.convergence import ConversionState
from ..ops.eta import EtaEstimator, get_eta
from ..utils.options import Options


@struct.dataclass
class MFState:
    P: jnp.ndarray  # [U, k]
    Q: jnp.ndarray  # [I, k]
    Bu: jnp.ndarray  # [U]
    Bi: jnp.ndarray  # [I]
    mu: jnp.ndarray  # []
    P_gg: Optional[jnp.ndarray]  # [U, k] adagrad accumulators (scaled)
    Q_gg: Optional[jnp.ndarray]
    touched_u: jnp.ndarray  # [U] int8
    touched_i: jnp.ndarray  # [I] int8
    step: jnp.ndarray  # [] int32


@dataclass(frozen=True)
class MFHyper:
    factor: int = 10
    lambda_: float = 0.03
    mu: float = 0.0
    update_mean: bool = False
    use_bias: bool = True
    rankinit: str = "random"
    maxval: float = 1.0
    min_init_stddev: float = 0.1
    eta: EtaEstimator = EtaEstimator("invscaling", 0.2, power_t=0.1)
    # adagrad
    adagrad: bool = False
    eps: float = 1.0
    scaling: float = 100.0
    seed: int = 31


def init_mf_state(num_users: int, num_items: int, hyper: MFHyper) -> MFState:
    k = hyper.factor
    key = jax.random.PRNGKey(hyper.seed)
    ku, ki = jax.random.split(key)
    if hyper.rankinit == "gaussian":
        P = jax.random.normal(ku, (num_users, k)) * hyper.min_init_stddev
        Q = jax.random.normal(ki, (num_items, k)) * hyper.min_init_stddev
    else:  # 'random' uniform in [0, maxval/k-ish] (ref: Rating.rand init)
        P = jax.random.uniform(ku, (num_users, k), maxval=hyper.maxval)
        Q = jax.random.uniform(ki, (num_items, k), maxval=hyper.maxval)
    gg = (jnp.zeros((num_users, k)), jnp.zeros((num_items, k))) if hyper.adagrad \
        else (None, None)
    return MFState(
        P=P.astype(jnp.float32), Q=Q.astype(jnp.float32),
        Bu=jnp.zeros((num_users,), jnp.float32),
        Bi=jnp.zeros((num_items,), jnp.float32),
        mu=jnp.asarray(hyper.mu, jnp.float32),
        P_gg=gg[0], Q_gg=gg[1],
        touched_u=jnp.zeros((num_users,), jnp.int8),
        touched_i=jnp.zeros((num_items,), jnp.int8),
        step=jnp.zeros((), jnp.int32),
    )


def make_mf_step(hyper: MFHyper, mode: str = "minibatch",
                 jit: bool = True):
    """Rating-MF block update over (users [B], items [B], ratings [B])."""

    def row_deltas(st: MFState, u, i, r, t):
        eta = hyper.eta.eta(t)
        Pu = st.P[u]
        Qi = st.Q[i]
        bu = st.Bu[u] if hyper.use_bias else 0.0
        bi = st.Bi[i] if hyper.use_bias else 0.0
        pred = st.mu + bu + bi + jnp.dot(Pu, Qi)
        err = r - pred
        lam = hyper.lambda_
        gq = err * Pu - lam * Qi
        gp = err * Qi - lam * Pu
        if hyper.adagrad:
            # scaled accumulator trick (ref: MatrixFactorizationAdaGradUDTF.java:111-123)
            ggp = st.P_gg[u] + gp * (gp / hyper.scaling)
            ggq = st.Q_gg[i] + gq * (gq / hyper.scaling)
            eta_p = hyper.eta.eta0 / jnp.sqrt(hyper.eps + ggp * hyper.scaling)
            eta_q = hyper.eta.eta0 / jnp.sqrt(hyper.eps + ggq * hyper.scaling)
            dP, dQ = eta_p * gp, eta_q * gq
            dggp, dggq = gp * (gp / hyper.scaling), gq * (gq / hyper.scaling)
        else:
            dP, dQ = eta * gp, eta * gq
            dggp = dggq = None
        dbu = eta * (err - lam * bu) if hyper.use_bias else 0.0
        dbi = eta * (err - lam * bi) if hyper.use_bias else 0.0
        dmu = eta * err if (hyper.use_bias and hyper.update_mean) else 0.0
        loss = err * err
        return dP, dQ, dbu, dbi, dmu, dggp, dggq, loss

    def apply(st: MFState, u, i, dP, dQ, dbu, dbi, dmu, dggp, dggq, nb):
        st = st.replace(
            P=st.P.at[u].add(dP),
            Q=st.Q.at[i].add(dQ),
            touched_u=st.touched_u.at[u].set(1),
            touched_i=st.touched_i.at[i].set(1),
            step=st.step + nb,
        )
        if hyper.use_bias:
            st = st.replace(Bu=st.Bu.at[u].add(dbu), Bi=st.Bi.at[i].add(dbi),
                            mu=st.mu + jnp.sum(dmu))
        if hyper.adagrad:
            st = st.replace(P_gg=st.P_gg.at[u].add(dggp), Q_gg=st.Q_gg.at[i].add(dggq))
        return st

    def scan_step(state: MFState, users, items, ratings):
        def body(st, row):
            u, i, r = row
            t = (st.step + 1).astype(jnp.float32)
            dP, dQ, dbu, dbi, dmu, dggp, dggq, loss = row_deltas(st, u, i, r, t)
            return apply(st, u, i, dP, dQ, dbu, dbi, dmu, dggp, dggq, 1), loss

        state, losses = jax.lax.scan(body, state, (users, items, ratings))
        return state, jnp.sum(losses)

    def minibatch_step(state: MFState, users, items, ratings):
        b = users.shape[0]
        ts = (state.step + 1 + jnp.arange(b)).astype(jnp.float32)
        dP, dQ, dbu, dbi, dmu, dggp, dggq, loss = jax.vmap(
            lambda u, i, r, t: row_deltas(state, u, i, r, t))(users, items, ratings, ts)
        return apply(state, users, items, dP, dQ, dbu, dbi, dmu, dggp, dggq, b), \
            jnp.sum(loss)

    step = scan_step if mode == "scan" else minibatch_step
    # jit=False returns the raw traceable fn for embedding in an outer scan
    # (whole-epoch lax.scan over staged blocks, scripts/bench_mf.py)
    return jax.jit(step, donate_argnums=(0,)) if jit else step


def make_bpr_step(hyper: "BPRHyper", mode: str = "minibatch",
                  jit: bool = True):
    def dloss_fn(x):
        if hyper.loss == "sigmoid":
            return 1.0 / (1.0 + jnp.exp(x))
        if hyper.loss == "logistic":
            s = jax.nn.sigmoid(x)
            return s * (1.0 - s)
        # lnLogistic (default): e^-x / (1 + e^-x) = sigmoid(-x)
        return jax.nn.sigmoid(-x)

    def loss_fn(x):
        if hyper.loss == "lnLogistic":
            return jnp.logaddexp(0.0, -x)  # -ln sigmoid(x)
        return -x  # proxy

    def row_deltas(st: MFState, u, i, j, t):
        eta = hyper.eta.eta(t)
        Pu, Qi, Qj = st.P[u], st.Q[i], st.Q[j]
        bi = st.Bi[i] if hyper.use_bias else 0.0
        bj = st.Bi[j] if hyper.use_bias else 0.0
        x_uij = (bi + jnp.dot(Pu, Qi)) - (bj + jnp.dot(Pu, Qj))
        g = dloss_fn(x_uij)
        dP = eta * (g * (Qi - Qj) - hyper.reg_u * Pu)
        dQi = eta * (g * Pu - hyper.reg_i * Qi)
        dQj = eta * (-g * Pu - hyper.reg_j * Qj)
        dbi = eta * (g - hyper.reg_bias * bi) if hyper.use_bias else 0.0
        dbj = eta * (-g - hyper.reg_bias * bj) if hyper.use_bias else 0.0
        return dP, dQi, dQj, dbi, dbj, loss_fn(x_uij)

    def apply(st, u, i, j, dP, dQi, dQj, dbi, dbj, nb):
        st = st.replace(
            P=st.P.at[u].add(dP),
            Q=st.Q.at[i].add(dQi).at[j].add(dQj),
            touched_u=st.touched_u.at[u].set(1),
            touched_i=st.touched_i.at[i].set(1).at[j].set(1),
            step=st.step + nb,
        )
        if hyper.use_bias:
            st = st.replace(Bi=st.Bi.at[i].add(dbi).at[j].add(dbj))
        return st

    def scan_step(state, users, pos, neg):
        def body(st, row):
            u, i, j = row
            t = (st.step + 1).astype(jnp.float32)
            d = row_deltas(st, u, i, j, t)
            return apply(st, u, i, j, *d[:-1], 1), d[-1]

        state, losses = jax.lax.scan(body, state, (users, pos, neg))
        return state, jnp.sum(losses)

    def minibatch_step(state, users, pos, neg):
        b = users.shape[0]
        ts = (state.step + 1 + jnp.arange(b)).astype(jnp.float32)
        dP, dQi, dQj, dbi, dbj, loss = jax.vmap(
            lambda u, i, j, t: row_deltas(state, u, i, j, t))(users, pos, neg, ts)
        return apply(state, users, pos, neg, dP, dQi, dQj, dbi, dbj, b), jnp.sum(loss)

    step = scan_step if mode == "scan" else minibatch_step
    # jit=False returns the raw traceable fn for embedding in an outer scan
    # (whole-epoch lax.scan over staged blocks, scripts/bench_mf.py)
    return jax.jit(step, donate_argnums=(0,)) if jit else step


@dataclass(frozen=True)
class BPRHyper:
    factor: int = 10
    loss: str = "lnLogistic"
    reg_u: float = 0.0025
    reg_i: float = 0.0025
    reg_j: float = 0.00125
    reg_bias: float = 0.01
    use_bias: bool = True
    rankinit: str = "random"
    maxval: float = 1.0
    min_init_stddev: float = 0.1
    eta: EtaEstimator = EtaEstimator("invscaling", 0.3, power_t=0.1)
    seed: int = 31

    # adapters so init_mf_state can be reused
    @property
    def mu(self):
        return 0.0

    @property
    def adagrad(self):
        return False


@dataclass
class TrainedMFModel:
    state: MFState
    use_bias: bool

    def predict(self, users, items) -> np.ndarray:
        """r̂ = mu + Bu + Bi + Pu·Qi (ref: MFPredictionUDF.java:33)."""
        u = np.asarray(users, dtype=np.int64)
        i = np.asarray(items, dtype=np.int64)
        P = np.asarray(self.state.P)[u]
        Q = np.asarray(self.state.Q)[i]
        out = np.sum(P * Q, axis=-1) + float(self.state.mu)
        if self.use_bias:
            out = out + np.asarray(self.state.Bu)[u] + np.asarray(self.state.Bi)[i]
        return out

    def predict_bpr(self, users, items) -> np.ndarray:
        """BPR score = Bi + Pu·Qi (ref: BPRMFPredictionUDF.java)."""
        u = np.asarray(users, dtype=np.int64)
        i = np.asarray(items, dtype=np.int64)
        out = np.sum(np.asarray(self.state.P)[u] * np.asarray(self.state.Q)[i], axis=-1)
        if self.use_bias:
            out = out + np.asarray(self.state.Bi)[i]
        return out

    def model_rows(self):
        """(idx, Pu, Qi, Bu, Bi, mu) — the reference's per-index emission
        (ref: OnlineMatrixFactorizationUDTF close/forward)."""
        tu = np.nonzero(np.asarray(self.state.touched_u))[0]
        ti = np.nonzero(np.asarray(self.state.touched_i))[0]
        return {
            "users": (tu, np.asarray(self.state.P)[tu], np.asarray(self.state.Bu)[tu]),
            "items": (ti, np.asarray(self.state.Q)[ti], np.asarray(self.state.Bi)[ti]),
            "mu": float(self.state.mu),
        }


def _mf_options(bpr: bool = False) -> Options:
    o = Options()
    o.add("k", "factor", True, "Number of latent factors [default: 10]", default=10,
          type=int)
    o.add("iter", "iterations", True, "Iterations [default: 1]",
          default=30 if bpr else 1, type=int)
    o.add("rankinit", None, True, "Init strategy [random, gaussian]", default="random")
    o.add("maxval", "max_init_value", True, "Max initial value [default: 1.0]",
          default=1.0, type=float)
    o.add("min_init_stddev", None, True, "Gaussian init stddev [default: 0.1]",
          default=0.1, type=float)
    o.add("disable_cv", "disable_cvtest", False, "Disable convergence check")
    o.add("cv_rate", "convergence_rate", True, "Convergence rate [default: 0.005]",
          default=0.005, type=float)
    o.add("disable_bias", "no_bias", False, "Turn off bias clause")
    o.add("eta", None, True, "Fixed learning rate", type=float)
    o.add("eta0", None, True, "Initial learning rate", type=float)
    o.add("t", "total_steps", True, "Total steps", type=int)
    o.add("power_t", None, True, "Inverse scaling exponent [default 0.1]",
          default=0.1, type=float)
    o.add("boldDriver", "bold_driver", False, "Bold driver eta")
    o.add("seed", None, True, "Init seed", default=31, type=int)
    o.add("mini_batch", None, True, "Mini batch size [default 1 = exact scan]",
          default=1, type=int)
    if bpr:
        o.add("loss", "loss_function", True,
              "Loss [lnLogistic (default), logistic, sigmoid]", default="lnLogistic")
        o.add("reg", "lambda", True, "Regularization factor [default 0.0025]",
              default=0.0025, type=float)
        o.add("reg_u", "reg_user", True, "User regularization", type=float)
        o.add("reg_i", "reg_item", True, "Positive item regularization", type=float)
        o.add("reg_j", None, True, "Negative item regularization", type=float)
        o.add("reg_bias", None, True, "Bias regularization [default 0.01]",
              default=0.01, type=float)
    else:
        o.add("r", "lambda", True, "Regularization factor [default: 0.03]",
              default=0.03, type=float)
        o.add("mu", "mean_rating", True, "Mean rating [default: 0.0]", default=0.0,
              type=float)
        o.add("update_mean", "update_mu", False, "Update the mean rating")
        o.add("eps", None, True, "AdaGrad eps [default 1.0]", default=1.0, type=float)
        o.add("scale", None, True, "AdaGrad scaling [default 100]", default=100.0,
              type=float)
    return o


def _dims_from(idx, given: Optional[int]) -> int:
    return given if given is not None else int(np.max(idx)) + 1


def _train_rating_mf(users, items, ratings, options: Optional[str], adagrad: bool,
                     name: str, num_users=None, num_items=None) -> TrainedMFModel:
    cl = _mf_options().parse(options, name)
    default_eta0 = 1.0 if adagrad else 0.2
    hyper = MFHyper(
        factor=cl.get_int("k", 10),
        lambda_=cl.get_float("r", 0.03),
        mu=cl.get_float("mu", 0.0),
        update_mean=cl.has("update_mean"),
        use_bias=not cl.has("disable_bias"),
        rankinit=cl.get("rankinit", "random"),
        maxval=cl.get_float("maxval", 1.0),
        min_init_stddev=cl.get_float("min_init_stddev", 0.1),
        eta=get_eta(cl, default_eta0),
        adagrad=adagrad,
        eps=cl.get_float("eps", 1.0),
        scaling=cl.get_float("scale", 100.0),
        seed=cl.get_int("seed", 31),
    )
    u = np.asarray(users, dtype=np.int32)
    i = np.asarray(items, dtype=np.int32)
    r = np.asarray(ratings, dtype=np.float32)
    state = init_mf_state(_dims_from(u, num_users), _dims_from(i, num_items), hyper)
    mini_batch = cl.get_int("mini_batch", 1)
    mode = "minibatch" if mini_batch > 1 else "scan"
    step = make_mf_step(hyper, mode)
    iters = cl.get_int("iter", 1)
    conv = ConversionState(not cl.has("disable_cv"), cl.get_float("cv_rate", 0.005))
    block = mini_batch if mode == "minibatch" else 8192
    n = len(u)
    for it in range(max(1, iters)):
        epoch_loss = 0.0
        for s in range(0, n, block):
            e = min(s + block, n)
            state, loss = step(state, u[s:e], i[s:e], r[s:e])
            epoch_loss += float(loss)
        conv.incr_loss(epoch_loss)
        if iters > 1 and conv.is_converged(n):
            break
    return TrainedMFModel(state=state, use_bias=hyper.use_bias)


def train_mf_sgd(users, items, ratings, options: Optional[str] = None, **kw):
    return _train_rating_mf(users, items, ratings, options, False, "train_mf_sgd", **kw)


def train_mf_adagrad(users, items, ratings, options: Optional[str] = None, **kw):
    return _train_rating_mf(users, items, ratings, options, True, "train_mf_adagrad", **kw)


def train_bprmf(users, pos_items, neg_items, options: Optional[str] = None,
                num_users=None, num_items=None) -> TrainedMFModel:
    cl = _mf_options(bpr=True).parse(options, "train_bprmf")
    reg = cl.get_float("reg", 0.0025)
    reg_i = cl.get_float("reg_i") if cl.has("reg_i") else reg
    hyper = BPRHyper(
        factor=cl.get_int("k", 10),
        loss=cl.get("loss", "lnLogistic"),
        reg_u=cl.get_float("reg_u") if cl.has("reg_u") else reg,
        reg_i=reg_i,
        reg_j=cl.get_float("reg_j") if cl.has("reg_j") else reg_i / 2.0,
        reg_bias=cl.get_float("reg_bias", 0.01),
        use_bias=not cl.has("disable_bias"),
        rankinit=cl.get("rankinit", "random"),
        maxval=cl.get_float("maxval", 1.0),
        min_init_stddev=cl.get_float("min_init_stddev", 0.1),
        eta=get_eta(cl, 0.3),
        seed=cl.get_int("seed", 31),
    )
    u = np.asarray(users, dtype=np.int32)
    i = np.asarray(pos_items, dtype=np.int32)
    j = np.asarray(neg_items, dtype=np.int32)
    nu = _dims_from(u, num_users)
    ni = _dims_from(np.concatenate([i, j]), num_items)
    mf_hyper = MFHyper(factor=hyper.factor, rankinit=hyper.rankinit,
                       maxval=hyper.maxval, min_init_stddev=hyper.min_init_stddev,
                       seed=hyper.seed)
    state = init_mf_state(nu, ni, mf_hyper)
    mini_batch = cl.get_int("mini_batch", 1)
    mode = "minibatch" if mini_batch > 1 else "scan"
    step = make_bpr_step(hyper, mode)
    iters = cl.get_int("iter", 30)
    conv = ConversionState(not cl.has("disable_cv"), cl.get_float("cv_rate", 0.005))
    block = mini_batch if mode == "minibatch" else 8192
    n = len(u)
    for it in range(max(1, iters)):
        epoch_loss = 0.0
        for s in range(0, n, block):
            e = min(s + block, n)
            state, loss = step(state, u[s:e], i[s:e], j[s:e])
            epoch_loss += float(loss)
        conv.incr_loss(epoch_loss)
        if iters > 1 and conv.is_converged(n):
            break
    return TrainedMFModel(state=state, use_bias=hyper.use_bias)


def mf_predict(Pu, Qi, Bu=0.0, Bi=0.0, mu=0.0) -> float:
    """`mf_predict(Pu, Qi[, Bu, Bi, mu])` (ref: mf/MFPredictionUDF.java:33)."""
    return float(np.dot(np.asarray(Pu), np.asarray(Qi)) + Bu + Bi + mu)


def bprmf_predict(Pu, Qi, Bi=0.0) -> float:
    """`bprmf_predict(Pu, Qi[, Bi])` (ref: mf/BPRMFPredictionUDF.java)."""
    return float(np.dot(np.asarray(Pu), np.asarray(Qi)) + Bi)
