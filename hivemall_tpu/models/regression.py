"""Online regressors: train_logistic_regr (logress) / train_adagrad_regr /
train_adadelta_regr / train_pa1_regr / train_pa1a_regr / train_pa2_regr /
train_pa2a_regr / train_arow_regr / train_arowe_regr / train_arowe2_regr.

Update formulas mirror the reference:
- Logress: SGD on the logistic "gradient" target - sigmoid(p) with the
  EtaEstimator schedules (ref: regression/LogressUDTF.java:35-83,
  common/EtaEstimator.java).
- AdaGrad: per-feature eta / sqrt(eps + G) with the x100 scaling trick
  (ref: regression/AdaGradUDTF.java:97-143).
- AdaDelta: rho/eps accumulators over g^2 and dx^2
  (ref: regression/AdaDeltaUDTF.java:97-140).
- PA regressors: epsilon-insensitive loss, eta = min(C, loss/|x|^2) (PA1) or
  loss/(|x|^2 + 1/2C) (PA2); the "a" variants scale epsilon by the running
  target stddev (ref: regression/PassiveAggressiveRegressionUDTF.java:39-216).
- AROW regression + e/e2 variants (ref: regression/AROWRegressionUDTF.java:41-232).

The mini-batch path (`-mini_batch`) reproduces RegressionBaseUDTF's
accumulate-then-apply-average semantics (ref: RegressionBaseUDTF.java:236-295).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.engine import Rule, RuleOutput
from ..ops.eta import get_eta
from ..utils.options import Options
from .base import FeatureRows, TrainedLinearModel, base_options, fit_linear

FLOAT_MAX = 3.4028235e38  # Java Float.MAX_VALUE (PA default aggressiveness)


def _sigmoid(x):
    return 1.0 / (1.0 + jnp.exp(-x))


def _logistic_grad(target, predicted):
    # LossFunctions.logisticLoss(target, predicted) (ref: LossFunctions.java:381-392)
    return jnp.where(predicted > -100.0, target - _sigmoid(predicted), target)


# ---------------------------------------------------------------- logress

def _make_logress_rule(eta_est):
    def update(ctx, hyper):
        gradient = _logistic_grad(ctx.y, ctx.score)
        coeff = eta_est.eta(ctx.t) * gradient  # (ref: LogressUDTF.java:78-82)
        dw = coeff * ctx.val
        loss = gradient * gradient  # squared residual proxy for convergence
        return RuleOutput(dw=dw, loss=loss, updated=jnp.array(True))

    return Rule("logress", update, is_regression=True)


def train_logistic_regr(features: FeatureRows, targets, options: Optional[str] = None, **kw):
    o = base_options()
    o.add("t", "total_steps", True, "total of n_samples * epochs time steps", type=int)
    o.add("power_t", None, True, "Exponent for inverse scaling learning rate [default 0.1]",
          default=0.1, type=float)
    o.add("eta0", None, True, "Initial learning rate [default 0.1]", default=0.1, type=float)
    o.add("eta", None, True, "Fixed learning rate", type=float)
    o.add("boldDriver", None, False, "Use bold-driver eta adjustment")
    cl = o.parse(options, "train_logistic_regr")
    rule = _make_logress_rule(get_eta(cl))
    return fit_linear(rule, {}, cl, features, targets, **kw)


train_logress = train_logistic_regr


# ---------------------------------------------------------------- adagrad

def _adagrad_update(ctx, hyper):
    gradient = _logistic_grad(ctx.y, ctx.score)
    g_g = gradient * (gradient / hyper["scale"])  # (ref: AdaGradUDTF.java:104)
    new_sqg = ctx.slots["sum_sqgrad"] + g_g
    eta_t = hyper["eta"] / jnp.sqrt(hyper["eps"] + new_sqg * hyper["scale"])  # (:139-143)
    dw = eta_t * gradient * ctx.val
    # slot delta only on lanes with a real feature value is not needed: padded
    # lanes are dropped by the scatter. g_g is lane-independent (broadcast).
    dslots = {"sum_sqgrad": jnp.broadcast_to(g_g, ctx.val.shape)}
    return RuleOutput(dw=dw, loss=gradient * gradient, updated=jnp.array(True), dslots=dslots)


ADAGRAD_REGR = Rule("adagrad_regr", _adagrad_update, slot_names=("sum_sqgrad",),
                    is_regression=True, slot_merge=(("sum_sqgrad", "sum"),))


def train_adagrad_regr(features: FeatureRows, targets, options: Optional[str] = None, **kw):
    o = base_options()
    o.add("eta", "eta0", True, "Initial learning rate [default 1.0]", default=1.0, type=float)
    o.add("eps", None, True, "Denominator constant [default 1.0]", default=1.0, type=float)
    o.add("scale", None, True, "Internal scaling factor [default 100]", default=100.0,
          type=float)
    cl = o.parse(options, "train_adagrad_regr")
    hyper = {"eta": cl.get_float("eta", 1.0), "eps": cl.get_float("eps", 1.0),
             "scale": cl.get_float("scale", 100.0)}
    return fit_linear(ADAGRAD_REGR, hyper, cl, features, targets, **kw)


# ---------------------------------------------------------------- adadelta

def _adadelta_update(ctx, hyper):
    decay, eps, scale = hyper["rho"], hyper["eps"], hyper["scale"]
    gradient = _logistic_grad(ctx.y, ctx.score)
    g_g = gradient * (gradient / scale)
    old_sqg = ctx.slots["sum_sqgrad"]
    old_sqdx = ctx.slots["sum_sq_dx"]
    new_sqg = decay * old_sqg + (1.0 - decay) * g_g
    dx = jnp.sqrt((old_sqdx + eps) / (old_sqg * scale + eps)) * gradient
    new_sqdx = decay * old_sqdx + (1.0 - decay) * dx * dx
    # (ref: AdaDeltaUDTF.java:120-140)
    dw = dx * ctx.val
    dslots = {"sum_sqgrad": new_sqg - old_sqg, "sum_sq_dx": new_sqdx - old_sqdx}
    return RuleOutput(dw=dw, loss=gradient * gradient, updated=jnp.array(True), dslots=dslots)


ADADELTA_REGR = Rule("adadelta_regr", _adadelta_update,
                     slot_names=("sum_sqgrad", "sum_sq_dx"), is_regression=True,
                     # rho-decayed EMAs, not sums: mean across replicas
                     slot_merge=(("sum_sqgrad", "mean"), ("sum_sq_dx", "mean")))


def train_adadelta_regr(features: FeatureRows, targets, options: Optional[str] = None, **kw):
    o = base_options()
    o.add("rho", "decay", True, "Decay rate [default 0.95]", default=0.95, type=float)
    o.add("eps", None, True, "Denominator constant [default 1e-6]", default=1e-6, type=float)
    o.add("scale", None, True, "Internal scaling factor [default 100]", default=100.0,
          type=float)
    cl = o.parse(options, "train_adadelta_regr")
    hyper = {"rho": cl.get_float("rho", 0.95), "eps": cl.get_float("eps", 1e-6),
             "scale": cl.get_float("scale", 100.0)}
    return fit_linear(ADADELTA_REGR, hyper, cl, features, targets, **kw)


# ----------------------------------------------------- Welford target stddev

def _welford_pre_row(gl, y):
    # single-observation Welford step (ref: common/OnlineVariance.java:24-44)
    n = gl["n"] + 1.0
    delta = y - gl["mean"]
    mean = gl["mean"] + delta / n
    m2 = gl["m2"] + delta * (y - mean)
    return {"n": n, "mean": mean, "m2": m2}


def _welford_pre_batch(gl, labels):
    # Chan et al. parallel merge of the block's stats into the running stats
    b = jnp.asarray(labels.shape[0], dtype=jnp.float32)
    bmean = jnp.mean(labels)
    bm2 = jnp.sum((labels - bmean) ** 2)
    n = gl["n"]
    tot = n + b
    delta = bmean - gl["mean"]
    mean = gl["mean"] + delta * b / tot
    m2 = gl["m2"] + bm2 + delta * delta * n * b / tot
    return {"n": tot, "mean": mean, "m2": m2}


def _stddev(gl):
    var = jnp.where(gl["n"] > 1.0, gl["m2"] / jnp.maximum(gl["n"] - 1.0, 1.0), 0.0)
    return jnp.sqrt(jnp.maximum(var, 0.0))


# ------------------------------------------------------------ PA regressors

def _pa_regr_update_factory(variant: str, adaptive: bool):
    def update(ctx, hyper):
        eps = hyper["epsilon"] * (_stddev(ctx.globals) if adaptive else 1.0)
        predicted = ctx.score
        loss = jnp.maximum(0.0, jnp.abs(ctx.y - predicted) - eps)
        sign = jnp.where(ctx.y - predicted > 0.0, 1.0, -1.0)
        if variant == "pa1":
            eta = jnp.minimum(hyper["c"], jnp.where(ctx.sq_norm == 0.0, FLOAT_MAX,
                                                    loss / jnp.maximum(ctx.sq_norm, 1e-38)))
        else:  # pa2
            eta = loss / (ctx.sq_norm + 0.5 / hyper["c"])
        coeff = sign * eta
        updated = (loss > 0.0) & jnp.isfinite(coeff)
        dw = jnp.where(updated, coeff * ctx.val, 0.0)
        return RuleOutput(dw=dw, loss=loss, updated=updated)

    return update


def _pa_regr_rule(variant: str, adaptive: bool) -> Rule:
    kw = {}
    if adaptive:
        kw = dict(global_names=("n", "mean", "m2"), pre_row=_welford_pre_row,
                  pre_batch=_welford_pre_batch)
    return Rule(f"{variant}{'a' if adaptive else ''}_regr",
                _pa_regr_update_factory(variant, adaptive), is_regression=True, **kw)


PA1_REGR = _pa_regr_rule("pa1", False)
PA1A_REGR = _pa_regr_rule("pa1", True)
PA2_REGR = _pa_regr_rule("pa2", False)
PA2A_REGR = _pa_regr_rule("pa2", True)


def _pa_regr_train(rule: Rule, name: str, default_c: float):
    def train(features: FeatureRows, targets, options: Optional[str] = None, **kw):
        o = base_options()
        o.add("c", "aggressiveness", True, "Aggressiveness parameter C", default=default_c,
              type=float)
        o.add("e", "epsilon", True, "Sensitivity to prediction mistakes [default 0.1]",
              default=0.1, type=float)
        cl = o.parse(options, name)
        hyper = {"c": cl.get_float("c", default_c), "epsilon": cl.get_float("e", 0.1)}
        return fit_linear(rule, hyper, cl, features, targets, **kw)

    train.__name__ = name
    return train


# PA1 default C = Float.MAX_VALUE; PA2 default C = 1
# (ref: PassiveAggressiveRegressionUDTF.java:94-98, 174-178)
train_pa1_regr = _pa_regr_train(PA1_REGR, "train_pa1_regr", FLOAT_MAX)
train_pa1a_regr = _pa_regr_train(PA1A_REGR, "train_pa1a_regr", FLOAT_MAX)
train_pa2_regr = _pa_regr_train(PA2_REGR, "train_pa2_regr", 1.0)
train_pa2a_regr = _pa_regr_train(PA2A_REGR, "train_pa2a_regr", 1.0)


# ---------------------------------------------------------- AROW regressors

def _arow_regr_update_factory(variant: str):
    def update(ctx, hyper):
        predicted = ctx.score
        beta = 1.0 / (ctx.variance + hyper["r"])
        cv = ctx.cov * ctx.val
        if variant == "arow":
            # always updates; coeff = (target - predicted)
            # (ref: AROWRegressionUDTF.java:90-143)
            coeff = ctx.y - predicted
            updated = jnp.array(True)
            loss = coeff * coeff
        else:
            # e / e2: epsilon-insensitive gate (ref: :176-190)
            eps = hyper["epsilon"] * (_stddev(ctx.globals) if variant == "arowe2" else 1.0)
            l = jnp.maximum(0.0, jnp.abs(ctx.y - predicted) - eps)
            coeff = jnp.where(ctx.y - predicted > 0.0, l, -l)
            updated = l > 0.0
            loss = l
        dw = jnp.where(updated, coeff * cv * beta, 0.0)
        dcov = jnp.where(updated, -beta * cv * cv, 0.0)
        return RuleOutput(dw=dw, loss=loss, updated=updated, dcov=dcov)

    return update


AROW_REGR = Rule("arow_regr", _arow_regr_update_factory("arow"), use_covariance=True,
                 is_regression=True)
AROWE_REGR = Rule("arowe_regr", _arow_regr_update_factory("arowe"), use_covariance=True,
                  is_regression=True)
AROWE2_REGR = Rule("arowe2_regr", _arow_regr_update_factory("arowe2"), use_covariance=True,
                   is_regression=True, global_names=("n", "mean", "m2"),
                   pre_row=_welford_pre_row, pre_batch=_welford_pre_batch)


def _arow_regr_train(rule: Rule, name: str, with_eps: bool):
    def train(features: FeatureRows, targets, options: Optional[str] = None, **kw):
        o = base_options()
        o.add("r", "regularization", True, "Regularization parameter r > 0 [default 0.1]",
              default=0.1, type=float)
        if with_eps:
            o.add("e", "epsilon", True, "Sensitivity to prediction mistakes [default 0.1]",
                  default=0.1, type=float)
        cl = o.parse(options, name)
        hyper = {"r": cl.get_float("r", 0.1)}
        if with_eps:
            hyper["epsilon"] = cl.get_float("e", 0.1)
        return fit_linear(rule, hyper, cl, features, targets, **kw)

    train.__name__ = name
    return train


train_arow_regr = _arow_regr_train(AROW_REGR, "train_arow_regr", False)
train_arowe_regr = _arow_regr_train(AROWE_REGR, "train_arowe_regr", True)
train_arowe2_regr = _arow_regr_train(AROWE2_REGR, "train_arowe2_regr", True)
