"""Field-aware Factorization Machines: train_ffm / ffm_predict.

Mirrors the reference FFM subsystem (ref: fm/FieldAwareFactorizationMachineUDTF.java:57-200,
fm/FieldAwareFactorizationMachineModel.java:40-200, fm/FFMStringFeatureMapModel.java:32-200,
fm/FFMHyperParameters.java):

- prediction  p = [w0] + [sum_i w_i x_i] + sum_{i<j} <V_{i,f_j}, V_{j,f_i}> x_i x_j
  (global bias and linear term both optional: -w0 / -disable_wi)
- V updates: SGD with per-factor L2, AdaGrad per-entry learning rate
  eta0_V / sqrt(eps + gg) using the accumulator value BEFORE the current
  gradient (ref: etaV, FieldAwareFactorizationMachineModel.java:126-134)
- W updates: FTRL by default (z/n accumulators, L1 sparsity; ref:
  updateWiFTRL, FFMStringFeatureMapModel.java:133-157), plain SGD with
  -disable_ftrl
- gradient note: the correct pairwise gradient d p/d V_{i,f_j,f} =
  x_i x_j V_{j,f_i,f} is used here; the reference's sumVfX multiplies by x_i
  instead of x_j (FieldAwareFactorizationMachineModel.java:170-181), which
  coincides exactly on the usual FFM encoding where all feature values are 1.

TPU-first: the reference's (feature, field) hash-map entries become ONE dense
[Dv, k] HBM table addressed by a mixed pair-hash (the standard hashed-FFM
trick); a row's pairwise term is a [K, K, k] gather + einsum, its V gradient
one scatter-add of K*K rows.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..core.batch import pad_to_bucket
from ..ops.scatter import scatter_rows_flat
from ..ops.convergence import ConversionState
from ..ops.eta import EtaEstimator, get_eta
from ..utils.feature import FMFeature
from ..utils.options import Options
from .fm import _fm_options

_MIX1 = 0x9E3779B1
_MIX2 = 0x85EBCA6B


def pair_hash(feature_idx, field, dv: int):
    """Deterministic (feature, field) -> V-table row. Works identically in
    numpy and jnp (int32 wraparound mixing)."""
    h = feature_idx.astype(jnp.uint32) * jnp.uint32(_MIX1) \
        + field.astype(jnp.uint32) * jnp.uint32(_MIX2)
    h ^= h >> 15
    h *= jnp.uint32(0x2C1B3C6D)
    h ^= h >> 12
    return (h % jnp.uint32(dv)).astype(jnp.int32)


@struct.dataclass
class FFMState:
    w0: jnp.ndarray  # []
    w: jnp.ndarray  # [D]
    z: jnp.ndarray  # [D] FTRL z
    n: jnp.ndarray  # [D] FTRL n (or adagrad gg for SGD-W — unused then)
    v: jnp.ndarray  # [Dv, k]
    v_gg: jnp.ndarray  # [Dv] adagrad accumulator for V
    touched: jnp.ndarray  # [D] int8
    step: jnp.ndarray  # []


@dataclass(frozen=True)
class FFMHyper:
    factors: int = 4
    classification: bool = True
    lambda_w: float = 0.01
    lambda_v: float = 0.01
    global_bias: bool = False
    linear_coeff: bool = True
    use_ftrl: bool = True
    use_adagrad: bool = True
    eta0_v: float = 1.0
    eps: float = 1.0
    alpha: float = 0.1  # FTRL
    beta: float = 1.0
    lambda1: float = 0.1
    lambda2: float = 0.01
    sigma: float = 0.1
    num_features: int = 1 << 21  # -feature_hashing 21 default
    num_fields: int = 1024
    v_dims: int = 1 << 22
    eta: EtaEstimator = EtaEstimator("invscaling", 0.2, power_t=0.1)
    min_target: float = -3.0e38
    max_target: float = 3.0e38
    seed: int = 31


def init_ffm_state(hyper: FFMHyper) -> FFMState:
    key = jax.random.PRNGKey(hyper.seed)
    d, dv, k = hyper.num_features, hyper.v_dims, hyper.factors
    return FFMState(
        w0=jnp.zeros(()),
        w=jnp.zeros((d,)),
        z=jnp.zeros((d,)),
        n=jnp.zeros((d,)),
        v=jax.random.normal(key, (dv, k)) * hyper.sigma,
        v_gg=jnp.zeros((dv,)),
        touched=jnp.zeros((d,), jnp.int8),
        step=jnp.zeros((), jnp.int32),
    )


def _row_pair_keys(idx, fields, dv):
    """[K] features -> [K, K] pair table rows: keys[i, j] = h(idx_i, field_j)."""
    return pair_hash(idx[:, None].astype(jnp.uint32),
                     jnp.broadcast_to(fields[None, :], (idx.shape[0], idx.shape[0]))
                     .astype(jnp.uint32), dv)


def _row_predict(state: FFMState, idx, val, fields, hyper: FFMHyper,
                 Vg=None, keys=None):
    K = idx.shape[0]
    if keys is None:
        keys = _row_pair_keys(idx, fields, hyper.v_dims)  # [K, K]
    if Vg is None:
        Vg = state.v[keys]  # [K, K, k]
    # pair mask: i < j and both lanes real (padded lanes have val 0)
    iu = jnp.triu_indices(K, 1)
    inter = jnp.einsum("ijf,jif->ij", Vg, Vg)  # <V_{i,fj}, V_{j,fi}>
    xx = val[:, None] * val[None, :]
    pair_term = jnp.sum(jnp.triu(inter * xx, 1))
    p = pair_term
    if hyper.linear_coeff:
        w = state.w.at[idx].get(mode="fill", fill_value=0.0)
        p = p + jnp.sum(w * val)
    if hyper.global_bias:
        p = p + state.w0
    return p, keys, Vg, xx


def sharded_ffm_gather(st: FFMState, idx, val, fields, hyper: FFMHyper,
                       shard_axis: str, stripe_w: int, stripe_v: int):
    """The ONE copy of the feature-sharded FFM row gather + prediction,
    shared by the sharded train step and the sharded serving path. Each
    device gathers the entries it owns of the row's [K, K, k] pair block
    (exactly one owner per hashed key) and ONE psum rebuilds the full block
    (and its gg) everywhere. Returns (p, local_keys, Vg, xx, gg, own)."""
    from ..core.striping import translate_to_stripe

    keys = _row_pair_keys(idx, fields, hyper.v_dims)
    dev = jax.lax.axis_index(shard_axis)
    lkeys = keys - dev * stripe_v
    owned = (lkeys >= 0) & (lkeys < stripe_v)
    lkeys = jnp.where(owned, lkeys, stripe_v)
    own = owned.astype(val.dtype)
    Vg, gg = jax.lax.psum(
        (st.v.at[lkeys].get(mode="fill", fill_value=0.0),
         st.v_gg.at[lkeys].get(mode="fill", fill_value=0.0)),
        shard_axis)
    xx = val[:, None] * val[None, :]
    inter = jnp.einsum("ijf,jif->ij", Vg, Vg)
    p = jnp.sum(jnp.triu(inter * xx, 1))
    if hyper.linear_coeff:
        lidx, vmask = translate_to_stripe(idx, val, shard_axis, stripe_w)
        w = st.w.at[lidx].get(mode="fill", fill_value=0.0)
        p = p + jax.lax.psum(jnp.sum(w * vmask), shard_axis)
    if hyper.global_bias:
        p = p + st.w0
    return p, lkeys, Vg, xx, gg, own


def make_ffm_step(hyper: FFMHyper, mode: str = "scan",
                  row_chunk: Optional[int] = None,
                  feature_shard: Optional[Tuple[str, int, int]] = None,
                  pack_v: Optional[bool] = None,
                  jit: bool = True,
                  update_backend: str = "xla"):
    """`row_chunk` (minibatch mode only) tiles the batch's K^2 pairwise work:
    the [B, K, K, k] dV / [B, K, K] gg activations are the FFM memory hot
    spot (256MB at B=16384, K=32, k=4 — grows with the square of the field
    count), so the batch is processed in chunks of `row_chunk` rows — every
    chunk computes against the SAME block-start parameters (identical
    accumulate-then-apply semantics, tested exact vs unchunked) and
    scatter-adds into the carried tables, bounding peak activation memory at
    [row_chunk, K, K, k].

    `feature_shard=(axis_name, stripe_w, stripe_v)` stripes the linear
    tables (w/z/n/touched, [num_features]) and the pairwise V tables
    (v/v_gg, [v_dims]) across the mesh. Unlike FM, a row's pairwise term
    needs CROSS-stripe products <V_{i,f_j}, V_{j,f_i}> — the two rows of a
    pair can live on different devices — so each device gathers the entries
    it owns of the row's [K, K, k] block (exactly one owner per hashed key)
    and ONE psum reconstructs the full block everywhere; updates scatter
    back owned entries only. Keys hash with the ORIGINAL v_dims, so the
    model is the same function as the unsharded one.

    `update_backend='mxu'` (local minibatch only) routes the pairwise
    [B*K*K] V+gg traffic — FFM's entire cost at CTR shapes — through the
    sorted-window MXU gather/scatter (ops/mxu_scatter.py): the packed
    [Dv, k+1] block table pads to a power-of-two lane count, ONE windowed
    gather serves the whole batch's pair blocks, and dV+dgg ride one
    windowed scatter whose id sort is shared with the gather's plan."""
    if update_backend not in ("xla", "mxu"):
        raise ValueError(f"unknown update_backend {update_backend!r}")
    if update_backend == "mxu":
        if mode != "minibatch" or feature_shard is not None:
            raise ValueError("update_backend='mxu' requires the local "
                             "minibatch path")
        if pack_v is False:
            raise ValueError("update_backend='mxu' rides the packed V+gg "
                             "table; pack_v=False contradicts it")
    use_mxu = update_backend == "mxu"

    if feature_shard is None:
        translate_w = None

        def predict_gather(st: FFMState, idx, val, fields, packed=None,
                           pg=None, keys=None):
            if pg is not None:
                # pre-gathered [K, K, k+1] pair block (the mxu path hoists
                # the whole batch's gather out of the vmap)
                Vg, gg = pg[..., :-1], pg[..., -1]
                p, _, _, xx = _row_predict(st, idx, val, fields, hyper,
                                           Vg=Vg, keys=keys)
            elif packed is None:
                p, keys, Vg, xx = _row_predict(st, idx, val, fields, hyper)
                gg = st.v_gg[keys]
            else:
                # v+gg interleaved [Dv, k+1]: ONE [K,K]-row gather yields
                # both — the separate scalar gg gather (K^2 scalars/row)
                # rides the V row gather for free (same borrowed-lane
                # pattern as FM; v5e cost model in PERF.md round 4c)
                keys = _row_pair_keys(idx, fields, hyper.v_dims)
                pg = packed[keys]  # [K, K, k+1]
                Vg, gg = pg[..., :-1], pg[..., -1]
                p, _, _, xx = _row_predict(st, idx, val, fields, hyper,
                                           Vg=Vg, keys=keys)
            own = jnp.ones(keys.shape, val.dtype)
            return p, keys, Vg, xx, gg, own
    else:
        from ..core.striping import translate_to_stripe

        shard_axis, stripe_w, stripe_v = feature_shard

        def translate_w(idx, val):
            return translate_to_stripe(idx, val, shard_axis, stripe_w)

        def predict_gather(st: FFMState, idx, val, fields, packed=None,
                           pg=None, keys=None):
            return sharded_ffm_gather(st, idx, val, fields, hyper,
                                      shard_axis, stripe_w, stripe_v)

    def dloss_fn(p, y):
        if hyper.classification:
            z = p * y
            return (jax.nn.sigmoid(z) - 1.0) * y, jnp.logaddexp(0.0, -z)
        pc = jnp.clip(p, hyper.min_target, hyper.max_target)
        return pc - y, 0.5 * (pc - y) ** 2

    def row_updates(st: FFMState, idx, val, fields, y, t, packed=None,
                    pg=None, keys=None):
        p, keys, Vg, xx, gg, own = predict_gather(st, idx, val, fields,
                                                  packed, pg, keys)
        g, loss = dloss_fn(p, y)
        K = idx.shape[0]
        # dV[i, j] = g * x_i x_j * V_{j, f_i} for i != j
        offdiag = 1.0 - jnp.eye(K)
        coeff = g * xx * offdiag  # [K, K]
        gradV = coeff[:, :, None] * jnp.transpose(Vg, (1, 0, 2))  # [K,K,k]
        # AdaGrad eta per (i,j) entry, using gg BEFORE this grad
        if hyper.use_adagrad:
            eta_v = hyper.eta0_v / jnp.sqrt(hyper.eps + gg)
        else:
            eta_v = jnp.broadcast_to(hyper.eta.eta(t), gg.shape)
        Vcur = Vg
        dV = -eta_v[:, :, None] * (gradV + 2.0 * hyper.lambda_v * Vcur)
        # zero out padded lanes (val == 0 kills coeff already; L2 pull must
        # not apply to untouched entries) and, sharded, foreign entries
        lane = (val != 0.0).astype(val.dtype)
        pair_real = lane[:, None] * lane[None, :] * offdiag * own
        dV = dV * pair_real[:, :, None]
        dgg = jnp.sum(gradV * gradV, axis=-1) * pair_real  # entry-level gg sum
        return p, g, loss, keys, dV, dgg

    def w_updates(st: FFMState, idx, val, g, t):
        """Linear-term update: FTRL (default) or SGD."""
        grad = g * val
        if hyper.use_ftrl:
            n_old = st.n.at[idx].get(mode="fill", fill_value=0.0)
            w_old = st.w.at[idx].get(mode="fill", fill_value=0.0)
            n_new = n_old + grad * grad
            sigma = (jnp.sqrt(n_new) - jnp.sqrt(n_old)) / hyper.alpha
            z_old = st.z.at[idx].get(mode="fill", fill_value=0.0)
            z_new = z_old + grad - sigma * w_old
            w_new = jnp.where(
                jnp.abs(z_new) <= hyper.lambda1,
                0.0,
                (jnp.sign(z_new) * hyper.lambda1 - z_new)
                / ((hyper.beta + jnp.sqrt(n_new)) / hyper.alpha + hyper.lambda2),
            )
            return (z_new - z_old), (n_new - n_old), w_new
        eta = hyper.eta.eta(t)
        w_old = st.w.at[idx].get(mode="fill", fill_value=0.0)
        dw = -eta * (grad + 2.0 * hyper.lambda_w * w_old)
        return jnp.zeros_like(val), jnp.zeros_like(val), w_old + dw

    def scan_step(state: FFMState, indices, values, fields, labels):
        def body(st: FFMState, row):
            idx, val, fld, y = row
            t = (st.step + 1).astype(jnp.float32)
            p, g, loss, keys, dV, dgg = row_updates(st, idx, val, fld, y, t)
            widx, wval = (idx, val) if translate_w is None \
                else translate_w(idx, val)
            v = scatter_rows_flat(
                st.v, keys.reshape(-1), dV.reshape(-1, dV.shape[-1]))
            v_gg = st.v_gg.at[keys.reshape(-1)].add(dgg.reshape(-1),
                                                    mode="drop")
            st = st.replace(v=v, v_gg=v_gg, step=st.step + 1)
            if hyper.linear_coeff:
                dz, dn, w_new = w_updates(st, widx, wval, g, t)
                st = st.replace(
                    z=st.z.at[widx].add(dz, mode="drop"),
                    n=st.n.at[widx].add(dn, mode="drop"),
                    w=st.w.at[widx].set(w_new, mode="drop"),
                )
            if hyper.global_bias:
                eta = hyper.eta.eta(t)
                st = st.replace(w0=st.w0 - eta * (g + 2.0 * hyper.lambda_w * st.w0))
            touched = st.touched.at[widx].max(
                jnp.ones_like(widx, dtype=jnp.int8), mode="drop")
            return st.replace(touched=touched), loss

        state, losses = jax.lax.scan(body, state, (indices, values, fields, labels))
        return state, jnp.sum(losses)

    def apply_row_group(carry: FFMState, base: FFMState, idx, val, fld, lab,
                        ts, pk_carry=None, pk_base=None):
        """Compute one row group's updates against the block-start `base`
        parameters and scatter-accumulate them into `carry` — the single
        accumulate-then-apply body shared by the unchunked minibatch step
        (carry == base, one group) and the tiled step (scan over groups).

        With `pk_base`/`pk_carry` (local path), V and gg live interleaved
        in one [Dv, k+1] table for the block: gathers and scatters each
        collapse to a single row op; carry.v / carry.v_gg are STALE inside
        and the caller unpacks at block end. Under the mxu backend the
        tables carry power-of-two pad lanes and both row ops go through
        one shared sorted-window plan."""
        if use_mxu:
            from ..ops import mxu_scatter as mxu

            keys_all = jax.vmap(
                lambda i, f: _row_pair_keys(i, f, hyper.v_dims))(idx, fld)
            plan = mxu.make_plan(keys_all.reshape(-1), hyper.v_dims)
            kp1 = hyper.factors + 1
            pg_all = mxu.gather(pk_base, plan) \
                .reshape(keys_all.shape + (pk_base.shape[-1],))[..., :kp1]
            p, g, loss, keys, dV, dgg = jax.vmap(
                lambda i, v, f, y, t, kk, pg: row_updates(
                    base, i, v, f, y, t, None, pg, kk))(
                    idx, val, fld, lab, ts, keys_all, pg_all)
        else:
            p, g, loss, keys, dV, dgg = jax.vmap(
                lambda i, v, f, y, t: row_updates(base, i, v, f, y, t,
                                                  pk_base))(
                    idx, val, fld, lab, ts)
        widx, wval = (idx, val) if translate_w is None \
            else jax.vmap(translate_w)(idx, val)
        k = dV.shape[-1]
        if use_mxu:
            from ..ops import mxu_scatter as mxu

            upd = jnp.concatenate([dV, dgg[..., None]], axis=-1)
            pk_carry = mxu.scatter_add(pk_carry, keys.reshape(-1),
                                       upd.reshape(-1, k + 1), plan)
        elif pk_carry is not None:
            upd = jnp.concatenate([dV, dgg[..., None]], axis=-1)
            pk_carry = scatter_rows_flat(pk_carry, keys.reshape(-1),
                                         upd.reshape(-1, k + 1))
        else:
            carry = carry.replace(
                v=scatter_rows_flat(carry.v, keys.reshape(-1),
                                    dV.reshape(-1, k)),
                v_gg=carry.v_gg.at[keys.reshape(-1)].add(dgg.reshape(-1),
                                                         mode="drop"),
            )
        if hyper.linear_coeff:
            dz, dn, w_new = jax.vmap(
                lambda i, v_, g_, t: w_updates(base, i, v_, g_, t))(
                    widx, wval, g, ts)
            carry = carry.replace(
                z=carry.z.at[widx].add(dz, mode="drop"),
                n=carry.n.at[widx].add(dn, mode="drop"),
                w=carry.w.at[widx].set(w_new, mode="drop"),
            )
        carry = carry.replace(touched=carry.touched.at[widx].max(
            jnp.ones_like(widx, dtype=jnp.int8), mode="drop"))
        return carry, jnp.sum(loss), jnp.sum(g), pk_carry

    def apply_w0(st: FFMState, base: FFMState, g_sum, b, t_last):
        # one batch-level w0 update with eta at the batch's final timestep
        if not hyper.global_bias:
            return st
        eta = hyper.eta.eta(t_last)
        return st.replace(w0=base.w0 - eta * (
            g_sum + b * 2.0 * hyper.lambda_w * base.w0))

    def _want_pack(b: int, K: int, state: FFMState) -> bool:
        """Packing costs ~2 full [Dv, k+1] table passes per block; the win
        is the B*K^2 random-scalar gg gather+scatter it absorbs into the V
        row ops. Pack only when the block's pairwise volume dominates the
        table traffic (always true at the deployment block sizes; tiny
        test minibatches stay on the split path). `pack_v` overrides."""
        if feature_shard is not None:
            return False
        if pack_v is not None:
            return pack_v
        return b * K * K * 8 >= state.v.shape[0]

    def _pack_v(state: FFMState):
        pk = jnp.concatenate([state.v, state.v_gg[:, None]], axis=1)
        if use_mxu:
            # mxu tables need power-of-two lane counts; extra pad lanes
            # receive no updates (kl < c scatter protocol)
            from ..ops.mxu_scatter import pad_cols

            cpad = pad_cols(pk.shape[1])
            if cpad != pk.shape[1]:
                pk = jnp.concatenate(
                    [pk, jnp.zeros((pk.shape[0], cpad - pk.shape[1]),
                                   pk.dtype)], axis=1)
        return pk

    def _unpack_v(st: FFMState, pk):
        k = hyper.factors
        return st.replace(v=pk[:, :k], v_gg=pk[:, k])

    def minibatch_step(state: FFMState, indices, values, fields, labels):
        b = indices.shape[0]
        ts = (state.step + 1 + jnp.arange(b)).astype(jnp.float32)
        pk = _pack_v(state) if use_mxu or _want_pack(
            b, indices.shape[1], state) else None
        st, loss, g_sum, pk = apply_row_group(state, state, indices, values,
                                              fields, labels, ts,
                                              pk_carry=pk, pk_base=pk)
        if pk is not None:
            st = _unpack_v(st, pk)
        st = apply_w0(st, state, g_sum, b, ts[-1])
        return st.replace(step=state.step + b), loss

    def chunked_minibatch_step(state: FFMState, indices, values, fields, labels):
        b = indices.shape[0]
        c = row_chunk
        if b % c != 0:
            raise ValueError(f"batch {b} not divisible by row_chunk {c}")
        chunks = jax.tree.map(
            lambda a: a.reshape((b // c, c) + a.shape[1:]),
            (indices, values, fields, labels))
        ts_all = (state.step + 1 + jnp.arange(b)).astype(jnp.float32) \
            .reshape(b // c, c)
        pk0 = _pack_v(state) if use_mxu or _want_pack(
            b, indices.shape[1], state) else None

        def body(carry, chunk_in):
            st, pk = carry
            idx, val, fld, lab, ts = chunk_in
            st, loss, g_sum, pk = apply_row_group(st, state, idx, val, fld,
                                                  lab, ts, pk_carry=pk,
                                                  pk_base=pk0)
            return (st, pk), (loss, g_sum)

        (st, pk), (losses, g_sums) = jax.lax.scan(
            body, (state, pk0), (*chunks, ts_all))
        if pk is not None:
            st = _unpack_v(st, pk)
        st = apply_w0(st, state, jnp.sum(g_sums), b, ts_all[-1, -1])
        return st.replace(step=state.step + b), jnp.sum(losses)

    if row_chunk is not None and mode != "minibatch":
        raise ValueError("row_chunk applies to minibatch mode only")
    if row_chunk is not None and row_chunk <= 0:
        raise ValueError(f"row_chunk must be positive, got {row_chunk}")
    if mode == "scan":
        fn = scan_step
    elif row_chunk is not None:
        fn = chunked_minibatch_step
    else:
        fn = minibatch_step
    # jit=False returns the raw traceable fn for embedding in an outer scan
    # (e.g. a whole-epoch lax.scan over staged blocks, scripts/bench_ffm.py)
    return jax.jit(fn, donate_argnums=(0,)) if jit else fn


from functools import partial


@partial(jax.jit, static_argnums=(0,))
def _ffm_scores_jit(hyper: FFMHyper, st: FFMState, idx, val, fld):
    def one(i, v, f):
        p, _, _, _ = _row_predict(st, i, v, f, hyper)
        return p

    return jax.vmap(one)(idx, val, fld)


def _ffm_scores(state: FFMState, hyper: FFMHyper, indices, values, fields):
    # module-level jit (hyper static): repeated same-shape calls — e.g. the
    # SQL engine's per-row ffm_predict scalar — hit the trace cache instead
    # of re-tracing a fresh closure every call
    return _ffm_scores_jit(hyper, state, indices, values, fields)


@dataclass
class TrainedFFMModel:
    state: FFMState
    hyper: FFMHyper

    def predict(self, rows: Sequence[Sequence[str]]) -> np.ndarray:
        idx, val, fld, _ = _stage_ffm_rows(rows, None, self.hyper)
        return np.asarray(_ffm_scores(self.state, self.hyper, idx, val, fld))

    def model_rows(self):
        touched = np.asarray(self.state.touched) != 0
        feats = np.nonzero(touched)[0]
        return feats, np.asarray(self.state.w)[feats], float(self.state.w0)

    def to_blob(self, half_float: bool = True) -> bytes:
        """Serialize the whole predictable model to one compressed blob —
        the FFMPredictionModel.writeExternal analog (ref:
        fm/FFMPredictionModel.java:46,149-200: ZigZag-LEB128 feature keys +
        half-float values + compression). The linear part reuses
        encode_sparse_model (the same recipe); V rows are stored sparsely
        as (delta-zigzag key, k values) for exactly the rows that differ
        from the seeded gaussian init — the untouched rest is re-derived
        from the PRNG at decode, so from_blob().predict reproduces this
        model's predict (bit-exact with half_float=False)."""
        import struct as _struct

        from ..utils.codec import (compress_model_blob, encode_sparse_model,
                                   float_to_half, zigzag_leb128_encode_array)

        st, hy = self.state, self.hyper
        feats, w, w0 = self.model_rows()
        w_blob = encode_sparse_model(feats, w, half_float=half_float)
        v = np.asarray(st.v, np.float32)
        init_v = np.asarray(
            jax.random.normal(jax.random.PRNGKey(hy.seed), v.shape)
            * hy.sigma, np.float32)
        changed = np.nonzero(np.any(v != init_v, axis=1))[0]
        vkeys = zigzag_leb128_encode_array(np.diff(changed, prepend=0))
        vvals = v[changed].ravel()
        v_bytes = (float_to_half(vvals).tobytes() if half_float
                   else vvals.astype("<f4").tobytes())
        flags = ((1 if hy.linear_coeff else 0)
                 | (2 if hy.global_bias else 0)
                 | (4 if hy.classification else 0)
                 | (8 if half_float else 0))
        header = _struct.pack(
            "<4sBiqqqqfBf", b"HFM1", 1, hy.factors, hy.num_features,
            hy.num_fields, hy.v_dims, hy.seed, hy.sigma, flags, w0)
        v_section = compress_model_blob(
            _struct.pack("<qq", len(changed), len(vkeys)) + vkeys + v_bytes)
        return (header + _struct.pack("<qq", len(w_blob), len(v_section))
                + w_blob + v_section)

    @classmethod
    def from_blob(cls, blob: bytes) -> "TrainedFFMModel":
        """Decode a to_blob() emission back into a servable model — the
        FFMPredictUDF deserialization path (ref: fm/FFMPredictUDF.java +
        FFMPredictionModel.readExternal)."""
        import struct as _struct

        from ..utils.codec import (decode_sparse_model,
                                   decompress_model_blob, half_to_float,
                                   zigzag_leb128_decode_array)

        magic, version, k, d, nf, dv, seed, sigma, flags, w0 = \
            _struct.unpack_from("<4sBiqqqqfBf", blob, 0)
        if magic != b"HFM1" or version != 1:
            raise ValueError("not an FFM model blob")
        off = _struct.calcsize("<4sBiqqqqfBf")
        wlen, vlen = _struct.unpack_from("<qq", blob, off)
        off += 16
        feats, w_sparse = decode_sparse_model(blob[off:off + wlen])
        off += wlen
        v_section = decompress_model_blob(blob[off:off + vlen])
        n_changed, keys_len = _struct.unpack_from("<qq", v_section, 0)
        deltas = zigzag_leb128_decode_array(v_section[16:16 + keys_len],
                                            n_changed)
        vkeys = np.cumsum(np.asarray(deltas, np.int64))
        raw = v_section[16 + keys_len:]
        if flags & 8:
            vvals = half_to_float(
                np.frombuffer(raw, np.float16, count=n_changed * k))
        else:
            vvals = np.frombuffer(raw, "<f4", count=n_changed * k).copy()
        vvals = np.asarray(vvals, np.float32).reshape(n_changed, k)

        hyper = FFMHyper(factors=int(k), classification=bool(flags & 4),
                         global_bias=bool(flags & 2),
                         linear_coeff=bool(flags & 1),
                         num_features=int(d), num_fields=int(nf),
                         v_dims=int(dv), seed=int(seed), sigma=float(sigma))
        st = init_ffm_state(hyper)
        w_full = np.zeros(int(d), np.float32)
        w_full[np.asarray(feats, np.int64)] = w_sparse
        touched = np.zeros(int(d), np.int8)
        touched[np.asarray(feats, np.int64)] = 1
        v = np.asarray(st.v, np.float32).copy()
        v[vkeys] = vvals
        st = st.replace(w0=jnp.asarray(np.float32(w0)),
                        w=jnp.asarray(w_full), v=jnp.asarray(v),
                        touched=jnp.asarray(touched))
        return cls(state=st, hyper=hyper)


def _stage_ffm_rows(rows, labels, hyper: FFMHyper):
    """Parse "field:idx:value" rows into padded [B, K] arrays (pad lane:
    idx = num_features OOB, value 0, field 0)."""
    parsed = [[FMFeature.parse(f, num_features=hyper.num_features,
                               num_fields=hyper.num_fields) for f in row]
              for row in rows]
    width = pad_to_bucket(max((len(r) for r in parsed), default=1))
    B = len(parsed)
    idx = np.full((B, width), hyper.num_features, np.int32)
    val = np.zeros((B, width), np.float32)
    fld = np.zeros((B, width), np.int32)
    for r, row in enumerate(parsed):
        for c, f in enumerate(row[:width]):
            idx[r, c] = f.index % hyper.num_features
            val[r, c] = f.value
            fld[r, c] = (f.field if f.field >= 0 else 0) % hyper.num_fields
    lab = None
    if labels is not None:
        lab = np.asarray(labels, np.float32)
        if hyper.classification:
            lab = np.where(lab > 0, 1.0, -1.0).astype(np.float32)
    return idx, val, fld, lab


def _ffm_options() -> Options:
    o = _fm_options()
    o.add("w0", "global_bias", False, "Include global bias w0 [default: OFF]")
    o.add("disable_wi", "no_coeff", False, "Exclude the linear term")
    o.add("feature_hashing", None, True, "Feature hashing bits [18,31] [default 21]",
          default=21, type=int)
    o.add("num_fields", None, True, "Number of fields [default 1024]", default=1024,
          type=int)
    o.add("disable_adagrad", None, False, "Disable AdaGrad for V")
    o.add("eta0_V", None, True, "Initial learning rate for V [default 1.0]",
          default=1.0, type=float)
    o.add("eps", None, True, "AdaGrad denominator constant [default 1.0]",
          default=1.0, type=float)
    o.add("disable_ftrl", None, False, "Disable FTRL for W")
    o.add("alpha", "alphaFTRL", True, "FTRL alpha [default 0.1]", default=0.1,
          type=float)
    o.add("beta", "betaFTRL", True, "FTRL beta [default 1.0]", default=1.0, type=float)
    o.add("lambda1", None, True, "FTRL L1 [default 0.1]", default=0.1, type=float)
    o.add("lambda2", None, True, "FTRL L2 [default 0.01]", default=0.01, type=float)
    o.add("v_bits", None, True, "log2 size of the hashed V table [default 22]",
          default=22, type=int)
    o.add("row_chunk", None, True,
          "Tile minibatch K^2 pairwise work in chunks of this many rows "
          "(bounds activation memory; 0 = no tiling)", default=0, type=int)
    return o


def train_ffm(rows: Sequence[Sequence[str]], labels, options: Optional[str] = None
              ) -> TrainedFFMModel:
    cl = _ffm_options().parse(options, "train_ffm")
    lam = cl.get_float("lambda0", 0.01)
    hyper = FFMHyper(
        factors=cl.get_int("factor", 4),
        classification=True,  # FFM is a CTR classifier; -c accepted for parity
        lambda_w=lam,
        lambda_v=lam,
        global_bias=cl.has("w0"),
        linear_coeff=not cl.has("disable_wi"),
        use_ftrl=not cl.has("disable_ftrl"),
        use_adagrad=not cl.has("disable_adagrad"),
        eta0_v=cl.get_float("eta0_V", 1.0),
        eps=cl.get_float("eps", 1.0),
        alpha=cl.get_float("alpha", 0.1),
        beta=cl.get_float("beta", 1.0),
        lambda1=cl.get_float("lambda1", 0.1),
        lambda2=cl.get_float("lambda2", 0.01),
        sigma=cl.get_float("sigma", 0.1),
        num_features=1 << cl.get_int("feature_hashing", 21),
        num_fields=cl.get_int("num_fields", 1024),
        v_dims=1 << cl.get_int("v_bits", 22),
        eta=get_eta(cl, 0.2),
        seed=cl.get_int("seed", 31),
    )
    idx, val, fld, lab = _stage_ffm_rows(rows, labels, hyper)
    mini_batch = cl.get_int("mini_batch", 1)
    mode = "minibatch" if mini_batch > 1 else "scan"
    block = mini_batch if mode == "minibatch" else cl.get_int("block_size", 4096)
    row_chunk = cl.get_int("row_chunk", 0) or None
    if row_chunk is not None:
        # positivity is validated by make_ffm_step (single source)
        if mode != "minibatch":
            raise ValueError("-row_chunk requires -mini_batch > 1 "
                             "(it tiles the minibatch pairwise work)")
        if block % row_chunk != 0:
            raise ValueError(
                f"-mini_batch {block} not divisible by -row_chunk {row_chunk}")
    backend = "mxu" if (cl.has("mxu_scatter") and mode == "minibatch") \
        else "xla"
    step = make_ffm_step(hyper, mode, row_chunk=row_chunk,
                         update_backend=backend)
    # the trailing partial block (n % block rows) won't divide by row_chunk;
    # it goes through an untiled step (same semantics, small shape)
    tail_step = make_ffm_step(hyper, mode, update_backend=backend) \
        if row_chunk is not None else step
    state = init_ffm_state(hyper)
    iters = cl.get_int("iters", 1)
    conv = ConversionState(not cl.has("disable_cv"), cl.get_float("cv_rate", 0.005))
    n = len(rows)
    for it in range(max(1, iters)):
        epoch_loss = 0.0
        for s in range(0, n, block):
            e = min(s + block, n)
            use = step if (row_chunk is None or (e - s) % row_chunk == 0) \
                else tail_step
            state, loss = use(state, idx[s:e], val[s:e], fld[s:e], lab[s:e])
            epoch_loss += float(loss)
        conv.incr_loss(epoch_loss)
        if iters > 1 and conv.is_converged(n):
            break
    return TrainedFFMModel(state=state, hyper=hyper)


def ffm_predict(model: TrainedFFMModel, rows: Sequence[Sequence[str]]) -> np.ndarray:
    """`ffm_predict` equivalent (ref: fm/FFMPredictUDF.java deserializes the
    compressed model; here the trained model object scores directly)."""
    return model.predict(rows)
