"""Binary online classifiers: train_perceptron / train_pa / train_pa1 /
train_pa2 / train_cw / train_arow / train_arowh / train_scw / train_scw2 /
train_adagrad_rda.

Each learner is a closed-form per-row update Rule executed by the batched
engine (core/engine.py). Update formulas mirror the reference exactly:

- Perceptron (ref: classifier/PerceptronUDTF.java:34-50)
- PA/PA1/PA2 (ref: classifier/PassiveAggressiveUDTF.java:38-135)
- CW (ref: classifier/ConfidenceWeightedUDTF.java:51-164)
- AROW/AROWh (ref: classifier/AROWClassifierUDTF.java:49-212)
- SCW1/SCW2 (ref: classifier/SoftConfideceWeightedUDTF.java:45-246)
- AdaGradRDA (ref: classifier/AdaGradRDAUDTF.java:40-143)
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
from jax.scipy.special import erfinv

from ..core.engine import Rule, RuleOutput
from ..utils.options import CommandLine, Options
from .base import FeatureRows, TrainedLinearModel, base_options, binary_label_map, fit_linear


def _probit(p: float, bound: float = 5.0) -> float:
    """probit(p) = sqrt(2) * erfinv(2p - 1), clamped to [-bound, bound]
    (ref: utils/math/StatsUtils.java:35-60)."""
    if p == 0.0:
        return -bound
    if p == 1.0:
        return bound
    v = math.sqrt(2.0) * float(erfinv(2.0 * p - 1.0))
    return max(-bound, min(bound, v))


def _resolve_phi(cl: CommandLine) -> float:
    """-phi directly, else probit(-eta) (ref: ConfidenceWeightedUDTF.java:85-104)."""
    if cl.has("phi"):
        return cl.get_float("phi")
    if cl.has("eta"):
        eta = cl.get_float("eta")
        if eta <= 0.5 or eta > 1.0:
            raise ValueError(f"eta must be in (0.5, 1]: {eta}")
        return _probit(eta, 5.0)
    return 1.0


def _safe_div(num, den):
    """x/y with 0 where y == 0 — the reference's explicit divide-by-zero guards."""
    return jnp.where(den == 0.0, 0.0, num / jnp.where(den == 0.0, 1.0, den))


# ---------------------------------------------------------------- perceptron

def _perceptron_update(ctx, hyper):
    # on misclassify (y * score <= 0): w += y * x (ref: PerceptronUDTF.java:44-50)
    updated = ctx.y * ctx.score <= 0.0
    dw = jnp.where(updated, ctx.y * ctx.val, 0.0)
    loss = jnp.where(updated, 1.0, 0.0)
    return RuleOutput(dw=dw, loss=loss, updated=updated)


def _perceptron_batch_update(ctx, hyper):
    # the same closed form with the [B] -> [B, K] broadcasts explicit
    updated = ctx.y * ctx.score <= 0.0  # [B]
    dw = jnp.where(updated[:, None], ctx.y[:, None] * ctx.val, 0.0)
    loss = jnp.where(updated, 1.0, 0.0)
    return RuleOutput(dw=dw, loss=loss, updated=updated)


PERCEPTRON = Rule("perceptron", _perceptron_update,
                  batch_update=_perceptron_batch_update)


# ------------------------------------------------------------------- PA family

def _pa_update_factory(variant: str):
    def update(ctx, hyper):
        loss = jnp.maximum(0.0, 1.0 - ctx.y * ctx.score)  # hinge
        if variant == "pa":
            eta = _safe_div(loss, ctx.sq_norm)  # (ref: PassiveAggressiveUDTF.java:67-68)
        elif variant == "pa1":
            eta = jnp.minimum(hyper["c"], _safe_div(loss, ctx.sq_norm))  # (:109-112)
        else:  # pa2
            eta = loss / (ctx.sq_norm + 0.5 / hyper["c"])  # (:125-128)
        updated = loss > 0.0
        dw = jnp.where(updated, eta * ctx.y * ctx.val, 0.0)
        return RuleOutput(dw=dw, loss=loss, updated=updated)

    return update


PA = Rule("pa", _pa_update_factory("pa"))
PA1 = Rule("pa1", _pa_update_factory("pa1"))
PA2 = Rule("pa2", _pa_update_factory("pa2"))


# -------------------------------------------------------------------------- CW

def _cw_update(ctx, hyper):
    phi = hyper["phi"]
    score = ctx.score * ctx.y
    var = ctx.variance
    b = 1.0 + 2.0 * phi * score
    disc = jnp.maximum(0.0, b * b - 8.0 * phi * (score - phi * var))
    gamma = _safe_div(-b + jnp.sqrt(disc), 4.0 * phi * var)  # (ref: ConfidenceWeightedUDTF.java:126-136)
    updated = gamma > 0.0
    alpha = jnp.where(updated, gamma, 0.0)
    coeff = alpha * ctx.y
    dw = coeff * ctx.cov * ctx.val
    # new_cov = 1/(1/cov + 2*alpha*phi*x^2), written div-safe as
    # cov/(1 + 2*alpha*phi*x^2*cov) (ref: ConfidenceWeightedUDTF.java:161)
    denom = 1.0 + 2.0 * alpha * phi * ctx.val * ctx.val * ctx.cov
    dcov = ctx.cov / denom - ctx.cov
    loss = jnp.where(ctx.score * ctx.y < 0.0, 1.0, 0.0)
    return RuleOutput(dw=dw, loss=loss, updated=updated, dcov=dcov)


def _cw_batch_update(ctx, hyper):
    # _cw_update's closed form over a whole [B, K] minibatch
    phi = hyper["phi"]
    score = ctx.score * ctx.y  # [B]
    var = ctx.variance  # [B]
    b = 1.0 + 2.0 * phi * score
    disc = jnp.maximum(0.0, b * b - 8.0 * phi * (score - phi * var))
    gamma = _safe_div(-b + jnp.sqrt(disc), 4.0 * phi * var)
    updated = gamma > 0.0
    alpha = jnp.where(updated, gamma, 0.0)
    coeff = (alpha * ctx.y)[:, None]
    dw = coeff * ctx.cov * ctx.val
    denom = 1.0 + 2.0 * alpha[:, None] * phi * ctx.val * ctx.val * ctx.cov
    dcov = ctx.cov / denom - ctx.cov
    loss = jnp.where(ctx.score * ctx.y < 0.0, 1.0, 0.0)
    return RuleOutput(dw=dw, loss=loss, updated=updated, dcov=dcov)


CW = Rule("cw", _cw_update, use_covariance=True,
          batch_update=_cw_batch_update)


# ------------------------------------------------------------------------ AROW

def _arow_update_factory(hinge: bool):
    def update(ctx, hyper):
        r = hyper["r"]
        m = ctx.score * ctx.y
        if hinge:  # AROWh: loss = max(0, c - m) (ref: AROWClassifierUDTF.java:190-209)
            loss = jnp.maximum(0.0, hyper["c"] - m)
            updated = loss > 0.0
            alpha_scale = loss
        else:  # AROW: fire when m < 1, alpha = (1 - m) * beta (ref: :101-108)
            updated = m < 1.0
            alpha_scale = 1.0 - m
            loss = jnp.where(m < 0.0, 1.0, 0.0)  # 0-1 loss (ref: :113-116)
        beta = 1.0 / (ctx.variance + r)
        alpha = jnp.where(updated, alpha_scale * beta, 0.0)
        cv = ctx.cov * ctx.val
        dw = ctx.y * alpha * cv
        dcov = jnp.where(updated, -beta * cv * cv, 0.0)  # (ref: :147)
        return RuleOutput(dw=dw, loss=loss, updated=updated, dcov=dcov)

    return update


def _arow_batch_update_factory(hinge: bool):
    def update(ctx, hyper):
        # the row update's closed form over a whole [B, K] minibatch: row
        # scalars stay [B], the per-lane broadcasts are written out (the
        # batched backend's hot path, core/batch_update.py)
        r = hyper["r"]
        m = ctx.score * ctx.y  # [B]
        if hinge:
            loss = jnp.maximum(0.0, hyper["c"] - m)
            updated = loss > 0.0
            alpha_scale = loss
        else:
            updated = m < 1.0
            alpha_scale = 1.0 - m
            loss = jnp.where(m < 0.0, 1.0, 0.0)
        beta = 1.0 / (ctx.variance + r)  # [B]
        alpha = jnp.where(updated, alpha_scale * beta, 0.0)
        cv = ctx.cov * ctx.val  # [B, K]
        dw = (ctx.y * alpha)[:, None] * cv
        dcov = jnp.where(updated[:, None], -beta[:, None] * cv * cv, 0.0)
        return RuleOutput(dw=dw, loss=loss, updated=updated, dcov=dcov)

    return update


AROW = Rule("arow", _arow_update_factory(False), use_covariance=True,
            batch_update=_arow_batch_update_factory(False))
AROWH = Rule("arowh", _arow_update_factory(True), use_covariance=True,
             batch_update=_arow_batch_update_factory(True))


# ------------------------------------------------------------------- SCW1/SCW2

def _scw_update_factory(variant: int):
    def update(ctx, hyper):
        phi = hyper["phi"]
        c = hyper["c"]
        m = ctx.score
        var = ctx.variance
        y = ctx.y
        # loss = max(0, phi*sqrt(var) - y*m) (ref: SoftConfideceWeightedUDTF.java:141-146)
        loss = jnp.maximum(0.0, phi * jnp.sqrt(jnp.maximum(var, 0.0)) - y * m)
        sq_phi = phi * phi
        if variant == 1:
            psi = 1.0 + sq_phi / 2.0
            zeta = 1.0 + sq_phi
            alpha_numer = -m * psi + jnp.sqrt(
                jnp.maximum(0.0, (m * m * sq_phi * sq_phi / 4.0) + var * sq_phi * zeta)
            )
            alpha = _safe_div(alpha_numer, var * zeta)
            # NB: the reference applies Math.max(c, alpha) here (the SCW paper
            # uses min); we mirror the reference (ref: SoftConfideceWeightedUDTF.java:186)
            alpha = jnp.where(alpha <= 0.0, 0.0, jnp.maximum(c, alpha))
        else:
            n = var + c / 2.0
            v_phi_phi = var * sq_phi
            v_phi_phi_m = v_phi_phi * m
            term = v_phi_phi_m * m * var + 4.0 * n * var * (n + v_phi_phi)
            gamma = phi * jnp.sqrt(jnp.maximum(0.0, term))
            alpha_numer = -(2.0 * m * n + v_phi_phi_m) + gamma
            alpha_denom = 2.0 * (n * n + n * v_phi_phi)
            alpha = jnp.where(alpha_numer <= 0.0, 0.0, _safe_div(alpha_numer, alpha_denom))
        # beta (shared) (ref: SoftConfideceWeightedUDTF.java:197-214)
        beta_numer = alpha * phi
        var_alpha_phi = var * beta_numer
        u = -var_alpha_phi + jnp.sqrt(
            jnp.maximum(0.0, var_alpha_phi * var_alpha_phi + 4.0 * var)
        )
        beta = _safe_div(beta_numer, u / 2.0 + var_alpha_phi)
        updated = (loss > 0.0) & (alpha != 0.0) & (beta != 0.0)
        alpha = jnp.where(updated, alpha, 0.0)
        beta = jnp.where(updated, beta, 0.0)
        cv = ctx.cov * ctx.val
        dw = ctx.y * alpha * cv  # (ref: :263-278)
        dcov = -beta * cv * cv
        return RuleOutput(dw=dw, loss=loss, updated=updated, dcov=dcov)

    return update


SCW1 = Rule("scw1", _scw_update_factory(1), use_covariance=True)
SCW2 = Rule("scw2", _scw_update_factory(2), use_covariance=True)


# ------------------------------------------------------------------ AdaGradRDA

def _adagrad_rda_update(ctx, hyper):
    scaling = hyper["scale"]
    loss = jnp.maximum(0.0, 1.0 - ctx.y * ctx.score)  # hinge (ref: AdaGradRDAUDTF.java:91-95)
    updated = loss > 0.0
    gradient = -ctx.y * ctx.val  # subgradient per feature (ref: :104-113)
    scaled_g = jnp.where(updated, gradient * scaling, 0.0)
    return RuleOutput(
        dw=jnp.zeros_like(ctx.val),
        loss=loss,
        updated=updated,
        dslots={"sum_grad": scaled_g, "sum_sqgrad": scaled_g * scaled_g},
    )


def _adagrad_rda_derive_w(slots, t, hyper):
    # w = -sign(u) * eta * t / sqrt(G) * (|u|/t - lambda), 0 when inside the
    # L1 ball (ref: AdaGradRDAUDTF.java:120-141, incl. the float-overflow
    # scaling trick :112-125).
    scaling = hyper["scale"]
    sum_grad = slots["sum_grad"] * scaling
    sum_sqgrad = slots["sum_sqgrad"] * scaling
    sign = jnp.where(sum_grad > 0.0, 1.0, -1.0)
    mog = sign * sum_grad / t - hyper["lambda"]
    denom = jnp.sqrt(jnp.maximum(sum_sqgrad, 1e-30))
    w = -1.0 * sign * hyper["eta"] * t * mog / denom
    return jnp.where(mog < 0.0, 0.0, w)


ADAGRAD_RDA = Rule(
    "adagrad_rda",
    _adagrad_rda_update,
    slot_names=("sum_grad", "sum_sqgrad"),
    derive_w=_adagrad_rda_derive_w,
    slot_merge=(("sum_grad", "sum"), ("sum_sqgrad", "sum")),
)


# -------------------------------------------------------------- public train_*

def _train(rule: Rule, hyper: dict, opts: Options, features: FeatureRows, labels,
           options: Optional[str], name: str, **kw) -> TrainedLinearModel:
    cl = opts.parse(options, name)
    # allow hyper resolution against parsed options
    hyper = dict(hyper)
    for k in list(hyper):
        if cl.has(k):
            hyper[k] = cl.get_float(k)
    return fit_linear(rule, hyper, cl, features, labels, label_map=binary_label_map, **kw)


def train_perceptron(features: FeatureRows, labels, options: Optional[str] = None, **kw):
    return _train(PERCEPTRON, {}, base_options(), features, labels, options,
                  "train_perceptron", **kw)


def _pa_opts(with_c: bool) -> Options:
    o = base_options()
    if with_c:
        o.add("c", "aggressiveness", True, "Aggressiveness parameter C [default 1.0]",
              default=1.0, type=float)
    return o


def train_pa(features: FeatureRows, labels, options: Optional[str] = None, **kw):
    return _train(PA, {}, _pa_opts(False), features, labels, options, "train_pa", **kw)


def train_pa1(features: FeatureRows, labels, options: Optional[str] = None, **kw):
    return _train(PA1, {"c": 1.0}, _pa_opts(True), features, labels, options, "train_pa1", **kw)


def train_pa2(features: FeatureRows, labels, options: Optional[str] = None, **kw):
    return _train(PA2, {"c": 1.0}, _pa_opts(True), features, labels, options, "train_pa2", **kw)


def _cw_opts(with_c: bool = False) -> Options:
    o = base_options()
    o.add("phi", "confidence", True, "Confidence parameter [default 1.0]", type=float)
    o.add("eta", "hyper_c", True, "Confidence hyperparameter in (0.5, 1] [default 0.85]",
          type=float)
    if with_c:
        o.add("c", "aggressiveness", True, "Aggressiveness parameter C [default 1.0]",
              default=1.0, type=float)
    return o


def train_cw(features: FeatureRows, labels, options: Optional[str] = None, **kw):
    opts = _cw_opts()
    cl = opts.parse(options, "train_cw")
    hyper = {"phi": _resolve_phi(cl)}
    return fit_linear(CW, hyper, cl, features, labels, label_map=binary_label_map, **kw)


def _arow_opts(with_c: bool) -> Options:
    o = base_options()
    o.add("r", "regularization", True, "Regularization parameter r [default 0.1]",
          default=0.1, type=float)
    if with_c:
        o.add("c", "aggressiveness", True, "Aggressiveness parameter C [default 1.0]",
              default=1.0, type=float)
    return o


def train_arow(features: FeatureRows, labels, options: Optional[str] = None, **kw):
    cl = _arow_opts(False).parse(options, "train_arow")
    hyper = {"r": cl.get_float("r", 0.1)}
    return fit_linear(AROW, hyper, cl, features, labels, label_map=binary_label_map, **kw)


def train_arowh(features: FeatureRows, labels, options: Optional[str] = None, **kw):
    cl = _arow_opts(True).parse(options, "train_arowh")
    hyper = {"r": cl.get_float("r", 0.1), "c": cl.get_float("c", 1.0)}
    return fit_linear(AROWH, hyper, cl, features, labels, label_map=binary_label_map, **kw)


def train_scw(features: FeatureRows, labels, options: Optional[str] = None, **kw):
    cl = _cw_opts(with_c=True).parse(options, "train_scw")
    hyper = {"phi": _resolve_phi(cl), "c": cl.get_float("c", 1.0)}
    return fit_linear(SCW1, hyper, cl, features, labels, label_map=binary_label_map, **kw)


def train_scw2(features: FeatureRows, labels, options: Optional[str] = None, **kw):
    cl = _cw_opts(with_c=True).parse(options, "train_scw2")
    hyper = {"phi": _resolve_phi(cl), "c": cl.get_float("c", 1.0)}
    return fit_linear(SCW2, hyper, cl, features, labels, label_map=binary_label_map, **kw)


def train_adagrad_rda(features: FeatureRows, labels, options: Optional[str] = None, **kw):
    o = base_options()
    o.add("eta", "eta0", True, "Learning rate eta [default 0.1]", default=0.1, type=float)
    o.add("lambda", None, True, "lambda constant of RDA [default 1e-6]",
          default=1e-6, type=float)
    o.add("scale", None, True, "Internal scaling factor [default 100]",
          default=100.0, type=float)
    cl = o.parse(options, "train_adagrad_rda")
    hyper = {
        "eta": cl.get_float("eta", 0.1),
        "lambda": cl.get_float("lambda", 1e-6),
        "scale": cl.get_float("scale", 100.0),
    }
    return fit_linear(ADAGRAD_RDA, hyper, cl, features, labels,
                      label_map=binary_label_map, **kw)
