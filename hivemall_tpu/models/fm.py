"""Factorization Machines: train_fm / fm_predict.

Mirrors the reference FM subsystem (ref: fm/FactorizationMachineUDTF.java:115-560,
fm/FactorizationMachineModel.java:118-300, fm/FMHyperParameters.java:30-110):

- prediction  p = w0 + sum_i w_i x_i + 1/2 sum_f [(sum_i V_if x_i)^2 - sum_i V_if^2 x_i^2]
- dloss: classification (sigmoid(p*y) - 1)*y with y in {-1,1}; regression
  p clamped to [min_target, max_target], p - y
- SGD updates with per-group L2: w0 -= eta*(g + 2*lambda_w0*w0),
  wi -= eta*(g*xi + 2*lambda_w*wi),
  Vif -= eta*(g*(xi*sumVfX_f - Vif*xi^2) + 2*lambda_Vf*Vif)
- adaptive regularization (-adareg): a validation fraction of rows updates the
  lambdas instead of theta (ref: trainLambda, FactorizationMachineUDTF.java:404-412,
  FactorizationMachineModel.java:253-300)
- multi-epoch: the reference serializes rows to a NioStatefullSegment temp
  file and replays in close() (ref: :291-332, :521-559); TPU-first the staged
  FeatureBlocks simply re-run, with the same ConversionState early exit.

TPU-first design: V is one [D, k] HBM table; a row's factor block is a [K, k]
gather, sumVfX is a matvec, and the V update is one fused outer-product —
batched across B rows in minibatch mode (the bench hot path).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..constants import DEFAULT_NUM_FEATURES
from ..core.batch import iter_blocks, pad_to_bucket, shuffle_rows
from ..ops.convergence import ConversionState
from ..ops.scatter import scatter_rows_flat
from ..ops.eta import EtaEstimator, get_eta
from ..utils.options import Options
from .base import FeatureRows, _stage_rows, base_options

DOUBLE_MIN = -1.7976931348623157e308  # mirrors Double.MIN_VALUE default semantics:
# the reference's minTarget default is Double.MIN_VALUE (smallest positive!),
# maxTarget Double.MAX_VALUE — i.e. clamping is effectively [tiny, huge] unless
# the user passes -min/-max. We default to no-op bounds instead (saner, and
# identical whenever the user sets them explicitly).


@struct.dataclass
class FMState:
    w0: jnp.ndarray  # []
    w: jnp.ndarray  # [D]
    v: jnp.ndarray  # [D, k]
    lambda_w0: jnp.ndarray  # []
    lambda_w: jnp.ndarray  # []
    lambda_v: jnp.ndarray  # [k]
    touched: jnp.ndarray  # [D] int8
    step: jnp.ndarray  # [] int32


@dataclass(frozen=True)
class FMHyper:
    factors: int = 5
    classification: bool = False
    lambda0: float = 0.01
    sigma: float = 0.1
    min_target: float = -3.0e38
    max_target: float = 3.0e38
    eta: EtaEstimator = EtaEstimator("invscaling", 0.05, power_t=0.1)
    adareg: bool = False
    va_ratio: float = 0.05
    seed: int = 31

    @property
    def padded_factors(self) -> int:
        """Physical lane count of the V table: k rounded up to a multiple
        of 8 when k > 4 (TPU f32 sublane granularity). Hardware note: the
        round-4b hypothesis that lane alignment rescues the [N,k]-ROW
        scatter was refuted on v5e (diag micro2: v8pad row scatter 69ms ==
        v5 row scatter 71ms per 512k rows) — the V update now scatters
        scalars into the flat [D*kp] view instead (ops/scatter.
        scatter_rows_flat, ~2x the row form on unaligned tables), touching
        only the logical k lanes. Padding is kept for tile-aligned
        storage/gather at zero measured cost (row gather 28.5M/s == padded
        28.2M/s). Pad lanes init to 0 and provably stay 0 (their grad
        terms are products with their own zero V entries and their
        lambda_v is 0), so every k-width result is bit-identical;
        model_rows / codecs slice back to the logical k."""
        k = self.factors
        if k > 4 and k % 8:
            return k + (8 - k % 8)
        return k


def init_fm_state(dims: int, hyper: FMHyper) -> FMState:
    k = hyper.factors
    k_pad = hyper.padded_factors
    key = jax.random.PRNGKey(hyper.seed)
    # 'random' init: uniform in [-maxval..maxval]-ish; 'gaussian': N(0, sigma).
    # We use gaussian * sigma for both (the reference default for
    # classification; regression's 'random' differs only in distribution shape,
    # ref: fm/VInitScheme.java).
    v = jax.random.normal(key, (dims, k), dtype=jnp.float32) * hyper.sigma
    if k_pad != k:
        v = jnp.concatenate(
            [v, jnp.zeros((dims, k_pad - k), jnp.float32)], axis=1)
    return FMState(
        w0=jnp.zeros((), jnp.float32),
        w=jnp.zeros((dims,), jnp.float32),
        v=v,
        lambda_w0=jnp.asarray(hyper.lambda0, jnp.float32),
        lambda_w=jnp.asarray(hyper.lambda0, jnp.float32),
        # pad-lane lambdas are 0: their V entries are pinned at 0, so any
        # nonzero lambda would only add a dead multiply
        lambda_v=jnp.concatenate(
            [jnp.full((k,), hyper.lambda0, jnp.float32),
             jnp.zeros((k_pad - k,), jnp.float32)]),
        touched=jnp.zeros((dims,), jnp.int8),
        step=jnp.zeros((), jnp.int32),
    )


def _row_predict(w0, wg, vg, val):
    """p and sumVfX for one row from gathered slices (padding lanes are 0)."""
    linear = jnp.sum(wg * val)
    vx = vg * val[:, None]  # [K, k]
    sum_vfx = jnp.sum(vx, axis=0)  # [k]
    sum_v2x2 = jnp.sum(vx * vx, axis=0)  # [k]
    p = w0 + linear + 0.5 * jnp.sum(sum_vfx * sum_vfx - sum_v2x2)
    return p, sum_vfx


def sharded_gather_predict(w, v, w0, idx, val, shard_axis: str, stripe: int):
    """The ONE copy of the feature-sharded FM gather + prediction, used by
    both the sharded train step and the sharded serving path (so train-time
    and serve-time p can never drift): translate global ids into the local
    [stripe] tables (foreign/pad lanes -> the drop slot, value masked to 0),
    gather owned lanes, and combine the three prediction partials with a
    single fused psum over the stripe axis. Works on any leading batch
    shape; idx/val are [..., K]."""
    from ..core.striping import translate_to_stripe

    lidx, vmask = translate_to_stripe(idx, val, shard_axis, stripe)
    wg = w.at[lidx].get(mode="fill", fill_value=0.0)
    vg = v.at[lidx].get(mode="fill", fill_value=0.0)
    vx = vg * vmask[..., None]
    linear, sum_vfx, sum_v2x2 = jax.lax.psum(
        (jnp.sum(wg * vmask, axis=-1),
         jnp.sum(vx, axis=-2),
         jnp.sum(vx * vx, axis=-2)), shard_axis)
    p = w0 + linear + 0.5 * jnp.sum(sum_vfx * sum_vfx - sum_v2x2, axis=-1)
    return wg, vg, vmask, lidx, p, sum_vfx


def _dloss_and_loss(p, y, hyper: FMHyper):
    if hyper.classification:
        # dloss = (sigmoid(p*y) - 1)*y; loss = log(1 + exp(-p*y))
        z = p * y
        g = (jax.nn.sigmoid(z) - 1.0) * y
        loss = jnp.logaddexp(0.0, -z)
    else:
        pc = jnp.clip(p, hyper.min_target, hyper.max_target)
        g = pc - y
        loss = 0.5 * g * g  # squared loss for cv tracking
    return g, loss


def make_fm_step(hyper: FMHyper, mode: str = "minibatch",
                 mini_batch_average: bool = True,
                 feature_shard: Optional[Tuple[str, int]] = None,
                 pack_w: bool = True,
                 jit: bool = True,
                 update_backend: str = "xla"):
    """Jitted FM block update. scan = reference-exact sequential; minibatch =
    accumulate-then-apply against block-start parameters.

    `mini_batch_average` applies each parameter's accumulated delta divided by
    its update count — w/V per-feature touch counts, w0 by the batch size —
    exactly the reference's own mini-batch application rule (sum/count,
    ref: RegressionBaseUDTF.java:281-295 + utils/lang/FloatAccumulator.java:38-41;
    the reference FM itself is per-row-only, so averaging is the documented
    bridge semantic, same as core/engine.py's minibatch mode). Without it the
    raw sums scale the effective step by the per-feature row frequency and
    diverge at CTR batch sizes/head features.

    `feature_shard=(axis_name, stripe)` runs the same step on a [D/stripe]
    model stripe inside shard_map — the FM analog of the engine's
    feature-sharded training (the V table is the framework's largest model
    state: [2^24, k] does not fit one chip with optimizer state). Per row,
    each device gathers its owned lanes, the three prediction partials
    (linear term, sumVfX[k], sumV2X2[k]) psum over the stripe axis, and the
    lane updates — functions of (global g, global sumVfX, lane-local w/V) —
    scatter into the local stripe only. Exact up to psum order. adareg is
    not supported sharded (its lambda updates need cross-stripe v' sums)."""
    if feature_shard is not None and hyper.adareg:
        raise ValueError("adareg is not supported with feature_shard")
    if update_backend not in ("xla", "mxu"):
        raise ValueError(f"unknown update_backend {update_backend!r}")
    if update_backend == "mxu":
        if mode != "minibatch" or feature_shard is not None:
            raise ValueError("update_backend='mxu' requires the local "
                             "minibatch path")
        from ..ops.mxu_scatter import pad_cols

        kp = hyper.padded_factors
        if kp <= hyper.factors or not pack_w:
            raise ValueError(
                "the mxu FM path rides the packed [D, kp] table and borrows "
                "pad lanes for w and the update counts; it needs "
                "padded_factors > factors (k = 8/16 exactly have no pad "
                "lane) and pack_w=True")
        if pad_cols(kp) != kp:
            # padded_factors rounds to a multiple of 8, not a power of two;
            # the mxu lane protocol needs power-of-two columns — fail at
            # build time with the constraint spelled out, not at trace time
            raise ValueError(
                f"the mxu FM path needs a power-of-two padded_factors "
                f"(lane tiling, ops/mxu_scatter.py); factors="
                f"{hyper.factors} pads to {kp} — choose k whose "
                f"multiple-of-8 round-up is a power of two (k <= 7, "
                f"9..15, 25..31, ...) or use the xla backend")

    # Borrowed-lane packing (minibatch local path): when V is lane-padded
    # (kp > k), the first pad lane carries w for the block — ONE [K,kp]
    # row gather replaces the separate w gather, and dw rides the same
    # flat row scatter as dv (one ~0.1ms full-table lane write each way
    # vs a ~13ms gather + ~7ms scatter saved per 512k-update block on
    # v5e). The pad-lane-zero invariant holds on the canonical state: the
    # lane is zeroed again at unpack.
    w_lane = hyper.factors
    # pack_w=False forces the split path (parity tests A/B it); packing
    # additionally requires a free pad lane (kp > k) and the local
    # (unsharded) path — without either it silently runs split
    use_packed = (feature_shard is None
                  and hyper.padded_factors > hyper.factors
                  and pack_w)

    if feature_shard is None:
        def gather_and_predict(state: FMState, idx, val, packed=None,
                               pg=None):
            if pg is not None or packed is not None:
                if pg is None:
                    pg = packed.at[idx].get(mode="fill", fill_value=0.0)
                wg = pg[:, w_lane]
                vg = pg.at[:, w_lane].set(0.0)  # restore the pad-lane zero
            else:
                wg = state.w.at[idx].get(mode="fill", fill_value=0.0)
                vg = state.v.at[idx].get(mode="fill", fill_value=0.0)
            p, sum_vfx = _row_predict(state.w0, wg, vg, val)
            return wg, vg, val, idx, p, sum_vfx
    else:
        shard_axis, stripe = feature_shard

        def gather_and_predict(state: FMState, idx, val, packed=None,
                               pg=None):
            wg, vg, vmask, lidx, p, sum_vfx = sharded_gather_predict(
                state.w, state.v, state.w0, idx, val, shard_axis, stripe)
            return wg, vg, vmask, lidx, p, sum_vfx

    def row_deltas(state: FMState, idx, val, y, t, packed=None, pg=None):
        eta = hyper.eta.eta(t)
        wg, vg, eff_val, sidx, p, sum_vfx = gather_and_predict(
            state, idx, val, packed, pg)
        g, loss = _dloss_and_loss(p, y, hyper)
        dw0 = -eta * (g + 2.0 * state.lambda_w0 * state.w0)
        dw = -eta * (g * eff_val + 2.0 * state.lambda_w * wg)
        x2 = eff_val * eff_val
        grad_v = eff_val[:, None] * sum_vfx[None, :] - vg * x2[:, None]
        dv = -eta * (g * grad_v + 2.0 * state.lambda_v[None, :] * vg)
        return dw0, dw, dv, loss, g, p, sum_vfx, wg, vg, eta, sidx

    def lambda_deltas(state: FMState, idx, val, y, t, wg, vg, g, sum_vfx, eta):
        # adaptive regularization (ref: FactorizationMachineModel.java:253-300)
        dl_w0 = -eta * g * (-2.0 * eta * state.w0)
        sum_wx = jnp.sum(wg * val)
        dl_w = -eta * g * (-2.0 * eta * sum_wx)
        grad_v = val[:, None] * sum_vfx[None, :] - vg * (val * val)[:, None]
        v_dash = vg - eta * (g * grad_v + 2.0 * state.lambda_v[None, :] * vg)
        sum_f_dash = jnp.sum(val[:, None] * v_dash, axis=0)
        sum_f = sum_vfx
        sum_f_dash_f = jnp.sum(val[:, None] * v_dash * val[:, None] * vg, axis=0)
        dl_v = -eta * g * (-2.0 * eta * (sum_f_dash * sum_f - sum_f_dash_f))
        return dl_w0, dl_w, dl_v

    def scan_step(state: FMState, indices, values, labels, va_mask):
        def body(st: FMState, row):
            idx, val, y, is_va = row
            t = (st.step + 1).astype(jnp.float32)
            dw0, dw, dv, loss, g, p, sum_vfx, wg, vg, eta, sidx = \
                row_deltas(st, idx, val, y, t)
            theta = 1.0 - is_va
            st2 = st.replace(
                w0=st.w0 + theta * dw0,
                w=st.w.at[sidx].add(theta * dw, mode="drop"),
                v=st.v.at[sidx].add(theta * dv, mode="drop"),
                touched=st.touched.at[sidx].max(
                    jnp.broadcast_to((theta > 0).astype(jnp.int8), sidx.shape),
                    mode="drop"),
                step=st.step + 1,
            )
            if hyper.adareg:
                dl_w0, dl_w, dl_v = lambda_deltas(st, idx, val, y, t, wg, vg, g,
                                                  sum_vfx, eta)
                st2 = st2.replace(
                    lambda_w0=jnp.maximum(0.0, st2.lambda_w0 + is_va * dl_w0),
                    lambda_w=jnp.maximum(0.0, st2.lambda_w + is_va * dl_w),
                    lambda_v=jnp.maximum(0.0, st2.lambda_v + is_va * dl_v),
                )
            return st2, theta * loss

        state, losses = jax.lax.scan(body, state, (indices, values, labels, va_mask))
        return state, jnp.sum(losses)

    use_mxu = update_backend == "mxu"

    def minibatch_step(state: FMState, indices, values, labels, va_mask):
        b = indices.shape[0]
        ts = (state.step + 1 + jnp.arange(b)).astype(jnp.float32)
        packed = (state.v.at[:, w_lane].set(state.w) if use_packed else None)

        plan = None
        if use_mxu:
            # sorted-window MXU path (ops/mxu_scatter.py): the packed
            # [D, kp] table is gathered ONCE for the whole block and the
            # update columns ride one windowed scatter — V traffic is the
            # whole FM step cost on v5e (PERF.md FM bisection), and the
            # scalar engine charges ~20ms/block for it
            from ..ops import mxu_scatter as mxu

            plan = mxu.make_plan(indices.reshape(-1), state.w.shape[0])
            pg_all = mxu.gather(packed, plan).reshape(indices.shape
                                                      + (packed.shape[-1],))

            def per_row(idx, val, y, t, pg):
                return row_deltas(state, idx, val, y, t, None, pg)

            dw0, dw, dv, loss, g, p, sum_vfx, wg, vg, eta, sidx = \
                jax.vmap(per_row)(indices, values, labels, ts, pg_all)
        else:
            def per_row(idx, val, y, t):
                return row_deltas(state, idx, val, y, t, packed)

            dw0, dw, dv, loss, g, p, sum_vfx, wg, vg, eta, sidx = \
                jax.vmap(per_row)(indices, values, labels, ts)
        theta = (1.0 - va_mask)  # [B]

        def scatter_v(v_table, upd):
            # Flat-scalar V scatter (ops/scatter.scatter_rows_flat — ~2x the
            # [B,K]-row form on v5e). Only the logical k lanes carry nonzero
            # grads (pad-lane grads are products with their own zero V
            # entries), so scatter those and pad lanes stay provably zero.
            return scatter_rows_flat(v_table, sidx, upd[..., : hyper.factors])

        # accumulate in f32 even if the tables ever go compact (same
        # store-compact/accumulate-wide policy as core/engine.py)
        acc_w = jnp.promote_types(state.w.dtype, jnp.float32)
        acc_v = jnp.promote_types(state.v.dtype, jnp.float32)
        if mini_batch_average and not use_mxu:
            # FloatAccumulator denominators (shared by the packed and
            # unpacked apply below): per-feature touch counts, w0 by the
            # effective batch size
            counts = jnp.zeros((state.w.shape[0],), jnp.float32).at[sidx].add(
                jnp.broadcast_to(theta[:, None], sidx.shape), mode="drop")
            denom = jnp.maximum(counts, 1.0)

        if use_mxu:
            # dv and dw ride one windowed scatter over the packed layout
            # (dw on lane w_lane == factors, exactly its packed position);
            # the per-feature update counts borrow the NEXT pad lane when
            # the shape has one, so counts, denom and touched all come out
            # of the same matmul pass
            from ..ops import mxu_scatter as mxu

            k_log = hyper.factors
            kp = state.v.shape[1]
            cnt_lane = k_log + 1 if k_log + 1 < kp else None
            ids = indices.reshape(-1)
            scaled = (theta[:, None, None] * jnp.concatenate(
                [dv[..., :k_log], dw[..., None]], axis=-1)).astype(acc_v)
            if cnt_lane is not None:
                lane_cnt = jnp.broadcast_to(
                    theta[:, None, None].astype(acc_v),
                    scaled.shape[:2] + (1,))
                scaled = jnp.concatenate([scaled, lane_cnt], axis=-1)
            upd_flat = scaled.reshape(-1, scaled.shape[-1])
            if mini_batch_average:
                acc = mxu.scatter_add(jnp.zeros(state.v.shape, acc_v), ids,
                                      upd_flat, plan)
                if cnt_lane is None:
                    counts = mxu.scatter_add(
                        jnp.zeros((state.w.shape[0],), jnp.float32), ids,
                        jnp.broadcast_to(theta[:, None],
                                         indices.shape).reshape(-1), plan)
                else:
                    counts = acc[:, cnt_lane]
                denom = jnp.maximum(counts, 1.0)
                new_w = (state.w.astype(acc_v) + acc[:, k_log] / denom) \
                    .astype(state.w.dtype)
                new_v = (state.v.astype(acc_v)
                         + acc.at[:, k_log:].set(0.0) / denom[:, None]) \
                    .astype(state.v.dtype)
                new_w0 = state.w0 + jnp.sum(theta * dw0) / jnp.maximum(
                    jnp.sum(theta), 1.0)
            else:
                pk = mxu.scatter_add(packed, ids, upd_flat, plan)
                new_w = pk[:, w_lane]
                if cnt_lane is None:
                    counts = mxu.scatter_add(
                        jnp.zeros((state.w.shape[0],), jnp.float32), ids,
                        jnp.broadcast_to(theta[:, None],
                                         indices.shape).reshape(-1), plan)
                else:
                    counts = pk[:, cnt_lane]
                new_v = pk.at[:, k_log:].set(0.0)
                new_w0 = state.w0 + jnp.sum(theta * dw0)
            touched = jnp.maximum(state.touched,
                                  (counts > 0).astype(jnp.int8))
        elif use_packed:
            # dw rides lane w_lane of the same flat row scatter as dv
            k_log = hyper.factors
            upd = jnp.concatenate([dv[..., :k_log], dw[..., None]], axis=-1)
            if mini_batch_average:
                acc = scatter_rows_flat(jnp.zeros(state.v.shape, acc_v),
                                        sidx,
                                        theta[:, None, None]
                                        * upd.astype(acc_v))
                new_w = (state.w.astype(acc_v) + acc[:, w_lane] / denom) \
                    .astype(state.w.dtype)
                new_v = (state.v.astype(acc_v)
                         + acc.at[:, w_lane].set(0.0) / denom[:, None]) \
                    .astype(state.v.dtype)
                new_w0 = state.w0 + jnp.sum(theta * dw0) / jnp.maximum(
                    jnp.sum(theta), 1.0)
            else:
                pk = scatter_rows_flat(packed, sidx,
                                       theta[:, None, None] * upd)
                new_w = pk[:, w_lane]
                new_v = pk.at[:, w_lane].set(0.0)
                new_w0 = state.w0 + jnp.sum(theta * dw0)
        elif mini_batch_average:
            # FloatAccumulator semantics via full-table delta temporaries +
            # one elementwise apply: scattering counts and delta SUMS then
            # dividing table-wide costs ~0.5ms of HBM streaming, vs ~13ms
            # for the per-lane denominator GATHER the pre-divided variant
            # needs (diag micro gather rate on v5e) — same math, the
            # denominators just divide at the table instead of the lanes.
            dw_sum = jnp.zeros(state.w.shape, acc_w).at[sidx].add(
                theta[:, None] * dw.astype(acc_w), mode="drop")
            new_w = (state.w.astype(acc_w) + dw_sum / denom) \
                .astype(state.w.dtype)
            dv_sum = scatter_v(jnp.zeros(state.v.shape, acc_v),
                               theta[:, None, None] * dv.astype(acc_v))
            new_v = (state.v.astype(acc_v) + dv_sum / denom[:, None]) \
                .astype(state.v.dtype)
            new_w0 = state.w0 + jnp.sum(theta * dw0) / jnp.maximum(
                jnp.sum(theta), 1.0)
        else:
            new_w = state.w.at[sidx].add(theta[:, None] * dw, mode="drop")
            new_v = scatter_v(state.v, theta[:, None, None] * dv)
            new_w0 = state.w0 + jnp.sum(theta * dw0)
        if not use_mxu:
            touched = state.touched.at[sidx].max(
                jnp.broadcast_to((theta > 0).astype(jnp.int8)[:, None],
                                 sidx.shape),
                mode="drop")
        new_state = state.replace(
            w0=new_w0,
            w=new_w,
            v=new_v,
            touched=touched,
            step=state.step + b,
        )
        if hyper.adareg:
            def per_row_lambda(idx, val, y, t, wg_, vg_, g_, sv_, eta_):
                return lambda_deltas(state, idx, val, y, t, wg_, vg_, g_, sv_, eta_)

            dl_w0, dl_w, dl_v = jax.vmap(per_row_lambda)(
                indices, values, labels, ts, wg, vg, g, sum_vfx, eta)
            vam = va_mask
            new_state = new_state.replace(
                lambda_w0=jnp.maximum(0.0, state.lambda_w0 + jnp.sum(vam * dl_w0)),
                lambda_w=jnp.maximum(0.0, state.lambda_w + jnp.sum(vam * dl_w)),
                lambda_v=jnp.maximum(0.0, state.lambda_v
                                     + jnp.sum(vam[:, None] * dl_v, axis=0)),
            )
        return new_state, jnp.sum(theta * loss)

    step = scan_step if mode == "scan" else minibatch_step
    # jit=False returns the raw traceable fn for embedding in an outer scan
    # (e.g. a whole-epoch lax.scan over staged blocks, scripts/bench_ctr_e2e.py)
    return jax.jit(step, donate_argnums=(0,)) if jit else step


@jax.jit
def _fm_scores(state: FMState, indices, values):
    def one(idx, val):
        wg = state.w.at[idx].get(mode="fill", fill_value=0.0)
        vg = state.v.at[idx].get(mode="fill", fill_value=0.0)
        p, _ = _row_predict(state.w0, wg, vg, val)
        return p

    return jax.vmap(one)(indices, values)


@dataclass
class TrainedFMModel:
    state: FMState
    hyper: FMHyper
    dims: int

    def predict(self, features: FeatureRows) -> np.ndarray:
        idx_rows, val_rows = _stage_rows(features, self.dims)
        n = len(idx_rows)
        width = pad_to_bucket(max((len(r) for r in idx_rows), default=1))
        out = []
        for blk in iter_blocks(idx_rows, val_rows, np.zeros(n), self.dims, 4096, width):
            out.append(np.asarray(_fm_scores(self.state, blk.indices, blk.values)))
        return np.concatenate(out)[:n]

    def model_rows(self):
        """(feature, Wi, Vi[factors]) rows + the w0 bias row (feature 0 carries
        w0, ref: forwardAsIntFeature FactorizationMachineUDTF.java:446-519)."""
        touched = np.asarray(self.state.touched) != 0
        feats = np.nonzero(touched)[0].astype(np.int64)
        w = np.asarray(self.state.w)[feats]
        # slice physical lane padding (padded_factors) back to the logical k
        v = np.asarray(self.state.v)[feats][:, :self.hyper.factors]
        return float(self.state.w0), feats, w, v


def _fm_options() -> Options:
    o = base_options()
    o.add("c", "classification", False, "Act as classification")
    o.add("seed", None, True, "Seed value [default: 31]", default=31, type=int)
    o.add("p", "num_features", True, "The size of feature dimensions", type=int)
    o.add("factor", "factors", True, "Number of latent factors [default: 5]",
          default=5, type=int)
    o.add("sigma", None, True, "Stddev for initializing V [default: 0.1]",
          default=0.1, type=float)
    o.add("lambda0", "lambda", True, "Regularization lambda [default: 0.01]",
          default=0.01, type=float)
    o.add("min", "min_target", True, "Min target value", type=float)
    o.add("max", "max_target", True, "Max target value", type=float)
    o.add("eta", None, True, "Fixed learning rate", type=float)
    o.add("eta0", None, True, "Initial learning rate [default 0.05]", default=0.05,
          type=float)
    o.add("t", "total_steps", True, "Total training steps", type=int)
    o.add("power_t", None, True, "Inverse-scaling exponent [default 0.1]",
          default=0.1, type=float)
    o.add("adareg", "adaptive_regularizaion", False, "Adaptive regularization")
    o.add("va_ratio", "validation_ratio", True, "Validation ratio [default 0.05]",
          default=0.05, type=float)
    o.add("int_feature", "feature_as_integer", False, "Parse features as integers")
    return o


def train_fm(features: FeatureRows, targets, options: Optional[str] = None,
             **kw) -> TrainedFMModel:
    cl = _fm_options().parse(options, "train_fm")
    dims = cl.get_int("dims") or cl.get_int("p") or DEFAULT_NUM_FEATURES
    hyper = FMHyper(
        factors=cl.get_int("factor", 5),
        classification=cl.has("c"),
        lambda0=cl.get_float("lambda0", 0.01),
        sigma=cl.get_float("sigma", 0.1),
        min_target=cl.get_float("min", -3.0e38),
        max_target=cl.get_float("max", 3.0e38),
        eta=get_eta(cl, 0.05),
        adareg=cl.has("adareg"),
        va_ratio=cl.get_float("va_ratio", 0.05),
        seed=cl.get_int("seed", 31),
    )
    targets = np.asarray(targets, dtype=np.float32)
    if hyper.classification:
        targets = np.where(targets > 0, 1.0, -1.0).astype(np.float32)
    idx_rows, val_rows = _stage_rows(features, dims)
    n = len(idx_rows)
    width = pad_to_bucket(max((len(r) for r in idx_rows), default=1))
    mini_batch = cl.get_int("mini_batch", 1)
    mode = "minibatch" if mini_batch > 1 else "scan"
    block = mini_batch if mode == "minibatch" else cl.get_int("block_size", 4096)
    iters = cl.get_int("iters", 1)
    if cl.has("native_scan"):
        return _train_fm_native_scan(cl, hyper, dims, idx_rows, val_rows,
                                     targets, width, block, mode, iters)
    backend = "mxu" if (cl.has("mxu_scatter") and mode == "minibatch") \
        else "xla"
    step = make_fm_step(hyper, mode, update_backend=backend)
    state = init_fm_state(dims, hyper)
    rng = np.random.RandomState(hyper.seed)
    conv = ConversionState(not cl.has("disable_cv"), cl.get_float("cv_rate", 0.005))
    for it in range(max(1, iters)):
        if cl.has("shuffle") and it > 0:
            idx_rows, val_rows, targets = shuffle_rows(idx_rows, val_rows, targets,
                                                       hyper.seed + it)
        epoch_loss = 0.0
        for blk in iter_blocks(idx_rows, val_rows, targets, dims, block, width):
            va = (rng.rand(blk.batch_size) < hyper.va_ratio).astype(np.float32) \
                if hyper.adareg else np.zeros(blk.batch_size, np.float32)
            state, loss = step(state, blk.indices, blk.values, blk.labels, va)
            epoch_loss += float(loss)
        conv.incr_loss(epoch_loss)
        if iters > 1 and conv.is_converged(n):
            break
    return TrainedFMModel(state=state, hyper=hyper, dims=dims)


def _train_fm_native_scan(cl, hyper: FMHyper, dims, idx_rows, val_rows,
                          targets, width, block, mode, iters
                          ) -> TrainedFMModel:
    """`-native_scan`: exact sequential FM epochs through the C row loop
    (native/hivemall_native.cpp::hm_fm_reference_rowloop — the train_fm
    bench anchor shipped as a host execution backend, like AROW's in
    models/base.py). Envelope = where the C loop and the framework step
    coincide: -classification, a FIXED -eta, no -adareg, per-row scan
    mode; anything else refuses loudly. Starts from the framework's own
    seeded V init, so results match the engine's scan mode (one pinned
    deviation: a feature duplicated WITHIN a row sees in-place partial
    updates lane to lane, exactly like the reference's per-feature loop,
    where the engine batch-gathers the row once)."""
    from .. import native

    problems = []
    if not hyper.classification:
        problems.append("-classification (the C loop is the logistic form)")
    if hyper.eta.kind != "fixed":
        problems.append("a fixed -eta (C runs a constant learning rate)")
    if hyper.adareg:
        problems.append("no -adareg")
    if mode != "scan":
        problems.append("per-row scan mode (drop -mini_batch)")
    if problems:
        raise ValueError("-native_scan for train_fm requires: "
                         + "; ".join(problems))
    state0 = init_fm_state(dims, hyper)
    k = hyper.factors
    # one sentinel slot at index dims: block padding writes land there and
    # are sliced off (value-0 lanes still take the L2 decay term, like the
    # reference's own loop — confined to the sentinel)
    st = {
        "w0": np.zeros(1, np.float32),
        "w": np.concatenate([np.asarray(state0.w), np.zeros(1, np.float32)]),
        "V": np.concatenate([np.asarray(state0.v)[:, :k],
                             np.zeros((1, k), np.float32)]),
        "touch": np.zeros(dims + 1, np.uint8),
    }
    # zero-row probe: availability check that cannot touch the state
    # (a fake row would shift the GLOBAL w0 — advisor-caught)
    probe = native.fm_reference_rowloop(
        np.zeros((0, 1), np.int32), np.zeros((0, 1), np.float32),
        np.zeros(0, np.float32), dims + 1, k=k, eta=hyper.eta.eta0,
        lam=hyper.lambda0, state=st, track_touched=True)
    if probe is None:
        raise RuntimeError("-native_scan requires the native library "
                           "(bash scripts/build_native.sh)")
    n = len(idx_rows)
    conv = ConversionState(not cl.has("disable_cv"),
                           cl.get_float("cv_rate", 0.005))
    for it in range(max(1, iters)):
        if cl.has("shuffle") and it > 0:
            idx_rows, val_rows, targets = shuffle_rows(
                idx_rows, val_rows, targets, hyper.seed + it)
        epoch_errors = 0
        for blk in iter_blocks(idx_rows, val_rows, targets, dims, block,
                               width):
            epoch_errors += native.fm_reference_rowloop(
                blk.indices, blk.values, blk.labels, dims + 1, k=k,
                eta=hyper.eta.eta0, lam=hyper.lambda0, state=st,
                track_touched=True)
        # convergence proxy = sign-error count (the C loop's return);
        # the engine tracks logloss — documented deviation
        conv.incr_loss(float(epoch_errors))
        if iters > 1 and conv.is_converged(n):
            break
    v_back = st["V"][:dims]
    if hyper.padded_factors != k:  # restore the physical lane padding
        v_back = np.concatenate(
            [v_back, np.zeros((dims, hyper.padded_factors - k), np.float32)],
            axis=1)
    state = state0.replace(
        w0=jnp.asarray(np.float32(st["w0"][0])),
        w=jnp.asarray(st["w"][:dims]),
        v=jnp.asarray(v_back),
        touched=jnp.asarray((st["touch"][:dims] != 0).astype(np.int8)),
        step=jnp.asarray(np.int32(n * (it + 1))),
    )
    return TrainedFMModel(state=state, hyper=hyper, dims=dims)


def fm_predict(w0: float, w: Sequence[float], v: Sequence[Sequence[float]],
               feats: Sequence[int], xs: Sequence[float]) -> float:
    """`fm_predict` UDAF equivalent: score one row from model rows
    (ref: fm/FMPredictGenericUDAF.java) — p = w0 + sum w_i x_i + pairwise V term."""
    w = np.asarray(w, dtype=np.float64)
    v = np.asarray(v, dtype=np.float64)
    x = np.asarray(xs, dtype=np.float64)
    linear = float(np.sum(w * x))
    vx = v * x[:, None]
    s = np.sum(vx, axis=0)
    s2 = np.sum(vx * vx, axis=0)
    return float(w0 + linear + 0.5 * np.sum(s * s - s2))
