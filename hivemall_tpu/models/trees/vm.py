"""StackMachine — opcode-script evaluator, operation-compatible with the
reference VM (ref: smile/vm/StackMachine.java:30-280, smile/vm/Operation.java:37):
push / pop / goto / ifeq / ifeq2 / ifge / ifgt / ifle / iflt / call end.

Comparison ops pop (lower, upper) in that order and fall through when the
comparison holds (e.g. ifle: continue when upper <= lower, else jump)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence


class VMRuntimeError(RuntimeError):
    pass


# numeric op encoding shared with the native bulk evaluator
# (native/hivemall_native.cpp hm_forest_eval)
OP_PUSH_FEATURE = 0
OP_PUSH_CONST = 1
OP_POP = 2
OP_GOTO = 3
OP_IFEQ = 4
OP_IFGE = 5
OP_IFGT = 6
OP_IFLE = 7
OP_IFLT = 8
OP_CALL_END = 9

_IF_OPS = {"ifeq": OP_IFEQ, "ifeq2": OP_IFEQ, "ifge": OP_IFGE,
           "ifgt": OP_IFGT, "ifle": OP_IFLE, "iflt": OP_IFLT}


def compile_script_arrays(script):
    """Lower a StackMachine script to flat (ops int8, argi int32, argf float64)
    arrays for the native bulk evaluator. Same semantics as StackMachine.eval;
    'last' jump targets resolve to the final op, 'end' pushes -1.0."""
    import numpy as np

    lines = script.split(StackMachine.SEP) if isinstance(script, str) \
        else list(script)
    n = len(lines)
    ops = np.zeros(n, np.int8)
    argi = np.zeros(n, np.int32)
    argf = np.zeros(n, np.float64)

    def target(operand: str) -> int:
        if operand == "last":
            return n - 1
        return int(operand)

    for k, line in enumerate(lines):
        parts = line.split(" ")
        op = parts[0].lower()
        operand = parts[1] if len(parts) > 1 and parts[1] != "" else None
        if op == "push":
            if operand.startswith("x[") and operand.endswith("]"):
                ops[k] = OP_PUSH_FEATURE
                argi[k] = int(operand[2:-1])
            elif operand == "end":
                ops[k] = OP_PUSH_CONST
                argf[k] = -1.0
            else:
                ops[k] = OP_PUSH_CONST
                argf[k] = float(operand)
        elif op == "pop":
            ops[k] = OP_POP
        elif op == "goto":
            ops[k] = OP_GOTO
            argi[k] = target(operand)
        elif op in _IF_OPS:
            ops[k] = _IF_OPS[op]
            argi[k] = target(operand)
        elif op == "call":
            if operand != "end":
                raise VMRuntimeError(f"unknown function {operand}")
            ops[k] = OP_CALL_END
        else:
            raise VMRuntimeError(f"unknown op {op}")
    return ops, argi, argf


class StackMachine:
    SEP = "; "

    def __init__(self) -> None:
        self.code: List[tuple] = []
        self.result: Optional[float] = None

    def compile(self, script) -> None:
        ops = script.split(self.SEP) if isinstance(script, str) else list(script)
        self.code = []
        for line in ops:
            parts = line.split(" ")
            op = parts[0].lower()
            operand = parts[1] if len(parts) > 1 and parts[1] != "" else None
            self.code.append((op, operand))

    def run(self, script, features: Sequence[float]) -> Optional[float]:
        self.compile(script)
        return self.eval(features)

    def eval(self, features: Sequence[float]) -> Optional[float]:
        values: Dict[str, float] = {f"x[{i}]": float(v) for i, v in enumerate(features)}
        values["end"] = -1.0
        jump = {"last": len(self.code) - 1}
        stack: List[float] = []
        done = [False] * len(self.code)
        self.result = None
        ip = 0

        def target(operand: str) -> int:
            try:
                return int(operand)
            except (TypeError, ValueError):
                return jump[operand]

        while ip < len(self.code):
            if done[ip]:
                raise VMRuntimeError("There is an infinite loop in the machine code.")
            done[ip] = True
            op, operand = self.code[ip]
            if op == "push":
                if operand in values:
                    stack.append(values[operand])
                else:
                    stack.append(float(operand))
                ip += 1
            elif op == "pop":
                self.result = stack.pop()
                ip += 1
            elif op == "goto":
                ip = target(operand)
            elif op in ("ifeq", "ifeq2"):
                a = stack.pop()
                b = stack.pop()
                ip = ip + 1 if a == b else target(operand)
            elif op in ("ifge", "ifgt", "ifle", "iflt"):
                lower = stack.pop()
                upper = stack.pop()
                ok = {"ifge": upper >= lower, "ifgt": upper > lower,
                      "ifle": upper <= lower, "iflt": upper < lower}[op]
                ip = ip + 1 if ok else target(operand)
            elif op == "call":
                if operand == "end":
                    self.result = stack.pop()
                    return self.result
                raise VMRuntimeError(f"unknown function {operand}")
            else:
                raise VMRuntimeError(f"unknown op {op}")
        return self.result
