"""Feature binning for histogram-based tree growth.

The reference does exact split search over per-column pre-sorted values
(ref: smile/classification/DecisionTree.java:407+, column order[][] built in
RandomForestClassifierUDTF.java:288-302). Exact sorted-column CART is hostile
to TPU (data-dependent loops, dynamic shapes); the TPU-first equivalent is
XGBoost/LightGBM-style quantile binning: each numeric column is discretized
into <=255 bins once up front, then every split decision is a histogram sum —
one big scatter-add per tree level (SURVEY.md §7 step 7 / hard part (d)).

Nominal attributes keep their category ids as bin ids and split by equality,
matching the reference's NOMINAL attribute handling.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

MAX_BINS = 64


@dataclass
class BinInfo:
    """Per-feature binning: `edges[b]` is the upper edge (inclusive) of bin b
    in original units; nominal features have edges = category values."""

    nominal: bool
    edges: np.ndarray  # [n_bins] float64
    n_bins: int


def make_bins(X: np.ndarray, attrs: Sequence[str],
              max_bins: int = MAX_BINS) -> List[BinInfo]:
    """attrs[i] in {'Q' (quantitative), 'C' (categorical/nominal)}
    (the reference's -attrs Q,C,... option, RandomForestClassifierUDTF.java:113)."""
    out: List[BinInfo] = []
    for f in range(X.shape[1]):
        col = X[:, f]
        if attrs[f] == "C":
            cats = np.unique(col)
            out.append(BinInfo(True, cats.astype(np.float64), len(cats)))
        else:
            qs = np.quantile(col, np.linspace(0, 1, max_bins + 1)[1:])
            edges = np.unique(qs)
            out.append(BinInfo(False, edges.astype(np.float64), len(edges)))
    return out


def bin_data(X: np.ndarray, bins: List[BinInfo]) -> np.ndarray:
    """[N, F] float -> [N, F] uint8 bin ids."""
    n, F = X.shape
    out = np.empty((n, F), dtype=np.int32)
    for f in range(F):
        b = bins[f]
        if b.nominal:
            out[:, f] = np.searchsorted(b.edges, X[:, f])
            out[:, f] = np.clip(out[:, f], 0, b.n_bins - 1)
        else:
            out[:, f] = np.searchsorted(b.edges, X[:, f], side="left")
            out[:, f] = np.clip(out[:, f], 0, b.n_bins - 1)
    return out


def threshold_of(bins: List[BinInfo], f: int, bin_id: int) -> float:
    """Real-unit split value for `x <= threshold` (numeric) or `x == value`
    (nominal) recovered from a bin id — so exported trees evaluate on raw
    features exactly like the reference's."""
    b = bins[f]
    return float(b.edges[min(bin_id, b.n_bins - 1)])
