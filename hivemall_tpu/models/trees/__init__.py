from .forest import (  # noqa: F401
    train_gradient_tree_boosting_classifier,
    train_randomforest_classifier,
    train_randomforest_regr,
)
from .predict import guess_attrs, tree_predict  # noqa: F401
from .vm import StackMachine  # noqa: F401
