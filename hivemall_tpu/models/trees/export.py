"""Tree model export formats.

The reference exports trees three ways (ref: smile/classification/DecisionTree.java):
- **opscode** — the StackMachine script (`opCodegen`, :300-341)
- **serialization** — compressed Java-serialized Node graph (`predictSerCodegen`, :927)
- **javascript** — nested if/else source

We export:
- the same opscode format (verbatim grammar: `push x[f]; push v; ifle L; ...;
  call end`), evaluable by vm.StackMachine and by the reference's own VM;
- a portable JSON node-graph (the serialization analog — Java object streams
  make no sense off-JVM);
- javascript source (nested ternaries) for parity.
"""

from __future__ import annotations

import json
from typing import List

import numpy as np

from .binning import BinInfo, threshold_of
from .grow import TreeArrays

# Numeric model-type ids, matching the reference enum (ref: smile/ModelType.java:20-27):
# positive = uncompressed, negative = compressed variant. Our "json" plays the
# serialization role off-JVM.
MODEL_TYPE_IDS = {
    "opscode": 1,
    "javascript": 2,
    "json": 3,  # serialization analog
    "opscode_compressed": -1,
    "javascript_compressed": -2,
    "json_compressed": -3,
}


def model_type_id(name: str, compressed: bool = False) -> int:
    key = f"{name}_compressed" if compressed else name
    return MODEL_TYPE_IDS[key]


def _op_codegen(tree: TreeArrays, bins: List[BinInfo], node: int,
                scripts: List[str], depth: int) -> int:
    """Mirror of DecisionTree.Node.opCodegen (ref: DecisionTree.java:300-341):
    true branch falls through, false branch target patched into the if op."""
    self_depth = 0
    f = int(tree.feature[node])
    if f < 0:
        scripts.append(f"push {_leaf_output(tree, node)}")
        scripts.append("goto last")
        return 2
    v = threshold_of(bins, f, int(tree.threshold_bin[node]))
    scripts.append(f"push x[{f}]")
    scripts.append(f"push {v}")
    op = "ifeq" if tree.nominal[node] else "ifle"
    scripts.append(f"{op} ")
    depth += 3
    self_depth += 3
    true_depth = _op_codegen(tree, bins, int(tree.left[node]), scripts, depth)
    self_depth += true_depth
    scripts[depth - 1] = f"{op} {depth + true_depth}"
    false_depth = _op_codegen(tree, bins, int(tree.right[node]), scripts,
                              depth + true_depth)
    return self_depth + false_depth


def _leaf_output(tree: TreeArrays, node: int):
    if tree.leaf_dist is not None:
        return int(tree.leaf_value[node])
    return float(tree.leaf_value[node])


def to_opscode(tree: TreeArrays, bins: List[BinInfo]) -> str:
    scripts: List[str] = []
    _op_codegen(tree, bins, 0, scripts, 0)
    scripts.append("call end")
    return "; ".join(scripts)


def to_json(tree: TreeArrays, bins: List[BinInfo]) -> str:
    """Portable node-graph export (serialization-format analog)."""

    def node_dict(i: int):
        f = int(tree.feature[i])
        if f < 0:
            d = {"leaf": _leaf_output(tree, i)}
            if tree.leaf_dist is not None:
                total = float(tree.leaf_dist[i].sum())
                if total > 0:
                    d["posteriori"] = (tree.leaf_dist[i] / total).tolist()
            return d
        return {
            "feature": f,
            "value": threshold_of(bins, f, int(tree.threshold_bin[i])),
            "nominal": bool(tree.nominal[i]),
            "left": node_dict(int(tree.left[i])),
            "right": node_dict(int(tree.right[i])),
        }

    return json.dumps(node_dict(0))


def to_javascript(tree: TreeArrays, bins: List[BinInfo]) -> str:
    """Nested if/else source (ref: DecisionTree jsCodegen export)."""

    def gen(i: int, indent: str) -> str:
        f = int(tree.feature[i])
        if f < 0:
            return f"{indent}{_leaf_output(tree, i)};"
        v = threshold_of(bins, f, int(tree.threshold_bin[i]))
        cmp = "==" if tree.nominal[i] else "<="
        return (f"{indent}if (x[{f}] {cmp} {v}) {{\n"
                + gen(int(tree.left[i]), indent + "  ")
                + f"\n{indent}}} else {{\n"
                + gen(int(tree.right[i]), indent + "  ")
                + f"\n{indent}}}")

    return gen(0, "")


def eval_json_tree(model: str, x) -> float:
    """Evaluate a to_json tree on raw features."""
    node = json.loads(model) if isinstance(model, str) else model
    while "leaf" not in node:
        f, v = node["feature"], node["value"]
        go_left = (x[f] == v) if node["nominal"] else (x[f] <= v)
        node = node["left"] if go_left else node["right"]
    return node["leaf"]
