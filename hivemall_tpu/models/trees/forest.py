"""Random forest + gradient tree boosting trainers.

Mirrors the reference decision-forest subsystem (ref: SURVEY.md §2.8):
- train_randomforest_classifier (RandomForestClassifierUDTF.java:113-425):
  batch training, bootstrap bag per tree, per-node random feature subspace,
  OOB error estimate, per-tree model emission (modelId, modelType, model,
  var_importance, oob_errors, oob_tests)
- train_randomforest_regr (RandomForestRegressionUDTF.java:75)
- train_gradient_tree_boosting_classifier (GradientTreeBoostingClassifierUDTF.java:70-658):
  binary logistic GBT with shrinkage + row subsampling; multiclass via
  softmax K-trees per round

TPU-first: the reference parallelizes per-tree across a JVM thread pool
(SmileTaskExecutor.java:63-78); here each tree's O(N·F) histogram work is a
jitted device kernel (grow.py) and the per-tree loop is host-side — the device
kernels are batched enough to saturate a chip; multi-device forests shard
trees across the mesh the same way the reference sharded across mappers.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ...utils.options import Options
from .binning import BinInfo, MAX_BINS, bin_data, make_bins
from .export import to_javascript, to_json, to_opscode
from .grow import (TreeArrays, grow_forest, grow_tree, predict_binned,
                   predict_forest_binned, stack_trees)


def _forest_options(gbt: bool = False) -> Options:
    o = Options()
    o.add("trees", "num_trees", True, "Number of trees [default: 50]",
          default=500 if gbt else 50, type=int)
    o.add("vars", "num_variables", True,
          "Random feature candidates per node [default: ceil(sqrt(F))]", type=float)
    o.add("depth", "max_depth", True, "Max tree depth", default=8 if gbt else 16,
          type=int)
    o.add("leafs", "max_leaf_nodes", True, "Max leaf nodes", default=512, type=int)
    o.add("splits", "min_split", True, "Min samples to split "
          "[default: 5 (gbt) / 2]", default=5 if gbt else 2, type=int)
    o.add("min_samples_leaf", None, True, "Min samples per leaf [default: 1]",
          default=1, type=int)
    o.add("seed", None, True, "Seed [default: -1 random]", default=-1, type=int)
    o.add("attrs", "attribute_types", True, "Comma-separated Q/C attribute types")
    o.add("output", "output_type", True,
          "Output type (serialization/ser, opscode/vm, javascript/js) "
          "[default: opscode]", default="opscode")
    o.add("disable_compression", None, False, "accepted for parity")
    o.add("grow", "grow_strategy", True,
          "Forest growth strategy auto|per_tree|batched [default: auto — "
          "per_tree unless row-sharded; measured fastest on both platforms, "
          "scripts/bench_forest.py]", default="auto")
    if gbt:
        o.add("eta", "learning_rate", True, "Learning rate [default: 0.05]",
              default=0.05, type=float)
        o.add("subsample", "sampling_frac", True, "Row subsample fraction "
              "[default: 0.7]", default=0.7, type=float)
        o.add("iters", None, True, "alias of -trees", type=int)
    else:
        o.add("rule", "split_rule", True, "Split rule GINI|ENTROPY [default GINI]",
              default="gini")
    return o


def _resolve_attrs(attrs_opt: Optional[str], F: int) -> List[str]:
    if not attrs_opt:
        return ["Q"] * F
    attrs = [a.strip().upper() for a in attrs_opt.split(",")]
    if len(attrs) != F:
        raise ValueError(f"-attrs has {len(attrs)} entries for {F} features")
    return attrs


def _num_vars(opt: Optional[float], F: int) -> int:
    """-vars: absolute count, or fraction when in (0, 1]
    (ref: RandomForestClassifierUDTF.java:115-117)."""
    if opt is None or opt <= 0:
        return max(1, int(math.ceil(math.sqrt(F))))
    if opt <= 1.0:
        return max(1, int(opt * F))
    return min(F, int(opt))


@dataclass
class TreeModel:
    model_id: int
    model_type: str  # opscode | json | javascript
    model: str
    var_importance: np.ndarray
    oob_errors: int
    oob_tests: int
    tree: TreeArrays
    bins: List[BinInfo]


@dataclass
class TrainedForest:
    trees: List[TreeModel]
    classification: bool
    n_classes: int
    bins: List[BinInfo]
    attrs: List[str]

    def predict(self, X) -> np.ndarray:
        """Majority vote (classification) / mean (regression) over trees —
        what rf_ensemble does over the emitted per-tree predictions. All trees
        evaluate in ONE vmapped device walk (stacked node arrays)."""
        from .grow import predict_forest_binned, stack_trees

        X = np.asarray(X, dtype=np.float64)
        Xb = bin_data(X, self.bins)
        stacked = stack_trees([t.tree for t in self.trees])
        leaf_vals = np.asarray(predict_forest_binned(stacked, Xb))  # [T, N]
        if self.classification:
            return forest_vote(leaf_vals, self.n_classes)
        return leaf_vals.mean(axis=0)

    def model_rows(self):
        """Per-tree rows (model_id, model_type, model, var_importance,
        oob_errors, oob_tests) (ref: RandomForestClassifierUDTF.java:343-351)."""
        return [(t.model_id, t.model_type, t.model, t.var_importance.tolist(),
                 t.oob_errors, t.oob_tests) for t in self.trees]


def forest_vote(leaf_vals: np.ndarray, n_classes: int) -> np.ndarray:
    """Per-tree leaf classes [T, N] -> majority-vote class ids [N]. The one
    aggregation both the trained object and the serving engine
    (serving/engine.py) run, so they cannot diverge."""
    n = leaf_vals.shape[1]
    votes = np.zeros((n, n_classes))
    for t in range(leaf_vals.shape[0]):
        votes[np.arange(n), leaf_vals[t].astype(int)] += 1
    return np.argmax(votes, axis=1)


def gbt_decision_scores(leaf_vals: np.ndarray, intercept, shrinkage: float,
                        n_rounds: int, n_class_trees: int) -> np.ndarray:
    """Per-tree leaf outputs [n_rounds * K, N] (round-major) ->
    intercept + shrinkage * per-class sums, [N, K]. Shared by
    TrainedGBT.decision_function and the serving engine."""
    n = leaf_vals.shape[1] if leaf_vals.ndim == 2 else 0
    # intercept keeps its training dtype (f64 from the boosting fit)
    scores = np.tile(np.asarray(intercept), (n, 1))
    if leaf_vals.size:
        contrib = leaf_vals.reshape(n_rounds, n_class_trees, n)
        scores += shrinkage * contrib.sum(axis=0).T
    return scores


def _var_importance(tree: TreeArrays, F: int) -> np.ndarray:
    """Accumulated impurity-gain importance recorded during growth (what the
    reference accumulates per split); split-count fallback for trees loaded
    without it."""
    if tree.importance is not None:
        return tree.importance
    imp = np.zeros(F)
    for i in range(tree.n_nodes):
        if tree.feature[i] >= 0:
            imp[tree.feature[i]] += 1.0
    return imp


def _export(tree: TreeArrays, bins, output: str) -> Tuple[str, str]:
    if output in ("opscode", "vm"):
        return "opscode", to_opscode(tree, bins)
    if output in ("javascript", "js"):
        return "javascript", to_javascript(tree, bins)
    # "serialization" -> portable JSON node graph (off-JVM analog)
    return "json", to_json(tree, bins)


def train_randomforest_classifier(X, labels, options: Optional[str] = None,
                                  classes=None) -> TrainedForest:
    """`classes`: optional GLOBAL label list — pass it when training shards
    on data partitions so every shard's exported trees vote in the same
    class-index space even if a partition is missing some class
    (parallel/forest_shard.py does this)."""
    cl = _forest_options().parse(options, "train_randomforest_classifier")
    X = np.asarray(X, dtype=np.float64)
    y = np.asarray(labels)
    if classes is None:
        classes, y_idx = np.unique(y, return_inverse=True)
    else:
        classes = np.unique(np.asarray(classes))  # sorted, like np.unique(y)
        y_idx = np.searchsorted(classes, y)
        if np.any(classes[np.clip(y_idx, 0, len(classes) - 1)] != y):
            raise ValueError("labels contain values not in `classes`")
    n_classes = len(classes)
    N, F = X.shape
    attrs = _resolve_attrs(cl.get("attrs"), F)
    bins = make_bins(X, attrs)
    Xb = bin_data(X, bins)
    n_bins = max(b.n_bins for b in bins)
    seed = cl.get_int("seed", -1)
    rng = np.random.RandomState(seed if seed >= 0 else None)
    rule = str(cl.get("rule", "gini")).lower()
    num_vars = _num_vars(cl.get_float("vars") if cl.has("vars") else None, F)
    nominal_mask = np.array([a == "C" for a in attrs])

    # bootstrap bag per tree (ref: :362-425 TrainingTask), then grow the WHOLE
    # forest level-synchronously — one device histogram pass per level covers
    # every tree (grow.grow_forest), replacing the reference's per-tree
    # thread-pool with batched kernels
    T = cl.get_int("trees", 50)
    W = np.stack([
        np.bincount(rng.randint(0, N, size=N), minlength=N).astype(np.float32)
        for _ in range(T)])
    tree_rngs = [np.random.RandomState(rng.randint(0, 2 ** 31)) for _ in range(T)]
    grown = grow_forest(
        Xb, y_idx, W, nominal_mask, n_bins,
        classification=True, n_classes=n_classes, rule=rule,
        max_depth=cl.get_int("depth", 16),
        min_split=cl.get_int("splits", 2),
        min_leaf=cl.get_int("min_samples_leaf", 1),
        max_leaf_nodes=cl.get_int("leafs", 512),
        num_vars=num_vars, rngs=tree_rngs,
        strategy=str(cl.get("grow", "auto")),
    )
    # OOB error for all trees in one vmapped walk (ref: :330-341)
    leaf_vals = np.asarray(predict_forest_binned(stack_trees(grown), Xb))  # [T, N]
    trees: List[TreeModel] = []
    output = str(cl.get("output", "opscode"))
    for t, tree in enumerate(grown):
        oob = W[t] == 0
        oob_tests = int(oob.sum())
        oob_errors = int(np.sum(leaf_vals[t, oob].astype(int) != y_idx[oob]))
        mtype, model = _export(tree, bins, output)
        trees.append(TreeModel(t, mtype, model, _var_importance(tree, F),
                               oob_errors, oob_tests, tree, bins))
    return TrainedForest(trees, True, n_classes, bins, attrs)


def train_randomforest_regr(X, targets, options: Optional[str] = None
                            ) -> TrainedForest:
    cl = _forest_options().parse(options, "train_randomforest_regr")
    X = np.asarray(X, dtype=np.float64)
    y = np.asarray(targets, dtype=np.float32)
    N, F = X.shape
    attrs = _resolve_attrs(cl.get("attrs"), F)
    bins = make_bins(X, attrs)
    Xb = bin_data(X, bins)
    n_bins = max(b.n_bins for b in bins)
    seed = cl.get_int("seed", -1)
    rng = np.random.RandomState(seed if seed >= 0 else None)
    num_vars = _num_vars(cl.get_float("vars") if cl.has("vars") else None, F)
    nominal_mask = np.array([a == "C" for a in attrs])

    T = cl.get_int("trees", 50)
    W = np.stack([
        np.bincount(rng.randint(0, N, size=N), minlength=N).astype(np.float32)
        for _ in range(T)])
    tree_rngs = [np.random.RandomState(rng.randint(0, 2 ** 31)) for _ in range(T)]
    grown = grow_forest(
        Xb, y, W, nominal_mask, n_bins,
        classification=False,
        max_depth=cl.get_int("depth", 16),
        min_split=cl.get_int("splits", 2),
        min_leaf=cl.get_int("min_samples_leaf", 1),
        max_leaf_nodes=cl.get_int("leafs", 512),
        num_vars=num_vars, rngs=tree_rngs,
        strategy=str(cl.get("grow", "auto")),
    )
    leaf_vals = np.asarray(predict_forest_binned(stack_trees(grown), Xb))  # [T, N]
    trees: List[TreeModel] = []
    output = str(cl.get("output", "opscode"))
    for t, tree in enumerate(grown):
        oob = W[t] == 0
        oob_tests = int(oob.sum())
        oob_err = float(np.sum((leaf_vals[t, oob] - y[oob]) ** 2))
        mtype, model = _export(tree, bins, output)
        trees.append(TreeModel(t, mtype, model, _var_importance(tree, F),
                               int(oob_err), oob_tests, tree, bins))
    return TrainedForest(trees, False, 0, bins, attrs)


@dataclass
class TrainedGBT:
    trees: List[List[TreeArrays]]  # per round, per class (1 for binary)
    intercept: np.ndarray  # [K] initial score
    shrinkage: float
    classes: np.ndarray
    bins: List[BinInfo]

    def decision_function(self, X) -> np.ndarray:
        from .grow import predict_forest_binned, stack_trees

        X = np.asarray(X, dtype=np.float64)
        Xb = bin_data(X, self.bins)
        K = len(self.intercept)
        flat = [t for round_trees in self.trees for t in round_trees]
        if not flat:
            return np.tile(self.intercept, (X.shape[0], 1))
        # rows are (round, class) in order
        leaf_vals = np.asarray(predict_forest_binned(stack_trees(flat), Xb))
        return gbt_decision_scores(leaf_vals, self.intercept, self.shrinkage,
                                   len(self.trees), K)

    def predict(self, X) -> np.ndarray:
        s = self.decision_function(X)
        if s.shape[1] == 1:
            return self.classes[(s[:, 0] > 0).astype(int)]
        return self.classes[np.argmax(s, axis=1)]

    def model_rows(self, output: str = "opscode"):
        """One row per (boosting round, class tree): (iter, cls,
        model_type, pred_model, intercept, shrinkage, var_importance,
        oob_error_rate, classes). The reference forwards (m, type,
        models[], intercept, shrinkage, importance, oobErrorRate) per
        round (GradientTreeBoostingClassifierUDTF.java:525-546); the
        per-class models ARRAY column flattens to one relational row per
        class here. Deviations, both documented: oob_error_rate is None
        (the subsample OOB estimate is not tracked), and a `classes` JSON
        column carries the label vocabulary — the reference needs none
        because it REQUIRES labels to be 0..K-1 indices
        (GradientTreeBoostingClassifierUDTF.java:301-303 rejects negative
        labels); this trainer accepts arbitrary labels, so predictions
        from rows must map score indices back through `classes`.
        Exported programs evaluate on RAW feature vectors (bins
        embedded), so SQL scoring is
        intercept + shrinkage * SUM(tree_predict(...)) over rounds."""
        import json as _json

        cls_vocab = _json.dumps([c.item() if hasattr(c, "item") else c
                                 for c in self.classes])
        rows = []
        for m, round_trees in enumerate(self.trees, start=1):
            for cls, tree in enumerate(round_trees):
                mtype, text = _export(tree, self.bins, output)
                imp = _var_importance(tree, len(self.bins)).tolist()
                rows.append((m, cls, mtype, text,
                             float(self.intercept[cls]),
                             float(self.shrinkage), imp, None, cls_vocab))
        return rows


def train_gradient_tree_boosting_classifier(X, labels, options: Optional[str] = None,
                                            row_shard=None) -> TrainedGBT:
    """Binary: logistic loss on y in {-1,1}, pseudo-response 2y/(1+e^{2yF}),
    shrinkage eta, row subsampling (ref: GradientTreeBoostingClassifierUDTF.java:70-658).
    Multiclass: softmax with K trees per round.

    `row_shard=(mesh, axis)`: every boosting round's histogram build runs
    over device-sharded rows with one psum per level (grow.py
    _sharded_hist_fn) — GBT scales with devices where the reference's
    per-tree thread pool cannot help its sequential rounds
    (parallel/forest_shard.train_gbt_data_parallel is the public wrapper)."""
    cl = _forest_options(gbt=True).parse(options, "train_gradient_tree_boosting_classifier")
    X = np.asarray(X, dtype=np.float64)
    y_raw = np.asarray(labels)
    classes, y_idx = np.unique(y_raw, return_inverse=True)
    K = len(classes)
    N, F = X.shape
    attrs = _resolve_attrs(cl.get("attrs"), F)
    bins = make_bins(X, attrs)
    Xb = bin_data(X, bins)
    n_bins = max(b.n_bins for b in bins)
    seed = cl.get_int("seed", -1)
    rng = np.random.RandomState(seed if seed >= 0 else None)
    eta = cl.get_float("eta", 0.05)
    subsample = cl.get_float("subsample", 0.7)
    n_trees = cl.get_int("iters") or cl.get_int("trees", 500)
    depth = cl.get_int("depth", 8)
    min_split = cl.get_int("splits", 5)
    nominal_mask = np.array([a == "C" for a in attrs])
    num_vars = _num_vars(cl.get_float("vars") if cl.has("vars") else None, F)

    def fit_residual_tree(residual, mask):
        w = mask.astype(np.float32)
        return grow_tree(Xb, residual.astype(np.float32), w, nominal_mask, n_bins,
                         classification=False, max_depth=depth, min_split=min_split,
                         min_leaf=cl.get_int("min_samples_leaf", 1),
                         max_leaf_nodes=cl.get_int("leafs", 512),
                         num_vars=num_vars, rng=rng, row_shard=row_shard)

    rounds: List[List[TreeArrays]] = []
    if K == 2:
        yb = np.where(y_idx == 1, 1.0, -1.0)
        p1 = max(1e-6, min(1 - 1e-6, float(np.mean(y_idx == 1))))
        f0 = 0.5 * math.log(p1 / (1 - p1)) * 2.0  # smile's 2-scaled logit init
        intercept = np.array([f0])
        Fx = np.full(N, f0)
        for _ in range(n_trees):
            response = 2.0 * yb / (1.0 + np.exp(2.0 * yb * Fx))
            mask = rng.rand(N) < subsample
            tree = fit_residual_tree(response, mask)
            leaf = predict_binned(tree, Xb)
            Fx = Fx + eta * tree.leaf_value[leaf]
            rounds.append([tree])
        return TrainedGBT(rounds, intercept, eta, classes, bins)

    # multiclass softmax: the K class-trees of a round share the subsample
    # mask but fit different residuals — grown as ONE batched forest pass
    # via grow_forest's per-tree targets
    intercept = np.zeros(K)
    Fx = np.zeros((N, K))
    Y = np.eye(K)[y_idx]
    for _ in range(n_trees):
        e = np.exp(Fx - Fx.max(axis=1, keepdims=True))
        P = e / e.sum(axis=1, keepdims=True)
        mask = rng.rand(N) < subsample
        responses = (Y - P).T.astype(np.float32)  # [K, N]
        Wk = np.tile(mask.astype(np.float32), (K, 1))
        round_rngs = [np.random.RandomState(rng.randint(0, 2 ** 31))
                      for _ in range(K)]
        round_trees = grow_forest(
            Xb, responses, Wk, nominal_mask, n_bins,
            classification=False, max_depth=depth, min_split=min_split,
            min_leaf=cl.get_int("min_samples_leaf", 1),
            max_leaf_nodes=cl.get_int("leafs", 512),
            num_vars=num_vars, rngs=round_rngs, row_shard=row_shard)
        leaf_vals = np.asarray(
            predict_forest_binned(stack_trees(round_trees), Xb))  # [K, N]
        Fx += eta * leaf_vals.T
        rounds.append(round_trees)
    return TrainedGBT(rounds, intercept, eta, classes, bins)
