"""`tree_predict` + `guess_attrs` (ref: smile/tools/TreePredictUDF.java:143-326,
smile/tools/GuessAttributesUDF.java)."""

from __future__ import annotations

import json
from typing import List, Sequence, Union

import numpy as np

import re

from .export import eval_json_tree
from .vm import StackMachine

_JS_TOKEN = re.compile(
    r"\s*(if|else|x\[(\d+)\]|<=|==|[(){};]|-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)")


def compile_js_tree(source: str):
    """Compile the javascript tree export (to_javascript's nested
    `if (x[F] <=|== V) { ... } else { ... }` with numeric-literal leaf
    statements) into a features -> float evaluator — the reference's third
    evaluator, which feeds the same source to Rhino
    (ref: smile/tools/TreePredictUDF.java:326). The emitted grammar is a
    closed expression subset, so a recursive-descent parser replaces the JS
    engine off-JVM; anything outside the grammar is a loud ValueError."""
    tokens: List = []
    pos = 0
    while pos < len(source):
        m = _JS_TOKEN.match(source, pos)
        if not m:
            if source[pos:].strip() == "":
                break
            raise ValueError(
                f"javascript tree: unexpected input at {pos}: {source[pos:pos+20]!r}")
        tokens.append(m.group(1) if m.group(2) is None else ("x", int(m.group(2))))
        pos = m.end()

    idx = [0]

    def peek():
        return tokens[idx[0]] if idx[0] < len(tokens) else None

    def eat(want=None):
        t = peek()
        if t is None or (want is not None and t != want):
            raise ValueError(f"javascript tree: expected {want!r}, got {t!r}")
        idx[0] += 1
        return t

    def eat_number():
        t = eat()
        try:
            return float(t)
        except (TypeError, ValueError):
            raise ValueError(f"javascript tree: expected a number, got {t!r}")

    def parse_stmt():
        t = peek()
        if t == "if":
            eat("if")
            eat("(")
            feat = eat()
            if not (isinstance(feat, tuple) and feat[0] == "x"):
                raise ValueError(f"javascript tree: expected x[i], got {feat!r}")
            op = eat()
            if op not in ("<=", "=="):
                raise ValueError(f"javascript tree: bad comparator {op!r}")
            thresh = eat_number()
            eat(")")
            eat("{")
            left = parse_stmt()
            eat("}")
            eat("else")
            eat("{")
            right = parse_stmt()
            eat("}")
            f = feat[1]
            if op == "<=":
                return lambda x: left(x) if x[f] <= thresh else right(x)
            return lambda x: left(x) if x[f] == thresh else right(x)
        # leaf: numeric literal followed by ';'
        val = eat_number()
        eat(";")
        return lambda x: val

    fn = parse_stmt()
    if peek() is not None:
        raise ValueError(f"javascript tree: trailing tokens {tokens[idx[0]:][:5]}")
    return fn


def compile_tree(model_type: str, model: str):
    """Parse/compile one exported tree program ONCE; returns a
    features -> float evaluator. The single model-type dispatch table —
    tree_predict and the merged-row ensemble both go through it."""
    mt = model_type.lower()
    if mt in ("opscode", "vm"):
        sm = StackMachine()
        sm.compile(model)

        def run_vm(features):
            result = sm.eval(features)
            if result is None:
                raise ValueError("opscode evaluation returned no result")
            return result

        return run_vm
    if mt in ("json", "serialization", "ser"):
        node = json.loads(model) if isinstance(model, str) else model
        return lambda features: eval_json_tree(node, list(features))
    if mt in ("javascript", "js"):
        return compile_js_tree(model)
    raise ValueError(f"unsupported model type: {model_type}")


def tree_predict(model_type: str, model: str, features: Sequence[float],
                 classification: bool = False) -> Union[int, float]:
    """Evaluate an exported tree on one raw feature vector. Evaluators:
    opscode -> StackMachine (ref: TreePredictUDF.java:257), json -> node-graph
    walk (the serialization-evaluator analog, :205), javascript -> the
    expression-subset compiler compile_js_tree (the Rhino-evaluator analog,
    :326). `classification` defaults false like the reference
    (TreePredictUDF.java:104), so regression forests scored via the 3-arg
    form keep float leaf values instead of silently int-truncating."""
    out = compile_tree(model_type, model)(features)
    return int(out) if classification else float(out)


def guess_attrs(row: Sequence) -> str:
    """Guess Q/C attribute types from a sample row — strings/bools are
    categorical, numbers quantitative (ref: GuessAttributesUDF.java)."""
    attrs: List[str] = []
    for v in row:
        if isinstance(v, bool) or isinstance(v, str):
            attrs.append("C")
        elif isinstance(v, (int, np.integer)):
            # integers could be either; the reference guesses from the Hive
            # column type — int columns are quantitative
            attrs.append("Q")
        else:
            attrs.append("Q")
    return ",".join(attrs)
