"""`tree_predict` + `guess_attrs` (ref: smile/tools/TreePredictUDF.java:143-326,
smile/tools/GuessAttributesUDF.java)."""

from __future__ import annotations

import json
from typing import List, Sequence, Union

import numpy as np

from .export import eval_json_tree
from .vm import StackMachine


def compile_tree(model_type: str, model: str):
    """Parse/compile one exported tree program ONCE; returns a
    features -> float evaluator. The single model-type dispatch table —
    tree_predict and the merged-row ensemble both go through it."""
    mt = model_type.lower()
    if mt in ("opscode", "vm"):
        sm = StackMachine()
        sm.compile(model)

        def run_vm(features):
            result = sm.eval(features)
            if result is None:
                raise ValueError("opscode evaluation returned no result")
            return result

        return run_vm
    if mt in ("json", "serialization", "ser"):
        node = json.loads(model) if isinstance(model, str) else model
        return lambda features: eval_json_tree(node, list(features))
    raise ValueError(f"unsupported model type: {model_type}")


def tree_predict(model_type: str, model: str, features: Sequence[float],
                 classification: bool = True) -> Union[int, float]:
    """Evaluate an exported tree on one raw feature vector. Evaluators:
    opscode -> StackMachine (ref: TreePredictUDF.java:257), json -> node-graph
    walk (the serialization-evaluator analog, :205), javascript unsupported
    off-JVM (Rhino, :326) — export json/opscode instead."""
    out = compile_tree(model_type, model)(features)
    return int(out) if classification else float(out)


def guess_attrs(row: Sequence) -> str:
    """Guess Q/C attribute types from a sample row — strings/bools are
    categorical, numbers quantitative (ref: GuessAttributesUDF.java)."""
    attrs: List[str] = []
    for v in row:
        if isinstance(v, bool) or isinstance(v, str):
            attrs.append("C")
        elif isinstance(v, (int, np.integer)):
            # integers could be either; the reference guesses from the Hive
            # column type — int columns are quantitative
            attrs.append("Q")
        else:
            attrs.append("Q")
    return ",".join(attrs)
