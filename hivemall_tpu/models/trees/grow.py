"""Histogram-based decision-tree growth (classification + regression).

One tree level = ONE jitted scatter-add building per-(node, feature, bin)
histograms + one jitted split-evaluation over the whole frontier — replacing
the reference's per-node sorted-column scan (DecisionTree.TrainNode.findBestSplit,
ref: smile/classification/DecisionTree.java:407+ and
smile/regression/RegressionTree.java:101+). Host code only walks the (tiny)
frontier bookkeeping; all O(N) work is on device.

Split criteria: GINI or ENTROPY for classification (the reference's -rule
option, RandomForestClassifierUDTF.java:130), variance reduction for
regression. Nominal features split by equality (bin == v), numeric by
threshold (bin <= v), mirroring the reference's NOMINAL/NUMERIC split types.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from ...runtime.jax_compat import shard_map

NEG = -1e30

# (mesh, axis_name) — histogram builds run over device-sharded rows with an
# explicit psum; see _sharded_hist_fn
RowShard = Tuple["jax.sharding.Mesh", str]


@dataclass
class TreeArrays:
    """Array-form tree; node 0 is the root. feature == -1 marks leaves."""

    feature: np.ndarray  # [M] int32
    threshold_bin: np.ndarray  # [M] int32 (bin id)
    nominal: np.ndarray  # [M] bool
    left: np.ndarray  # [M] int32
    right: np.ndarray  # [M] int32
    leaf_dist: Optional[np.ndarray]  # [M, C] classification posteriors
    leaf_value: np.ndarray  # [M] regression output / argmax class
    n_nodes: int
    # accumulated impurity gain per feature (the reference's variable
    # importance, RandomForestClassifierUDTF importance accumulation)
    importance: Optional[np.ndarray] = None

    @property
    def max_depth_used(self) -> int:
        # depth via BFS
        depth = {0: 0}
        best = 0
        for i in range(self.n_nodes):
            d = depth.get(i, 0)
            best = max(best, d)
            if self.feature[i] >= 0:
                depth[int(self.left[i])] = d + 1
                depth[int(self.right[i])] = d + 1
        return best


@partial(jax.jit, static_argnums=(4, 5, 6))
def _hist_classification(Xb, y, w, assign, S: int, B: int, C: int):
    """[S, F, B, C] weighted class histograms for the current frontier."""
    N, F = Xb.shape
    fidx = jnp.arange(F)[None, :]  # [1, F]
    slot = assign[:, None]  # [N, 1]
    flat = ((slot * F + fidx) * B + Xb) * C + y[:, None]
    flat = jnp.where(slot >= 0, flat, S * F * B * C)  # drop settled rows
    hist = jnp.zeros((S * F * B * C,), jnp.float32).at[flat].add(
        jnp.broadcast_to(w[:, None], (N, F)), mode="drop")
    return hist.reshape(S, F, B, C)


@partial(jax.jit, static_argnums=(3, 4))
def _hist_regression(Xb, y, w, S: int, B: int, assign=None):
    """[S, F, B, 3] (count, sum, sumsq) histograms."""
    N, F = Xb.shape
    fidx = jnp.arange(F)[None, :]
    slot = assign[:, None]
    flat = (slot * F + fidx) * B + Xb
    flat = jnp.where(slot >= 0, flat, S * F * B)
    size = S * F * B
    wN = jnp.broadcast_to(w[:, None], (N, F))
    cnt = jnp.zeros((size,), jnp.float32).at[flat].add(wN, mode="drop")
    s = jnp.zeros((size,), jnp.float32).at[flat].add(wN * y[:, None], mode="drop")
    s2 = jnp.zeros((size,), jnp.float32).at[flat].add(wN * (y * y)[:, None], mode="drop")
    return jnp.stack([cnt, s, s2], axis=-1).reshape(S, F, B, 3)


def _impurity(counts, rule: str):
    """counts [..., C] -> impurity * n (so parent/child weighting is additive)."""
    n = jnp.sum(counts, -1)
    p = counts / jnp.maximum(n, 1e-12)[..., None]
    if rule == "entropy":
        ent = -jnp.sum(jnp.where(p > 0, p * jnp.log2(jnp.maximum(p, 1e-12)), 0.0), -1)
        return ent * n
    gini = 1.0 - jnp.sum(p * p, -1)
    return gini * n


@partial(jax.jit, static_argnums=(3,))
def _best_split_classification(hist, nominal_mask, feat_ok, rule: str,
                               min_leaf: float = 1.0):
    """hist [S,F,B,C]; nominal_mask [F] bool; feat_ok [S,F] per-node random
    subspace. Returns per slot: gain, feature, bin, node class counts [C]."""
    S, F, B, C = hist.shape
    total = jnp.sum(hist, axis=2)  # [S, F, C] (same per F)
    node_counts = total[:, 0, :]  # [S, C]
    parent_imp = _impurity(node_counts, rule)  # [S]

    cum = jnp.cumsum(hist, axis=2)  # [S,F,B,C] numeric left counts
    left_num = cum
    right_num = total[:, :, None, :] - cum
    left_nom = hist
    right_nom = total[:, :, None, :] - hist
    left = jnp.where(nominal_mask[None, :, None, None], left_nom, left_num)
    right = jnp.where(nominal_mask[None, :, None, None], right_nom, right_num)

    nl = jnp.sum(left, -1)
    nr = jnp.sum(right, -1)
    child_imp = _impurity(left, rule) + _impurity(right, rule)  # [S,F,B]
    gain = parent_imp[:, None, None] - child_imp

    valid = (nl >= min_leaf) & (nr >= min_leaf)
    # numeric cannot split on the last bin (empty right side by construction)
    last_bin = jnp.arange(B)[None, None, :] == (B - 1)
    valid &= ~(last_bin & ~nominal_mask[None, :, None])
    valid &= feat_ok[:, :, None]
    gain = jnp.where(valid, gain, NEG)

    flat = gain.reshape(S, F * B)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    return best_gain, best // B, best % B, node_counts


@jax.jit
def _best_split_regression(stats, nominal_mask, feat_ok, min_leaf: float = 1.0):
    """stats [S,F,B,3] -> variance-reduction split. Returns gain, f, b, and
    (count, mean) per slot."""
    S, F, B, _ = stats.shape
    total = jnp.sum(stats, axis=2)  # [S,F,3]
    node_stats = total[:, 0, :]  # [S,3]

    def sse(st):
        cnt, s, s2 = st[..., 0], st[..., 1], st[..., 2]
        return s2 - jnp.where(cnt > 0, s * s / jnp.maximum(cnt, 1e-12), 0.0)

    parent = sse(node_stats)
    cum = jnp.cumsum(stats, axis=2)
    left = jnp.where(nominal_mask[None, :, None, None], stats, cum)
    right = total[:, :, None, :] - left
    gain = parent[:, None, None] - (sse(left) + sse(right))
    valid = (left[..., 0] >= min_leaf) & (right[..., 0] >= min_leaf)
    last_bin = jnp.arange(B)[None, None, :] == (B - 1)
    valid &= ~(last_bin & ~nominal_mask[None, :, None])
    valid &= feat_ok[:, :, None]
    gain = jnp.where(valid, gain, NEG)
    flat = gain.reshape(S, F * B)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    mean = node_stats[:, 1] / jnp.maximum(node_stats[:, 0], 1e-12)
    return best_gain, best // B, best % B, node_stats[:, 0], mean


@functools.lru_cache(maxsize=None)
def _sharded_hist_fn(kind: str, mesh, axis: str, S: int, B: int, C: int):
    """Data-parallel histogram build: rows shard across `axis`, each device
    scatter-adds its partial (node, feature, bin) histogram, ONE psum
    reduces them — the cross-device analog of the reference's single-JVM
    per-node column scans (DecisionTree.TrainNode.findBestSplit), and the
    collective VERDICT r3 weak #6 called 'one collective away'. The split
    search then runs on the replicated global histogram, so growth
    decisions are identical to the single-device path up to float
    reduction order."""
    from jax.sharding import PartitionSpec as P

    if kind == "cls":
        def body(xb, yy, ww, aa):
            return jax.lax.psum(
                _hist_classification(xb, yy, ww, aa, S, B, C), axis)
        in_specs = (P(axis, None), P(axis), P(axis), P(axis))
    elif kind == "reg":
        def body(xb, yy, ww, aa):
            return jax.lax.psum(_hist_regression(xb, yy, ww, S, B, aa), axis)
        in_specs = (P(axis, None), P(axis), P(axis), P(axis))
    elif kind == "cls_forest":
        def body(xb, yy, ww, aa):
            return jax.lax.psum(
                _hist_classification_forest(xb, yy, ww, aa, S, B, C), axis)
        in_specs = (P(axis, None), P(axis), P(None, axis), P(None, axis))
    elif kind == "reg_forest":
        def body(xb, yy, ww, aa):
            return jax.lax.psum(
                _hist_regression_forest(xb, yy, ww, aa, S, B), axis)
        in_specs = (P(axis, None), P(None, axis), P(None, axis),
                    P(None, axis))
    else:
        raise ValueError(f"unknown sharded-hist kind {kind!r}")
    return jax.jit(shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=P()))


def _pad_rows(arrs, Xb, n_dev: int):
    """Pad the row axis up to a multiple of the mesh size so shard_map can
    split it evenly. Rows is Xb's axis 0 and each extra array's LAST axis
    ([N] vectors or [T, N] per-tree stacks). Padded rows carry weight 0 AND
    assign -1 (set by the caller), so they contribute nothing to any
    histogram and never route anywhere."""
    N = Xb.shape[0]
    pad = (-N) % n_dev
    if pad == 0:
        return arrs, Xb, N
    Xb = np.pad(np.asarray(Xb), ((0, pad), (0, 0)))
    padded = [np.pad(np.asarray(a),
                     [(0, 0)] * (np.asarray(a).ndim - 1) + [(0, pad)])
              for a in arrs]
    return padded, Xb, N + pad


def _route(Xb, assign, feat, thr, nominal, leftslot, rightslot, isleaf):
    """Route rows to next-level slots (-1 = settled in a leaf)."""
    slot = jnp.maximum(assign, 0)
    f = feat[slot]
    t = thr[slot]
    b = jnp.take_along_axis(Xb, f[:, None], axis=1)[:, 0]
    go_left = jnp.where(nominal[slot], b == t, b <= t)
    nxt = jnp.where(go_left, leftslot[slot], rightslot[slot])
    nxt = jnp.where(isleaf[slot], -1, nxt)
    return jnp.where(assign < 0, -1, nxt)


_update_assign = jax.jit(_route)
# same routing for a whole group of trees: assign/feat/... gain a tree axis
_update_assign_forest = jax.jit(
    jax.vmap(_route, in_axes=(None, 0, 0, 0, 0, 0, 0, 0)))


@partial(jax.jit, static_argnums=(4, 5, 6))
def _hist_classification_forest(Xb, y, W, assign, S: int, B: int, C: int):
    """Class histograms for a GROUP of trees in one scatter.

    Xb [N,F] shared binned rows; W [G,N] per-tree bootstrap weights;
    assign [G,N] per-tree frontier slots. Returns [G*S, F, B, C] laid out so
    the single-tree split kernels apply unchanged over the flattened
    (tree, slot) axis."""
    N, F = Xb.shape
    G = W.shape[0]
    fidx = jnp.arange(F)[None, None, :]
    slot = assign[:, :, None]  # [G, N, 1]
    tid = jnp.arange(G)[:, None, None]
    flat = (((tid * S + slot) * F + fidx) * B + Xb[None, :, :]) * C + y[None, :, None]
    flat = jnp.where(slot >= 0, flat, G * S * F * B * C)
    hist = jnp.zeros((G * S * F * B * C,), jnp.float32).at[flat.reshape(-1)].add(
        jnp.broadcast_to(W[:, :, None], (G, N, F)).reshape(-1), mode="drop")
    return hist.reshape(G * S, F, B, C)


@partial(jax.jit, static_argnums=(4, 5))
def _hist_regression_forest(Xb, y, W, assign, S: int, B: int):
    """[G*S, F, B, 3] (count, sum, sumsq) histograms for a group of trees.
    y is [G, N] — per-tree targets (GBT grows K class-trees per round on
    different residuals; plain forests broadcast one target row)."""
    N, F = Xb.shape
    G = W.shape[0]
    fidx = jnp.arange(F)[None, None, :]
    slot = assign[:, :, None]
    tid = jnp.arange(G)[:, None, None]
    flat = ((tid * S + slot) * F + fidx) * B + Xb[None, :, :]
    flat = jnp.where(slot >= 0, flat, G * S * F * B).reshape(-1)
    size = G * S * F * B
    wN = jnp.broadcast_to(W[:, :, None], (G, N, F)).reshape(-1)
    yN = jnp.broadcast_to(y[:, :, None], (G, N, F)).reshape(-1)
    cnt = jnp.zeros((size,), jnp.float32).at[flat].add(wN, mode="drop")
    s = jnp.zeros((size,), jnp.float32).at[flat].add(wN * yN, mode="drop")
    s2 = jnp.zeros((size,), jnp.float32).at[flat].add(wN * yN * yN, mode="drop")
    return jnp.stack([cnt, s, s2], axis=-1).reshape(G * S, F, B, 3)


def grow_tree(
    Xb: np.ndarray,  # [N, F] int32 binned
    y: np.ndarray,  # [N] int (classification) or float (regression)
    w: np.ndarray,  # [N] float32 bootstrap weights
    nominal_mask: np.ndarray,  # [F] bool
    n_bins: int,
    *,
    classification: bool,
    n_classes: int = 0,
    rule: str = "gini",
    max_depth: int = 10,
    min_split: int = 2,
    min_leaf: int = 1,
    max_leaf_nodes: int = 512,
    num_vars: Optional[int] = None,
    rng: Optional[np.random.RandomState] = None,
    row_shard: Optional[RowShard] = None,
) -> TreeArrays:
    """Level-wise growth; per-node random feature subspace of size `num_vars`
    (the reference samples numVars candidates per node, DecisionTree.java).

    `row_shard=(mesh, axis)`: the histogram build runs over device-sharded
    rows with one psum per level (_sharded_hist_fn) — data parallelism the
    reference's single-JVM growth cannot express."""
    rng = rng or np.random.RandomState(0)
    n_real = np.shape(Xb)[0]
    if row_shard is not None:
        mesh_, axis_ = row_shard
        (y, w), Xb, _ = _pad_rows([np.asarray(y), np.asarray(w)],
                                  np.asarray(Xb), mesh_.shape[axis_])
    N, F = Xb.shape
    Xb = jnp.asarray(Xb, jnp.int32)
    yj = jnp.asarray(y, jnp.int32 if classification else jnp.float32)
    wj = jnp.asarray(w, jnp.float32)
    nomj = jnp.asarray(nominal_mask)

    # host node table
    feature: List[int] = []
    thr: List[int] = []
    nom: List[bool] = []
    left: List[int] = []
    right: List[int] = []
    dists: List[np.ndarray] = []
    values: List[float] = []
    importance = np.zeros(F)

    def new_node():
        feature.append(-1)
        thr.append(0)
        nom.append(False)
        left.append(-1)
        right.append(-1)
        dists.append(None)
        values.append(0.0)
        return len(feature) - 1

    root = new_node()
    frontier = [root]  # node ids for current slots
    # pad rows (row_shard divisibility) start settled at -1: they never
    # enter a histogram and never route anywhere
    assign = jnp.where(jnp.arange(N) < n_real, 0, -1).astype(jnp.int32)
    n_leaves = 1

    for depth in range(max_depth + 1):
        S = len(frontier)
        if S == 0:
            break
        # pad the frontier to the next power of two: bounds the set of
        # compiled histogram/split shapes to {1, 2, 4, ...} across all trees
        S_pad = 1
        while S_pad < S:
            S_pad <<= 1
        if num_vars is None or num_vars >= F:
            feat_ok = np.ones((S_pad, F), bool)
        else:
            feat_ok = np.zeros((S_pad, F), bool)
            for s in range(S):
                feat_ok[s, rng.choice(F, size=num_vars, replace=False)] = True
        feat_okj = jnp.asarray(feat_ok)

        # ONE batched device_get per level for the split decision arrays —
        # element-wise np.asarray reads here would sync the dispatch stream
        # once per array instead of once per level (graftcheck G002)
        if classification:
            if row_shard is not None:
                hist = _sharded_hist_fn("cls", mesh_, axis_, S_pad, n_bins,
                                        n_classes)(Xb, yj, wj, assign)
            else:
                hist = _hist_classification(Xb, yj, wj, assign, S_pad,
                                            n_bins, n_classes)
            gain, bf, bb, counts = jax.device_get(_best_split_classification(
                hist, nomj, feat_okj, rule, float(min_leaf)))
            node_sizes = counts.sum(-1)
        else:
            if row_shard is not None:
                stats = _sharded_hist_fn("reg", mesh_, axis_, S_pad,
                                         n_bins, 0)(Xb, yj, wj, assign)
            else:
                stats = _hist_regression(Xb, yj, wj, S_pad, n_bins, assign)
            gain, bf, bb, node_sizes, means = jax.device_get(
                _best_split_regression(stats, nomj, feat_okj,
                                       float(min_leaf)))

        # decide splits on host (tiny); build next frontier (padded slots stay
        # leaves so _update_assign keeps power-of-two shapes too)
        isleaf = np.ones(S_pad, bool)
        leftslot = np.full(S_pad, -1, np.int32)
        rightslot = np.full(S_pad, -1, np.int32)
        next_frontier: List[int] = []
        for s, nid in enumerate(frontier):
            if classification:
                dists[nid] = counts[s]
                values[nid] = float(np.argmax(counts[s]))
            else:
                values[nid] = float(means[s])
            can_split = (
                depth < max_depth
                and gain[s] > 1e-7
                and node_sizes[s] >= min_split
                and n_leaves < max_leaf_nodes
            )
            if not can_split:
                continue
            isleaf[s] = False
            feature[nid] = int(bf[s])
            thr[nid] = int(bb[s])
            nom[nid] = bool(nominal_mask[bf[s]])
            importance[feature[nid]] += float(gain[s])
            l, r = new_node(), new_node()
            left[nid], right[nid] = l, r
            leftslot[s] = len(next_frontier)
            next_frontier.append(l)
            rightslot[s] = len(next_frontier)
            next_frontier.append(r)
            n_leaves += 1  # one leaf became two

        if not next_frontier:
            break
        feat_arr = np.zeros(S_pad, np.int32)
        thr_arr = np.zeros(S_pad, np.int32)
        nom_arr = np.zeros(S_pad, bool)
        for s, nid in enumerate(frontier):
            feat_arr[s] = feature[nid] if feature[nid] >= 0 else 0
            thr_arr[s] = thr[nid]
            nom_arr[s] = nom[nid]
        assign = _update_assign(
            Xb, assign, jnp.asarray(feat_arr), jnp.asarray(thr_arr),
            jnp.asarray(nom_arr), jnp.asarray(leftslot), jnp.asarray(rightslot),
            jnp.asarray(isleaf))
        frontier = next_frontier

    M = len(feature)
    C = n_classes if classification else 0
    leaf_dist = None
    if classification:
        leaf_dist = np.zeros((M, C), np.float32)
        for i, d in enumerate(dists):
            if d is not None:
                leaf_dist[i] = d
    return TreeArrays(
        feature=np.asarray(feature, np.int32),
        threshold_bin=np.asarray(thr, np.int32),
        nominal=np.asarray(nom, bool),
        left=np.asarray(left, np.int32),
        right=np.asarray(right, np.int32),
        leaf_dist=leaf_dist,
        leaf_value=np.asarray(values, np.float32),
        n_nodes=M,
        importance=importance,
    )


class _TreeBuild:
    """Host-side bookkeeping for one tree growing inside a forest group."""

    __slots__ = ("feature", "thr", "nom", "left", "right", "dists", "values",
                 "importance", "frontier", "n_leaves", "rng")

    def __init__(self, rng, n_features: int):
        self.feature: List[int] = []
        self.thr: List[int] = []
        self.nom: List[bool] = []
        self.left: List[int] = []
        self.right: List[int] = []
        self.dists: List[Optional[np.ndarray]] = []
        self.values: List[float] = []
        self.importance = np.zeros(n_features)
        self.rng = rng
        self.frontier = [self.new_node()]
        self.n_leaves = 1

    def new_node(self) -> int:
        self.feature.append(-1)
        self.thr.append(0)
        self.nom.append(False)
        self.left.append(-1)
        self.right.append(-1)
        self.dists.append(None)
        self.values.append(0.0)
        return len(self.feature) - 1

    def finish(self, classification: bool, n_classes: int) -> TreeArrays:
        M = len(self.feature)
        leaf_dist = None
        if classification:
            leaf_dist = np.zeros((M, n_classes), np.float32)
            for i, d in enumerate(self.dists):
                if d is not None:
                    leaf_dist[i] = d
        return TreeArrays(
            feature=np.asarray(self.feature, np.int32),
            threshold_bin=np.asarray(self.thr, np.int32),
            nominal=np.asarray(self.nom, bool),
            left=np.asarray(self.left, np.int32),
            right=np.asarray(self.right, np.int32),
            leaf_dist=leaf_dist,
            leaf_value=np.asarray(self.values, np.float32),
            n_nodes=M,
            importance=self.importance,
        )


def grow_forest(
    Xb: np.ndarray,  # [N, F] int32 binned (shared by all trees)
    y: np.ndarray,  # [N] int (classification) or float (regression)
    W: np.ndarray,  # [T, N] float32 per-tree bootstrap weights
    nominal_mask: np.ndarray,
    n_bins: int,
    *,
    classification: bool,
    n_classes: int = 0,
    rule: str = "gini",
    max_depth: int = 10,
    min_split: int = 2,
    min_leaf: int = 1,
    max_leaf_nodes: int = 512,
    num_vars: Optional[int] = None,
    rngs: Optional[Sequence[np.random.RandomState]] = None,
    hist_budget_bytes: int = 1 << 26,
    row_shard: Optional[RowShard] = None,
    strategy: str = "auto",
) -> List[TreeArrays]:
    """Grow ALL trees of a forest.

    Two strategies, IDENTICAL results (each tree draws its per-node feature
    subspace from its OWN rng, so both reproduce `grow_tree(..., rng=r_t)`
    exactly — parity-tested):

    - "per_tree": loop `grow_tree` — the direct analog of the reference's
      one-TrainingTask-per-tree thread pool
      (ref: smile/utils/SmileTaskExecutor.java:63-78).
    - "batched": level-synchronous — per level, ONE scatter-add builds every
      tree's (node, feature, bin) histograms and one kernel scores every
      split. Groups of trees are chunked so the histogram stays under
      `hist_budget_bytes`; chunk shapes are padded to fixed sizes so the
      set of compiled kernels stays O(log max_frontier).
    - "auto" (default): per_tree unless `row_shard` is set. Measured on
      both platforms (scripts/bench_forest.py, PERF.md round 5): the
      batched padding waste exceeds its dispatch savings — batched runs
      0.62x the per-tree loop on relay-attached v5e and 0.35x on CPU — so
      the loop is the default wherever it is legal. Row-sharded growth
      keeps the batched kernels: its per-level psum'd histogram
      (_sharded_hist_fn) is the data-parallel path's whole point and
      amortizes across the forest.

    `row_shard=(mesh, axis)`: each level's histograms build from
    device-sharded rows and psum across the mesh (_sharded_hist_fn) —
    data-parallel growth for forests AND for GBT's sequential boosting
    rounds (VERDICT r3 weak #6)."""
    if strategy not in ("auto", "batched", "per_tree"):
        raise ValueError(f"unknown strategy {strategy!r}")
    if strategy == "auto":
        strategy = "batched" if row_shard is not None else "per_tree"
    y = np.asarray(y)
    # ONE copy of the default-rng policy for both strategies — the
    # IDENTICAL-results guarantee depends on it
    rngs = list(rngs) if rngs is not None else [
        np.random.RandomState(t) for t in range(W.shape[0])]
    if strategy == "per_tree":
        per_tree_targets = (not classification) and y.ndim == 2
        return [
            grow_tree(Xb, y[t] if per_tree_targets else y, W[t],
                      nominal_mask, n_bins, classification=classification,
                      n_classes=n_classes, rule=rule, max_depth=max_depth,
                      min_split=min_split, min_leaf=min_leaf,
                      max_leaf_nodes=max_leaf_nodes, num_vars=num_vars,
                      rng=rngs[t], row_shard=row_shard)
            for t in range(W.shape[0])]
    per_tree_y = (not classification) and y.ndim == 2
    n_real = np.shape(Xb)[0]
    if row_shard is not None:
        mesh_, axis_ = row_shard
        (y, W), Xb, _ = _pad_rows([y, W], np.asarray(Xb),
                                  mesh_.shape[axis_])
    N, F = Xb.shape
    T = W.shape[0]
    stat_w = n_classes if classification else 3
    Xbj = jnp.asarray(Xb, jnp.int32)
    yj = jnp.asarray(y, jnp.int32 if classification else jnp.float32)
    Wj = jnp.asarray(W, jnp.float32)
    nomj = jnp.asarray(nominal_mask)

    builds = [_TreeBuild(rngs[t], F) for t in range(T)]
    # pad rows (row_shard divisibility) start settled at -1 on every tree
    assign = jnp.broadcast_to(
        jnp.where(jnp.arange(N) < n_real, 0, -1).astype(jnp.int32),
        (T, N))

    for depth in range(max_depth + 1):
        # sort active trees by frontier size so chunks group similar shapes
        # and each chunk pads S only to ITS largest frontier
        act = sorted((t for t in range(T) if builds[t].frontier),
                     key=lambda t: -len(builds[t].frontier))
        if not act:
            break
        c0 = 0
        while c0 < len(act):
            S = len(builds[act[c0]].frontier)
            S_pad = 1
            while S_pad < S:
                S_pad <<= 1
            # chunk the tree axis so [G, S, F, B, C] fits the budget; G is a
            # power of two (plus drop-masking) so compiled shapes stay bounded
            per_tree = S_pad * F * n_bins * stat_w * 4
            G = max(1, min(64, len(act) - c0, hist_budget_bytes // max(per_tree, 1)))
            while G & (G - 1):
                G &= G - 1
            chunk = act[c0:c0 + G]
            c0 += G
            g = len(chunk)
            # dummy pad slots point PAST the tree axis so the write-back
            # scatter drops them (duplicate in-range indices would race)
            idx = np.full(G, T, np.int64)
            idx[:g] = chunk
            valid = np.zeros(G, bool)
            valid[:g] = True
            idxj = jnp.asarray(idx)
            validj = jnp.asarray(valid)
            W_c = jnp.where(validj[:, None], Wj[jnp.minimum(idxj, T - 1)], 0.0)
            a_c = jnp.where(validj[:, None], assign[jnp.minimum(idxj, T - 1)], -1)

            feat_ok = np.zeros((G * S_pad, F), bool)
            for ci, t in enumerate(chunk):
                b = builds[t]
                if num_vars is None or num_vars >= F:
                    feat_ok[ci * S_pad:ci * S_pad + len(b.frontier)] = True
                else:
                    for s in range(len(b.frontier)):
                        feat_ok[ci * S_pad + s,
                                b.rng.choice(F, size=num_vars, replace=False)] = True
            feat_okj = jnp.asarray(feat_ok)

            # ONE batched device_get per level-chunk (graftcheck G002), as
            # in grow_tree
            if classification:
                if row_shard is not None:
                    hist = _sharded_hist_fn(
                        "cls_forest", mesh_, axis_, S_pad, n_bins,
                        n_classes)(Xbj, yj, W_c, a_c)
                else:
                    hist = _hist_classification_forest(
                        Xbj, yj, W_c, a_c, S_pad, n_bins, n_classes)
                gain, bf, bb, counts = jax.device_get(
                    _best_split_classification(hist, nomj, feat_okj, rule,
                                               float(min_leaf)))
                node_sizes = counts.sum(-1)
            else:
                if per_tree_y:
                    y_c = jnp.where(validj[:, None], yj[jnp.minimum(idxj, T - 1)], 0.0)
                else:
                    y_c = jnp.broadcast_to(yj[None, :], (G, N))
                if row_shard is not None:
                    stats = _sharded_hist_fn(
                        "reg_forest", mesh_, axis_, S_pad, n_bins, 0)(
                        Xbj, y_c, W_c, a_c)
                else:
                    stats = _hist_regression_forest(Xbj, y_c, W_c, a_c,
                                                    S_pad, n_bins)
                gain, bf, bb, node_sizes, means = jax.device_get(
                    _best_split_regression(stats, nomj, feat_okj,
                                           float(min_leaf)))

            # host split decisions per tree (same policy as grow_tree)
            isleaf = np.ones((G, S_pad), bool)
            leftslot = np.full((G, S_pad), -1, np.int32)
            rightslot = np.full((G, S_pad), -1, np.int32)
            feat_arr = np.zeros((G, S_pad), np.int32)
            thr_arr = np.zeros((G, S_pad), np.int32)
            nom_arr = np.zeros((G, S_pad), bool)
            any_next = False
            for ci, t in enumerate(chunk):
                b = builds[t]
                frontier = b.frontier
                next_frontier: List[int] = []
                for s, nid in enumerate(frontier):
                    k = ci * S_pad + s
                    if classification:
                        b.dists[nid] = counts[k]
                        b.values[nid] = float(np.argmax(counts[k]))
                    else:
                        b.values[nid] = float(means[k])
                    can_split = (
                        depth < max_depth
                        and gain[k] > 1e-7
                        and node_sizes[k] >= min_split
                        and b.n_leaves < max_leaf_nodes
                    )
                    if not can_split:
                        continue
                    isleaf[ci, s] = False
                    b.feature[nid] = int(bf[k])
                    b.thr[nid] = int(bb[k])
                    b.nom[nid] = bool(nominal_mask[bf[k]])
                    b.importance[b.feature[nid]] += float(gain[k])
                    l, r = b.new_node(), b.new_node()
                    b.left[nid], b.right[nid] = l, r
                    leftslot[ci, s] = len(next_frontier)
                    next_frontier.append(l)
                    rightslot[ci, s] = len(next_frontier)
                    next_frontier.append(r)
                    b.n_leaves += 1
                    feat_arr[ci, s] = b.feature[nid]
                    thr_arr[ci, s] = b.thr[nid]
                    nom_arr[ci, s] = b.nom[nid]
                b.frontier = next_frontier
                any_next = any_next or bool(next_frontier)

            if any_next:
                routed = _update_assign_forest(
                    Xbj, a_c, jnp.asarray(feat_arr), jnp.asarray(thr_arr),
                    jnp.asarray(nom_arr), jnp.asarray(leftslot),
                    jnp.asarray(rightslot), jnp.asarray(isleaf))
                assign = assign.at[idxj].set(routed, mode="drop")

    return [b.finish(classification, n_classes) for b in builds]


def stack_trees(trees) -> dict:
    """Pad per-tree arrays to a common node count for vmapped prediction."""
    M = max(t.n_nodes for t in trees)

    def pad(a, fill):
        out = np.full((len(trees), M), fill, dtype=a[0].dtype)
        for i, x in enumerate(a):
            out[i, : len(x)] = x
        return out

    return {
        "feature": jnp.asarray(pad([t.feature for t in trees], -1)),
        "thr": jnp.asarray(pad([t.threshold_bin for t in trees], 0)),
        "nominal": jnp.asarray(pad([t.nominal for t in trees], False)),
        "left": jnp.asarray(pad([t.left for t in trees], -1)),
        "right": jnp.asarray(pad([t.right for t in trees], -1)),
        "value": jnp.asarray(pad([t.leaf_value for t in trees], 0.0)),
    }


@jax.jit
def predict_forest_binned(stacked: dict, Xb, max_depth: int = 64):
    """All trees x all rows in one vmapped walk -> leaf values [T, N]."""
    Xbj = jnp.asarray(Xb, jnp.int32)

    def one_tree(feature, thr, nominal, left, right, value):
        node = jnp.zeros((Xbj.shape[0],), jnp.int32)

        def body(_, node):
            f = feature[node]
            leaf = f < 0
            fz = jnp.maximum(f, 0)
            b = jnp.take_along_axis(Xbj, fz[:, None], axis=1)[:, 0]
            go_left = jnp.where(nominal[node], b == thr[node], b <= thr[node])
            nxt = jnp.where(go_left, left[node], right[node])
            return jnp.where(leaf, node, nxt)

        node = jax.lax.fori_loop(0, max_depth, body, node)
        return value[node]

    return jax.vmap(one_tree)(stacked["feature"], stacked["thr"], stacked["nominal"],
                              stacked["left"], stacked["right"], stacked["value"])


def predict_binned(tree: TreeArrays, Xb: np.ndarray, max_depth: int = 64) -> np.ndarray:
    """Vectorized tree walk on binned rows -> leaf node ids."""
    feature = jnp.asarray(tree.feature)
    thr = jnp.asarray(tree.threshold_bin)
    nominal = jnp.asarray(tree.nominal)
    left = jnp.asarray(tree.left)
    right = jnp.asarray(tree.right)
    Xbj = jnp.asarray(Xb, jnp.int32)

    @jax.jit
    def walk(Xb_):
        node = jnp.zeros((Xb_.shape[0],), jnp.int32)

        def body(_, node):
            f = feature[node]
            leaf = f < 0
            fz = jnp.maximum(f, 0)
            b = jnp.take_along_axis(Xb_, fz[:, None], axis=1)[:, 0]
            go_left = jnp.where(nominal[node], b == thr[node], b <= thr[node])
            nxt = jnp.where(go_left, left[node], right[node])
            return jnp.where(leaf, node, nxt)

        return jax.lax.fori_loop(0, max_depth, body, node)

    return np.asarray(walk(Xbj))
