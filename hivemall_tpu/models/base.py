"""Shared trainer driver for the linear-learner family.

Mirrors LearnerBaseUDTF + BinaryOnlineClassifierUDTF / RegressionBaseUDTF
(ref: core/.../hivemall/LearnerBaseUDTF.java:61-343,
BinaryOnlineClassifierUDTF.java:51-298, regression/RegressionBaseUDTF.java:58-295):
option parsing, model creation, the training loop, and model emission — with
rows staged into fixed-shape FeatureBlocks and the update rules executed as
jitted TPU kernels (core/engine.py).

Execution modes:
- default (`-mini_batch 1`): scan mode — per-row sequential semantics,
  reference-exact.
- `-mini_batch B` > 1: minibatch mode — the reference's accumulate-then-
  apply-average semantics, the TPU hot path.
- `-iters N` + `-cv_rate`: multi-epoch with convergence checking; the epoch
  replay that FM/MF do via NioStatefullSegment disk spills is simply re-running
  the staged blocks (host RAM / HBM resident).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from ..constants import DEFAULT_NUM_FEATURES
from ..core.batch import iter_blocks, pad_to_bucket, shuffle_rows
from ..core.engine import Rule, make_predict, make_train_step
from ..core.state import LinearState, init_linear_state, model_rows
from ..ops.convergence import ConversionState
from ..utils.feature import parse_features_batch
from ..utils.options import CommandLine, Options


def base_options() -> Options:
    """Options shared by all linear learners (ref: LearnerBaseUDTF.java:85-103)."""
    o = Options()
    o.add("dense", "densemodel", False, "Use dense model or not (always dense on TPU)")
    o.add("dims", "feature_dimensions", True,
          "The dimension of model [default: 2^24 hashed space]", default=None, type=int)
    o.add("disable_halffloat", None, False, "(accepted for parity; TPU uses fp32/bf16)")
    o.add("loadmodel", None, True,
          "Warm-start from a saved model-rows table (ref: LearnerBaseUDTF.java:215-333)")
    # MIX client options accepted for signature parity
    # (ref: LearnerBaseUDTF.java:92-103). In the TPU build, model mixing is a
    # collective inside the train step — use parallel.MixTrainer on a mesh
    # (and runtime.init_cluster for multi-host) instead of a server fleet.
    o.add("mix", "mix_servers", True, "(parity) MIX server list; see parallel.MixTrainer")
    o.add("mix_session", "mix_session_name", True, "(parity) MIX session name")
    o.add("mix_threshold", None, True, "(parity) MIX push threshold", type=int)
    o.add("mix_cancel", "enable_mix_canceling", False, "(parity) no-op under sync SPMD")
    o.add("ssl", None, False, "(parity) TLS handled by the deployment, not the library")
    o.add("mini_batch", "mini_batch_size", True,
          "Mini batch size [default: 1 = exact per-row scan]", default=1, type=int)
    o.add("iters", "iterations", True, "Number of epochs [default: 1]", default=1, type=int)
    o.add("disable_cv", "disable_cvtest", False, "Disable convergence check")
    o.add("cv_rate", "convergence_rate", True, "Convergence rate [default: 0.005]",
          default=0.005, type=float)
    # TPU-native extensions
    o.add("block_size", None, True, "Rows per staged device block [default: 4096]",
          default=4096, type=int)
    o.add("shuffle", None, False, "Shuffle rows between epochs")
    o.add("seed", None, True, "Shuffle seed", default=31, type=int)
    o.add("pallas", None, False,
          "Use the VMEM-resident Pallas backend for exact scan mode "
          "(models that fit on-chip; kernels/linear_scan.py)")
    o.add("native_scan", None, False,
          "Run exact scan epochs through the native C row loop — the "
          "host fast path for accelerator-less mappers (train_arow: any "
          "options; train_fm: -classification with a fixed -eta)")
    o.add("mxu_scatter", None, False,
          "Route -mini_batch table updates through the sorted-window MXU "
          "gather/scatter (ops/mxu_scatter.py) instead of XLA's scalar "
          "scatter engine — same semantics, f32 sums up to addition order")
    o.add("batch", "batch_backend", True,
          "Segment-sum batched backend: apply minibatches of B rows "
          "through one host-staged dedup plan (core/batch_update.py) — "
          "the CPU hot path; same mini-batch semantics as -mini_batch B "
          "(docs/execution_backends.md)", type=int)
    o.add("native_apply", None, False,
          "With -batch B: apply the staged dedup plans through one "
          "vectorized C++ pass per block (core/native_batch.py) instead "
          "of the XLA segment-sum step — same mini-batch semantics, "
          "host-resident f32 tables; falls back LOUDLY to the XLA batch "
          "path when the .so or the rule's native form is missing")
    return o


ArrayRows = Tuple[List[np.ndarray], List[np.ndarray]]
FeatureRows = Union[Sequence[Sequence[str]], ArrayRows]


def _stage_rows(features: FeatureRows, dims: int) -> ArrayRows:
    if isinstance(features, tuple) and len(features) == 2:
        idx_rows = [np.asarray(r, dtype=np.int64) % dims for r in features[0]]
        val_rows = [np.asarray(v, dtype=np.float32) for v in features[1]]
        return idx_rows, val_rows
    return parse_features_batch(features, dims)


@dataclass
class TrainedLinearModel:
    """A fitted model: holds device state + the jitted predictor."""

    state: LinearState
    rule: Rule
    dims: int
    block_width: int

    def predict(self, features: FeatureRows, return_variance: bool = False):
        """Batched scoring — the SQL join+sum inference path collapsed into one
        gather-dot kernel (ref: SURVEY.md §3.5; tools/math/SigmoidGenericUDF.java)."""
        idx_rows, val_rows = _stage_rows(features, self.dims)
        n = len(idx_rows)
        width = pad_to_bucket(max((len(r) for r in idx_rows), default=1))
        want_var = return_variance and self.rule.use_covariance
        predict = make_predict(use_covariance=want_var)
        # keep per-block outputs on device so dispatch stays async across
        # blocks; ONE batched transfer at the end (graftcheck G002)
        scores, variances = [], []
        for block in iter_blocks(idx_rows, val_rows, np.zeros(n), self.dims, 4096, width):
            out = predict(self.state, block.indices, block.values)
            if want_var:
                scores.append(out[0])
                variances.append(out[1])
            else:
                scores.append(out)
        if want_var:
            scores, variances = jax.device_get((scores, variances))
            return np.concatenate(scores)[:n], np.concatenate(variances)[:n]
        return np.concatenate(jax.device_get(scores))[:n]

    def model_rows(self, filter_zero: bool = False):
        return model_rows(self.state, filter_zero)


def _fit_native_scan(rule, hyper, cl, dims, idx_rows, val_rows, labels,
                     width, block_size, initial_weights, initial_covars
                     ) -> "TrainedLinearModel":
    """`-native_scan`: exact sequential AROW epochs through the C row loop
    (native/hivemall_native.cpp::hm_arow_reference_rowloop — the same code
    measured as the bench anchor, shipped as an execution backend). This is
    the host fast path for accelerator-less workers: a Hive TRANSFORM
    mapper training through the bridge runs at the reference JVM's
    theoretical-best speed with zero JAX dispatch. Semantics = engine scan
    mode (per-row sequential, AROWClassifierUDTF.java:99-150), parity-
    tested; epoch 'loss' for -iters convergence is the margin-violation
    count (the reference's own AROW loss() is the sign-error count — close
    but not identical, documented here)."""
    from .. import native

    if rule.name != "arow":
        raise ValueError(
            "-native_scan supports train_arow only (the C row loop "
            f"implements AROW's closed form); {rule.name} has no native "
            "path — drop the flag")
    # state arrays get one extra sentinel slot: block padding uses
    # index == dims with value 0, so pad lanes read/write the sentinel
    # and contribute nothing to real features
    st = {
        "w": np.zeros(dims + 1, np.float32),
        "cov": np.ones(dims + 1, np.float32),
        "clocks": np.zeros(dims + 1, np.int16),
        "deltas": np.zeros(dims + 1, np.int8),
    }
    if initial_weights is not None:
        st["w"][:dims] = np.asarray(initial_weights, np.float32)
    if initial_covars is not None:
        st["cov"][:dims] = np.asarray(initial_covars, np.float32)
    # zero-row probe: availability check that cannot touch the state
    # (AROW's updates happen to confine to the sentinel slot under a fake
    # row, but only by accident of x=0 scaling — don't rely on it)
    probe = native.arow_reference_rowloop(
        np.zeros((0, 1), np.int32), np.zeros((0, 1), np.float32),
        np.zeros(0, np.float32), dims + 1, r=hyper.get("r", 0.1), state=st,
        track_touched=True)
    if probe is None:
        raise RuntimeError("-native_scan requires the native library "
                           "(bash scripts/build_native.sh)")

    from ..runtime.metrics import REGISTRY

    iters = cl.get_int("iters", 1)
    n = len(idx_rows)
    conv = ConversionState(not cl.has("disable_cv"),
                           cl.get_float("cv_rate", 0.005))
    row_counter = REGISTRY.counter("hivemall", f"{rule.name}.examples")
    iter_counter = REGISTRY.counter("hivemall", f"{rule.name}.iterations")
    r = hyper.get("r", 0.1)
    for it in range(max(1, iters)):
        if cl.has("shuffle") and it > 0:
            idx_rows, val_rows, labels = shuffle_rows(
                idx_rows, val_rows, labels, cl.get_int("seed", 31) + it)
        epoch_violations = 0
        for block in iter_blocks(idx_rows, val_rows, labels, dims,
                                 block_size, width):
            epoch_violations += native.arow_reference_rowloop(
                block.indices, block.values, block.labels, dims + 1,
                r=r, state=st, track_touched=True)
            row_counter.increment(block.batch_size)
        iter_counter.increment()
        conv.incr_loss(float(epoch_violations))
        if iters > 1 and conv.is_converged(n):
            break

    import jax.numpy as jnp

    state = init_linear_state(dims, use_covariance=True,
                              initial_weights=st["w"][:dims],
                              initial_covars=st["cov"][:dims])
    # monotone C-loop touch flags OR the warm-start mask — exactly the
    # engine's semantics (init seeds touched from initial_weights != 0 and
    # the kernel only max-updates it); the wrap-prone clocks/deltas never
    # feed model emission
    touched = st["touch"][:dims] != 0
    if initial_weights is not None:
        touched |= np.asarray(initial_weights) != 0
    state = state.replace(
        touched=jnp.asarray(touched.astype(np.int8)),
        step=jnp.asarray(np.int32(n * (it + 1))))
    return TrainedLinearModel(state=state, rule=rule, dims=dims,
                              block_width=width)


def _fit_native_batch(rule, hyper, cl, dims, idx_rows, val_rows, labels,
                      width, block_size, batch_b, initial_weights,
                      initial_covars) -> "TrainedLinearModel":
    """`-batch B -native_apply`: the staged-plan batch backend executed by
    one native C++ pass per block (core/native_batch.py). Plans are built
    host-side exactly like the XLA batch path and REUSED across epochs
    (cleared when -shuffle re-deals the rows); tables stay host-resident
    f32 and collapse to a LinearState at the end."""
    from ..core.batch_update import stage_block_plans
    from ..core.native_batch import (init_native_tables,
                                     make_native_batch_step,
                                     native_tables_to_state)
    from ..ops.convergence import ConversionState
    from ..runtime.metrics import REGISTRY

    step = make_native_batch_step(rule, hyper)
    tables = init_native_tables(dims, rule.use_covariance,
                                initial_weights, initial_covars)
    iters = cl.get_int("iters", 1)
    n = len(idx_rows)
    conv = ConversionState(not cl.has("disable_cv"),
                           cl.get_float("cv_rate", 0.005))
    row_counter = REGISTRY.counter("hivemall", f"{rule.name}.examples")
    iter_counter = REGISTRY.counter("hivemall", f"{rule.name}.iterations")
    plan_cache: list = []
    for it in range(max(1, iters)):
        if cl.has("shuffle") and it > 0:
            idx_rows, val_rows, labels = shuffle_rows(
                idx_rows, val_rows, labels, cl.get_int("seed", 31) + it)
            plan_cache = []
        epoch_loss = 0.0
        for bi, block in enumerate(iter_blocks(idx_rows, val_rows, labels,
                                               dims, block_size, width)):
            if bi >= len(plan_cache):
                plan_cache.append(
                    stage_block_plans(block.indices, batch_b, dims))
            epoch_loss += step(tables, block.values, block.labels,
                               plan_cache[bi])
            row_counter.increment(block.batch_size)
        iter_counter.increment()
        conv.incr_loss(epoch_loss)
        if iters > 1 and conv.is_converged(n):
            break
    state = native_tables_to_state(tables, rule, n * (it + 1))
    return TrainedLinearModel(state=state, rule=rule, dims=dims,
                              block_width=width)


def fit_linear(
    rule: Rule,
    hyper: dict,
    cl: CommandLine,
    features: FeatureRows,
    labels: Sequence[float],
    label_map: Callable[[np.ndarray], np.ndarray] = None,
    initial_weights: Optional[np.ndarray] = None,
    initial_covars: Optional[np.ndarray] = None,
    default_dims: int = DEFAULT_NUM_FEATURES,
) -> TrainedLinearModel:
    """The generic fit loop used by every classifier/regressor `train_*`."""
    dims = cl.get_int("dims") or default_dims
    mini_batch = cl.get_int("mini_batch", 1)
    iters = cl.get_int("iters", 1)
    block_size = cl.get_int("block_size", 4096)
    labels = np.asarray(labels, dtype=np.float32)
    if label_map is not None:
        labels = label_map(labels)

    if cl.has("loadmodel") and initial_weights is None:
        from ..io.checkpoint import dense_from_rows, load_model_rows

        feats0, w0, c0 = load_model_rows(cl.get("loadmodel"))
        initial_weights, initial_covars = dense_from_rows(dims, feats0, w0, c0)

    idx_rows, val_rows = _stage_rows(features, dims)
    n = len(idx_rows)
    if n == 0:
        raise ValueError("no training rows")
    width = pad_to_bucket(max((len(r) for r in idx_rows), default=1))

    batch_b = cl.get_int("batch", 0) if cl.has("batch") else 0
    mode = "minibatch" if mini_batch > 1 else "scan"
    if cl.has("batch"):
        if batch_b < 1:
            raise ValueError(f"-batch must be >= 1: {batch_b}")
        if mini_batch > 1:
            raise ValueError("-batch IS the mini-batch backend; drop "
                             "-mini_batch (its size becomes -batch's B)")
        if cl.has("native_scan") or cl.has("pallas") \
                or cl.has("mxu_scatter"):
            raise ValueError("-batch does not compose with -native_scan/"
                             "-pallas/-mxu_scatter; pick one execution "
                             "backend (docs/execution_backends.md)")
        mode = "batch"
    if cl.has("native_apply") and mode != "batch":
        # -native_apply is a modifier of the batch backend, not a backend
        # of its own — and it never composes with the other execution
        # flags (the -mxu_scatter/-pallas/-native_scan combos land here
        # or in the -batch refusal above)
        raise ValueError("-native_apply rides the -batch backend; add "
                         "-batch B (docs/execution_backends.md)")
    if mode == "minibatch":
        block_size = mini_batch
    if mode == "batch":
        # a staged block must hold whole minibatches: round the block up
        # to a multiple of B (only the dataset's final partial block
        # stages a tail chunk)
        block_size = -(-max(block_size, batch_b) // batch_b) * batch_b
    if cl.has("native_scan"):
        if mode != "scan":
            raise ValueError("-native_scan is the exact per-row path; "
                             "drop -mini_batch or drop -native_scan")
        return _fit_native_scan(rule, hyper, cl, dims, idx_rows, val_rows,
                                labels, width, block_size,
                                initial_weights, initial_covars)
    if mode == "batch" and cl.has("native_apply"):
        from ..core.native_batch import native_batch_unsupported_reason

        f32_tables = not (dims > (1 << 24)
                          and not cl.has("disable_halffloat"))
        reason = native_batch_unsupported_reason(
            rule, table_dtype_is_f32=f32_tables)
        if reason is None:
            return _fit_native_batch(rule, hyper, cl, dims, idx_rows,
                                     val_rows, labels, width, block_size,
                                     batch_b, initial_weights,
                                     initial_covars)
        # loud fallback, never silent: the XLA batch path has identical
        # semantics, so training proceeds — but the operator asked for
        # the native pass and must learn why they didn't get it
        import warnings

        warnings.warn(f"-native_apply unavailable ({reason}); falling "
                      "back to the XLA batch backend", stacklevel=2)
    if mode == "batch":
        from ..core.batch_update import make_batch_train_step

        step = make_batch_train_step(rule, hyper, batch_size=batch_b)
    elif cl.has("pallas") and mode == "scan":
        from ..kernels.linear_scan import make_pallas_scan_step

        interpret = jax.devices()[0].platform != "tpu"
        step = make_pallas_scan_step(rule, hyper, interpret=interpret)
    else:
        backend = "mxu" if (cl.has("mxu_scatter") and mode == "minibatch") \
            else "xla"
        step = make_train_step(rule, hyper, mode=mode,
                               update_backend=backend)
    # SpaceEfficientDenseModel analog: above 2^24 dims the reference switches
    # to half-float storage unless -disable_halffloat
    # (ref: LearnerBaseUDTF.java:172-175); TPU-native that is bf16.
    import jax.numpy as jnp

    dtype = jnp.float32
    if dims > (1 << 24) and not cl.has("disable_halffloat"):
        dtype = jnp.bfloat16
    state = init_linear_state(
        dims,
        use_covariance=rule.use_covariance,
        slot_names=rule.slot_names,
        global_names=rule.global_names,
        dtype=dtype,
        initial_weights=initial_weights,
        initial_covars=initial_covars,
    )

    conv = ConversionState(not cl.has("disable_cv"), cl.get_float("cv_rate", 0.005))
    # progress counters, the Hadoop Reporter/Counter analog
    # (ref: UDTFWithOptions.java:59-88, FM iteration counter :529-543)
    from ..runtime.metrics import REGISTRY

    iter_counter = REGISTRY.counter("hivemall", f"{rule.name}.iterations")
    row_counter = REGISTRY.counter("hivemall", f"{rule.name}.examples")
    # -batch: plans are a pure function of each block's indices, so they
    # are staged on the host once and replayed every epoch (cleared when
    # -shuffle re-deals the rows)
    plan_cache: list = []
    for it in range(max(1, iters)):
        if cl.has("shuffle") and it > 0:
            idx_rows, val_rows, labels = shuffle_rows(
                idx_rows, val_rows, labels, cl.get_int("seed", 31) + it
            )
            plan_cache = []
        # losses stay on device through the epoch — a float() per block
        # would sync the dispatch stream every step; the convergence check
        # only needs the epoch total, fetched in ONE batched device_get at
        # the epoch boundary (graftcheck G002)
        epoch_losses = []
        for bi, block in enumerate(
                iter_blocks(idx_rows, val_rows, labels, dims, block_size,
                            width)):
            if mode == "batch":
                from ..core.batch_update import stage_block_plans

                if bi >= len(plan_cache):
                    # device_put once at staging: replayed epochs must not
                    # re-upload the plan arrays every block
                    plan_cache.append(jax.tree_util.tree_map(
                        jax.device_put,
                        stage_block_plans(block.indices, batch_b, dims)))
                state, loss = step(state, block.indices, block.values,
                                   block.labels, plan_cache[bi])
            else:
                state, loss = step(state, block.indices, block.values,
                                   block.labels)
            epoch_losses.append(loss)
            row_counter.increment(block.batch_size)
        iter_counter.increment()
        epoch_loss = float(np.sum(jax.device_get(epoch_losses)))
        conv.incr_loss(epoch_loss)
        if iters > 1 and conv.is_converged(n):
            break
    return TrainedLinearModel(state=state, rule=rule, dims=dims, block_width=width)


def binary_label_map(labels: np.ndarray) -> np.ndarray:
    """int labels -> {-1, +1} (ref: BinaryOnlineClassifierUDTF train: y = label > 0 ? 1 : -1)."""
    return np.where(labels > 0, 1.0, -1.0).astype(np.float32)
