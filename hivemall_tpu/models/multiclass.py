"""Multiclass online classifiers: train_multiclass_{perceptron, pa, pa1, pa2,
cw, arow, arowh, scw, scw2}.

The reference keeps a lazily-grown per-label model map
(`Map<Object, PredictionModel> label2model`,
ref: classifier/multiclass/MulticlassOnlineClassifierUDTF.java:70-110). TPU-first
this becomes ONE stacked weight tensor [num_labels, dims]: scoring every label
is a [L, K] gather + matvec instead of L hash lookups, and the correct/missed
row updates are two scatter-adds into the same tensor.

Semantics note: the reference computes the "max another" margin over labels
seen so far; we compute it over the full fixed label vocabulary (unseen rows
score 0 from zero weights) — identical once every label has occurred, which is
the steady state.

Update rules mirror (file:line cited in each rule):
- perceptron: misclassify -> +x to actual, -x to predicted
  (ref: MulticlassPerceptronUDTF.java:50-57)
- PA: loss = 1 - margin, eta = loss/(2|x|^2); PA1 clips at C; PA2
  eta = loss/(2|x|^2 + 1/2C) (ref: MulticlassPassiveAggressiveUDTF.java:51-123)
- CW: gamma from margin + variance(correct) + variance(missed), covariance
  1/(1/cov + 2*alpha*phi*x^2) on both rows
  (ref: MulticlassConfidenceWeightedUDTF.java:112-192)
- AROW: alpha = (1-m)*beta, beta = 1/(var + r); AROWh: alpha = (c-m)*beta when
  c-m > 0; covariance cov - beta*(cov*x)^2 on both rows
  (ref: MulticlassAROWClassifierUDTF.java:99-234)
- SCW1/SCW2: binary SCW closed forms with m := margin, var := var_correct +
  var_missed (ref: MulticlassSoftConfidenceWeightedUDTF.java)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..constants import DEFAULT_NUM_FEATURES
from ..core.batch import iter_blocks, pad_to_bucket
from ..utils.options import Options
from .base import FeatureRows, _stage_rows, base_options
from .classifier import _resolve_phi, _safe_div

NEG_INF = -3.0e38


@struct.dataclass
class MulticlassState:
    weights: jnp.ndarray  # [L, D]
    covars: Optional[jnp.ndarray]  # [L, D] init 1.0
    touched: jnp.ndarray  # [L, D] int8
    step: jnp.ndarray  # [] int32
    # optimizer aux, [L, D] per name — empty for every current rule (the
    # reference's multiclass learners are all closed-form alpha/beta with no
    # accumulator state). mc_mix.final_state merges these per
    # MCRule.slot_merge so a distributed collapse can never silently keep
    # replica 0's accumulators; a slotted rule would additionally need
    # init/update plumbing here and in make_mc_train_step.
    slots: Dict[str, jnp.ndarray] = struct.field(default_factory=dict)


@dataclass(frozen=True)
class MCRule:
    """alpha/beta from (margin m, variance, sq_norm); cov_kind selects the
    covariance update shape ('none' | 'arow' | 'cw')."""

    name: str
    compute: Callable  # (m, var, sq_norm, hyper) -> (alpha, beta, loss, updated)
    cov_kind: str = "none"
    # (slot_name, "sum"|"mean") merge kinds for distributed final_state —
    # same contract as core.engine.Rule.slot_merge; empty for every current
    # rule (no multiclass rule carries accumulator slots)
    slot_merge: Tuple[Tuple[str, str], ...] = ()

    @property
    def use_covariance(self) -> bool:
        return self.cov_kind != "none"


def _perceptron_compute(m, var, sq_norm, hyper):
    updated = m <= 0.0  # predicted (max other) >= correct
    return jnp.where(updated, 1.0, 0.0), jnp.zeros(()), jnp.where(updated, 1.0, 0.0), updated


def _pa_compute_factory(variant: str):
    def compute(m, var, sq_norm, hyper):
        loss = 1.0 - m
        if variant == "pa":
            eta = _safe_div(loss, 2.0 * sq_norm)
        elif variant == "pa1":
            eta = jnp.minimum(hyper["c"], _safe_div(loss, 2.0 * sq_norm))
        else:
            eta = loss / (2.0 * sq_norm + 0.5 / hyper["c"])
        updated = (loss > 0.0) & (sq_norm > 0.0)
        return jnp.where(updated, eta, 0.0), jnp.zeros(()), jnp.maximum(loss, 0.0), updated

    return compute


def _cw_compute(m, var, sq_norm, hyper):
    phi = hyper["phi"]
    b = 1.0 + 2.0 * phi * m
    disc = jnp.maximum(0.0, b * b - 8.0 * phi * (m - phi * var))
    gamma = _safe_div(-b + jnp.sqrt(disc), 4.0 * phi * var)
    updated = gamma > 0.0
    alpha = jnp.where(updated, gamma, 0.0)
    return alpha, alpha * phi, jnp.where(m <= 0.0, 1.0, 0.0), updated


def _arow_compute_factory(hinge: bool):
    def compute(m, var, sq_norm, hyper):
        beta = 1.0 / (var + hyper["r"])
        if hinge:
            loss = hyper["c"] - m
        else:
            loss = 1.0 - m
        updated = loss > 0.0
        alpha = jnp.where(updated, loss * beta, 0.0)
        beta = jnp.where(updated, beta, 0.0)
        return alpha, beta, jnp.maximum(loss, 0.0), updated

    return compute


def _scw_compute_factory(variant: int):
    def compute(m, var, sq_norm, hyper):
        phi, c = hyper["phi"], hyper["c"]
        loss = jnp.maximum(0.0, phi * jnp.sqrt(jnp.maximum(var, 0.0)) - m)
        sq_phi = phi * phi
        if variant == 1:
            psi = 1.0 + sq_phi / 2.0
            zeta = 1.0 + sq_phi
            numer = -m * psi + jnp.sqrt(
                jnp.maximum(0.0, m * m * sq_phi * sq_phi / 4.0 + var * sq_phi * zeta))
            alpha = _safe_div(numer, var * zeta)
            alpha = jnp.where(alpha <= 0.0, 0.0, jnp.maximum(c, alpha))  # mirrors ref max()
        else:
            n = var + c / 2.0
            vpp = var * sq_phi
            vppm = vpp * m
            term = vppm * m * var + 4.0 * n * var * (n + vpp)
            gamma = phi * jnp.sqrt(jnp.maximum(0.0, term))
            numer = -(2.0 * m * n + vppm) + gamma
            alpha = jnp.where(numer <= 0.0, 0.0, _safe_div(numer, 2.0 * (n * n + n * vpp)))
        beta_numer = alpha * phi
        vap = var * beta_numer
        u = -vap + jnp.sqrt(jnp.maximum(0.0, vap * vap + 4.0 * var))
        beta = _safe_div(beta_numer, u / 2.0 + vap)
        updated = (loss > 0.0) & (alpha != 0.0) & (beta != 0.0)
        return (jnp.where(updated, alpha, 0.0), jnp.where(updated, beta, 0.0),
                loss, updated)

    return compute


MC_PERCEPTRON = MCRule("mc_perceptron", _perceptron_compute)
MC_PA = MCRule("mc_pa", _pa_compute_factory("pa"))
MC_PA1 = MCRule("mc_pa1", _pa_compute_factory("pa1"))
MC_PA2 = MCRule("mc_pa2", _pa_compute_factory("pa2"))
MC_CW = MCRule("mc_cw", _cw_compute, cov_kind="cw")
MC_AROW = MCRule("mc_arow", _arow_compute_factory(False), cov_kind="arow")
MC_AROWH = MCRule("mc_arowh", _arow_compute_factory(True), cov_kind="arow")
MC_SCW1 = MCRule("mc_scw1", _scw_compute_factory(1), cov_kind="arow")
MC_SCW2 = MCRule("mc_scw2", _scw_compute_factory(2), cov_kind="arow")


def _take2(table, idx, fill):
    # [L, D] gathered at idx [K] -> [L, K]; OOB padding -> fill
    return jnp.take(table, idx, axis=1, mode="fill", fill_value=fill)


def _margin_from_scores(scores, variances, COV, label, val, use_cov):
    """Margin / missed label / variance / cov rows from (global) per-label
    scores — the ONE copy of the downstream selection logic shared by the
    local and feature-sharded gathers (so their semantics cannot drift)."""
    L = scores.shape[0]
    correct = scores[label]
    if L == 1:
        # No other label yet: the reference scores "max another" as 0 with a
        # null missed label and only updates the correct row
        # (ref: MulticlassOnlineClassifierUDTF.getMargin:211-229 null branch).
        missed = label
        m = correct
    else:
        others = scores.at[label].set(NEG_INF)
        missed = jnp.argmax(others)
        m = correct - others[missed]
    if use_cov:
        var = variances[label] + jnp.where(missed == label, 0.0,
                                           variances[missed])
        cov_a, cov_m = COV[label], COV[missed]
    else:
        var = jnp.zeros(())
        cov_a = cov_m = jnp.ones_like(val)
    return m, var, missed, cov_a, cov_m


def _row_quantities(weights, covars, idx, val, label, use_cov):
    W = _take2(weights, idx, 0.0)  # [L, K]
    scores = W @ val  # [L]
    COV = variances = None
    if use_cov:
        COV = _take2(covars, idx, 1.0)
        variances = COV @ (val * val)
    return _margin_from_scores(scores, variances, COV, label, val, use_cov)


def _cov_delta(kind, cov, val, alpha, beta):
    if kind == "arow":
        cv = cov * val
        return -beta * cv * cv
    # cw: new = cov / (1 + 2*beta_term*x^2*cov) with beta_term = alpha*phi
    denom = 1.0 + 2.0 * beta * val * val * cov
    return cov / denom - cov


def _row_quantities_sharded(weights, covars, idx, val, label, use_cov,
                            shard_axis, stripe):
    """Sharded twin of _row_quantities: tables are [L, D/S] stripes; the
    per-label score/variance partials psum over the stripe axis (one fused
    collective), everything downstream (margin, missed label, closed-form
    alpha/beta) is the same _margin_from_scores as the local path. Returns
    the translated lane indices + masked values for the scatters."""
    from ..core.striping import translate_to_stripe

    lidx, vmask = translate_to_stripe(idx, val, shard_axis, stripe)
    W = _take2(weights, lidx, 0.0)  # [L, K] owned lanes only
    COV = variances = None
    if use_cov:
        COV = _take2(covars, lidx, 1.0)
        scores, variances = jax.lax.psum(
            (W @ vmask, COV @ (vmask * vmask)), shard_axis)
    else:
        scores = jax.lax.psum(W @ vmask, shard_axis)
    m, var, missed, cov_a, cov_m = _margin_from_scores(
        scores, variances, COV, label, val, use_cov)
    return m, var, missed, cov_a, cov_m, lidx, vmask


def make_mc_train_step(rule: MCRule, hyper: dict, mode: str = "scan",
                       feature_shard: Optional[Tuple[str, int]] = None,
                       jit: bool = True):
    """`feature_shard=(axis_name, stripe)` runs the same step on [L, D/S]
    table stripes inside shard_map — the multiclass analog of the engine's
    feature-sharded training (an L-label covariance model at 2^24 dims is
    L x 2 tables that do not fit one chip)."""
    use_cov = rule.use_covariance

    if feature_shard is None:
        def row_q(weights, covars, idx, val, label):
            m, var, missed, cov_a, cov_m = _row_quantities(
                weights, covars, idx, val, label, use_cov)
            return m, var, missed, cov_a, cov_m, idx, val
    else:
        shard_axis, stripe = feature_shard

        def row_q(weights, covars, idx, val, label):
            return _row_quantities_sharded(weights, covars, idx, val, label,
                                           use_cov, shard_axis, stripe)

    def apply_row(state_arrays, idx, val, label, alpha, beta, updated, cov_a, cov_m, missed):
        weights, covars, touched = state_arrays
        upd = updated.astype(val.dtype)
        has_miss = jnp.where(missed == label, 0.0, 1.0)  # L==1 degenerate case
        dwa = upd * alpha * cov_a * val
        dwm = -upd * has_miss * alpha * cov_m * val
        weights = weights.at[label, idx].add(dwa, mode="drop")
        weights = weights.at[missed, idx].add(dwm, mode="drop")
        if use_cov:
            dca = upd * _cov_delta(rule.cov_kind, cov_a, val, alpha, beta)
            dcm = upd * has_miss * _cov_delta(rule.cov_kind, cov_m, val, alpha, beta)
            covars = covars.at[label, idx].add(dca, mode="drop")
            covars = covars.at[missed, idx].add(dcm, mode="drop")
        u8 = updated.astype(jnp.int8)
        miss8 = (updated & (missed != label)).astype(jnp.int8)
        touched = touched.at[label, idx].max(jnp.broadcast_to(u8, idx.shape), mode="drop")
        touched = touched.at[missed, idx].max(jnp.broadcast_to(miss8, idx.shape), mode="drop")
        return weights, covars, touched

    def scan_step(state: MulticlassState, indices, values, labels):
        def body(carry, row):
            weights, covars, touched, t = carry
            idx, val, label = row
            # sq_norm from the raw replicated values: a global row scalar
            sq_norm = jnp.sum(val * val)
            m, var, missed, cov_a, cov_m, sidx, eff_val = row_q(
                weights, covars, idx, val, label)
            alpha, beta, loss, updated = rule.compute(m, var, sq_norm, hyper)
            weights, covars, touched = apply_row((weights, covars, touched),
                                                 sidx, eff_val,
                                                 label, alpha, beta, updated, cov_a,
                                                 cov_m, missed)
            return (weights, covars, touched, t + 1), loss

        carry0 = (state.weights, state.covars, state.touched, state.step)
        (weights, covars, touched, step), losses = jax.lax.scan(
            body, carry0, (indices, values, labels))
        return state.replace(weights=weights, covars=covars, touched=touched,
                             step=step), jnp.sum(losses)

    def minibatch_step(state: MulticlassState, indices, values, labels):
        b = indices.shape[0]

        def per_row(idx, val, label):
            sq_norm = jnp.sum(val * val)
            m, var, missed, cov_a, cov_m, sidx, eff_val = row_q(
                state.weights, state.covars, idx, val, label)
            alpha, beta, loss, updated = rule.compute(m, var, sq_norm, hyper)
            return m, missed, cov_a, cov_m, alpha, beta, loss, updated, \
                sidx, eff_val

        (m, missed, cov_a, cov_m, alpha, beta, loss, updated, sidx,
         eff_val) = jax.vmap(per_row)(indices, values, labels)
        upd = updated.astype(values.dtype)[:, None]
        has_miss = jnp.where(missed == labels, 0.0, 1.0)[:, None]
        dwa = upd * alpha[:, None] * cov_a * eff_val
        dwm = -upd * has_miss * alpha[:, None] * cov_m * eff_val
        weights = state.weights.at[labels[:, None], sidx].add(dwa, mode="drop")
        weights = weights.at[missed[:, None], sidx].add(dwm, mode="drop")
        covars = state.covars
        if use_cov:
            dca = upd * jax.vmap(
                lambda c, v, a, be: _cov_delta(rule.cov_kind, c, v, a, be))(
                    cov_a, eff_val, alpha, beta)
            dcm = upd * has_miss * jax.vmap(
                lambda c, v, a, be: _cov_delta(rule.cov_kind, c, v, a, be))(
                    cov_m, eff_val, alpha, beta)
            covars = covars.at[labels[:, None], sidx].add(dca, mode="drop")
            covars = covars.at[missed[:, None], sidx].add(dcm, mode="drop")
        u8 = jnp.broadcast_to(updated.astype(jnp.int8)[:, None], sidx.shape)
        touched = state.touched.at[labels[:, None], sidx].max(u8, mode="drop")
        touched = touched.at[missed[:, None], sidx].max(u8, mode="drop")
        return state.replace(weights=weights, covars=covars, touched=touched,
                             step=state.step + b), jnp.sum(loss)

    step = scan_step if mode == "scan" else minibatch_step
    # jit=False returns the raw traceable fn for embedding in an outer scan
    # (e.g. a whole-epoch lax.scan over staged blocks, scripts/bench_mc.py)
    return jax.jit(step, donate_argnums=(0,)) if jit else step


@jax.jit
def _mc_scores(weights, indices, values):
    W = jnp.take(weights, indices, axis=1, mode="fill", fill_value=0.0)  # [L, B, K]
    return jnp.einsum("lbk,bk->bl", W, values)


@dataclass
class TrainedMulticlassModel:
    state: MulticlassState
    label_vocab: List
    dims: int

    def scores(self, features: FeatureRows) -> np.ndarray:
        idx_rows, val_rows = _stage_rows(features, self.dims)
        n = len(idx_rows)
        width = pad_to_bucket(max((len(r) for r in idx_rows), default=1))
        out = []
        for blk in iter_blocks(idx_rows, val_rows, np.zeros(n), self.dims, 1024, width):
            out.append(np.asarray(_mc_scores(self.state.weights, blk.indices, blk.values)))
        return np.concatenate(out)[:n]

    def predict(self, features: FeatureRows) -> List:
        s = self.scores(features)
        return [self.label_vocab[i] for i in np.argmax(s, axis=1)]

    def model_rows(self):
        """(label, feature, weight[, covar]) rows over touched entries —
        the reference's per-label close() emission."""
        t = np.asarray(self.state.touched) != 0
        lab_i, feat_i = np.nonzero(t)
        labels = [self.label_vocab[i] for i in lab_i]
        weights = np.asarray(self.state.weights)[lab_i, feat_i]
        if self.state.covars is not None:
            return labels, feat_i, weights, np.asarray(self.state.covars)[lab_i, feat_i]
        return labels, feat_i, weights


def _fit_multiclass(rule: MCRule, hyper: dict, cl, features: FeatureRows,
                    labels: Sequence, num_classes: Optional[int] = None):
    dims = cl.get_int("dims") or DEFAULT_NUM_FEATURES
    mini_batch = cl.get_int("mini_batch", 1)
    iters = cl.get_int("iters", 1)
    vocab = sorted(set(labels), key=lambda x: str(x))
    if num_classes is not None and num_classes > len(vocab):
        vocab = vocab + [f"__unused_{i}" for i in range(num_classes - len(vocab))]
    lab2i = {l: i for i, l in enumerate(vocab)}
    y = np.array([lab2i[l] for l in labels], dtype=np.int32)
    idx_rows, val_rows = _stage_rows(features, dims)
    width = pad_to_bucket(max((len(r) for r in idx_rows), default=1))
    L = len(vocab)
    state = MulticlassState(
        weights=jnp.zeros((L, dims), dtype=jnp.float32),
        covars=jnp.ones((L, dims), dtype=jnp.float32) if rule.use_covariance else None,
        touched=jnp.zeros((L, dims), dtype=jnp.int8),
        step=jnp.zeros((), dtype=jnp.int32),
    )
    mode = "minibatch" if mini_batch > 1 else "scan"
    block = mini_batch if mode == "minibatch" else cl.get_int("block_size", 4096)
    step = make_mc_train_step(rule, hyper, mode)
    for _ in range(max(1, iters)):
        for blk in iter_blocks(idx_rows, val_rows, y, dims, block, width):
            state, _ = step(state, blk.indices, blk.values,
                            blk.labels.astype(np.int32))
    return TrainedMulticlassModel(state=state, label_vocab=vocab, dims=dims)


def _mc_opts(phi: bool = False, c: bool = False, r: bool = False) -> Options:
    o = base_options()
    if phi:
        o.add("phi", "confidence", True, "Confidence parameter [default 1.0]", type=float)
        o.add("eta", "hyper_c", True, "Confidence hyperparameter in (0.5, 1]", type=float)
    if c:
        o.add("c", "aggressiveness", True, "Aggressiveness parameter C [default 1.0]",
              default=1.0, type=float)
    if r:
        o.add("r", "regularization", True, "Regularization parameter r [default 0.1]",
              default=0.1, type=float)
    return o


def _make_train(name, rule, opts_kw, hyper_fn):
    def train(features: FeatureRows, labels, options: Optional[str] = None,
              num_classes: Optional[int] = None):
        cl = _mc_opts(**opts_kw).parse(options, name)
        return _fit_multiclass(rule, hyper_fn(cl), cl, features, labels, num_classes)

    train.__name__ = name
    return train


train_multiclass_perceptron = _make_train(
    "train_multiclass_perceptron", MC_PERCEPTRON, {}, lambda cl: {})
train_multiclass_pa = _make_train(
    "train_multiclass_pa", MC_PA, {}, lambda cl: {})
train_multiclass_pa1 = _make_train(
    "train_multiclass_pa1", MC_PA1, {"c": True}, lambda cl: {"c": cl.get_float("c", 1.0)})
train_multiclass_pa2 = _make_train(
    "train_multiclass_pa2", MC_PA2, {"c": True}, lambda cl: {"c": cl.get_float("c", 1.0)})
train_multiclass_cw = _make_train(
    "train_multiclass_cw", MC_CW, {"phi": True}, lambda cl: {"phi": _resolve_phi(cl)})
train_multiclass_arow = _make_train(
    "train_multiclass_arow", MC_AROW, {"r": True}, lambda cl: {"r": cl.get_float("r", 0.1)})
train_multiclass_arowh = _make_train(
    "train_multiclass_arowh", MC_AROWH, {"r": True, "c": True},
    lambda cl: {"r": cl.get_float("r", 0.1), "c": cl.get_float("c", 1.0)})
train_multiclass_scw = _make_train(
    "train_multiclass_scw", MC_SCW1, {"phi": True, "c": True},
    lambda cl: {"phi": _resolve_phi(cl), "c": cl.get_float("c", 1.0)})
train_multiclass_scw2 = _make_train(
    "train_multiclass_scw2", MC_SCW2, {"phi": True, "c": True},
    lambda cl: {"phi": _resolve_phi(cl), "c": cl.get_float("c", 1.0)})
