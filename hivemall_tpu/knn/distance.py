"""Distance UDFs (ref: knn/distance/*.java).

Scalar/sparse-string variants mirror the reference UDF surface; `*_batch`
variants are vectorized jnp kernels over dense [N, D] matrices (the TPU-shaped
path for bulk kNN: one matmul per distance matrix instead of per-pair loops).
"""

from __future__ import annotations

import math
from typing import Dict, List, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ..utils.feature import parse_feature

VecLike = Union[Sequence[str], Dict[Union[int, str], float]]


def _to_map(v: VecLike) -> Dict:
    if isinstance(v, dict):
        return v
    out = {}
    for fv in v:
        name, val = parse_feature(fv)
        out[name] = out.get(name, 0.0) + val
    return out


def popcnt(x: Union[int, Sequence[int]]) -> int:
    """popcnt(bigint|array<bigint>) (ref: knn/distance/PopcountUDF.java)."""
    if isinstance(x, (list, tuple, np.ndarray)):
        return int(sum(bin(int(v) & 0xFFFFFFFFFFFFFFFF).count("1") for v in x))
    return bin(int(x) & 0xFFFFFFFFFFFFFFFF).count("1")


def hamming_distance(a: Union[int, Sequence[int]], b: Union[int, Sequence[int]]) -> int:
    """popcnt(a xor b) (ref: knn/distance/HammingDistanceUDF.java)."""
    if isinstance(a, (list, tuple, np.ndarray)):
        return int(sum(popcnt(int(x) ^ int(y)) for x, y in zip(a, b)))
    return popcnt(int(a) ^ int(b))


def kld(mu1: float, sigma1: float, mu2: float, sigma2: float) -> float:
    """KL divergence between two 1-D gaussians (ref: knn/distance/KLDivergenceUDF.java)."""
    return float(0.5 * (math.log(sigma2 / sigma1) + (sigma1 + (mu1 - mu2) ** 2) / sigma2
                        - 1.0))


def euclid_distance(a: VecLike, b: VecLike) -> float:
    ma, mb = _to_map(a), _to_map(b)
    keys = set(ma) | set(mb)
    return float(math.sqrt(sum((ma.get(k, 0.0) - mb.get(k, 0.0)) ** 2 for k in keys)))


def manhattan_distance(a: VecLike, b: VecLike) -> float:
    ma, mb = _to_map(a), _to_map(b)
    keys = set(ma) | set(mb)
    return float(sum(abs(ma.get(k, 0.0) - mb.get(k, 0.0)) for k in keys))


def minkowski_distance(a: VecLike, b: VecLike, p: float) -> float:
    ma, mb = _to_map(a), _to_map(b)
    keys = set(ma) | set(mb)
    return float(sum(abs(ma.get(k, 0.0) - mb.get(k, 0.0)) ** p for k in keys) ** (1.0 / p))


def cosine_distance(a: VecLike, b: VecLike) -> float:
    """1 - cosine_similarity (ref: knn/distance/CosineDistanceUDF.java:40)."""
    from .similarity import cosine_similarity

    return 1.0 - cosine_similarity(a, b)


def angular_distance(a: VecLike, b: VecLike) -> float:
    """acos(cos_sim)/pi (ref: knn/distance/AngularDistanceUDF.java)."""
    from .similarity import cosine_similarity

    cos = min(1.0, max(-1.0, cosine_similarity(a, b)))
    return float(math.acos(cos) / math.pi)


def jaccard_distance(a: Union[int, Sequence], b: Union[int, Sequence],
                     k: int = 128) -> float:
    """1 - jaccard (ref: knn/distance/JaccardDistanceUDF.java: on b-bit minhash
    signatures, union approximated via k-bit blocks)."""
    from .similarity import jaccard_similarity

    return 1.0 - jaccard_similarity(a, b, k)


# ---- dense batched kernels (TPU path) ----

def euclid_distance_batch(A, B):
    """Pairwise distances for [N, D] x [M, D] via one matmul."""
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    sq = jnp.sum(A * A, 1)[:, None] + jnp.sum(B * B, 1)[None, :] - 2.0 * A @ B.T
    return jnp.sqrt(jnp.maximum(sq, 0.0))


def cosine_distance_batch(A, B):
    A = jnp.asarray(A)
    B = jnp.asarray(B)
    An = A / jnp.maximum(jnp.linalg.norm(A, axis=1, keepdims=True), 1e-12)
    Bn = B / jnp.maximum(jnp.linalg.norm(B, axis=1, keepdims=True), 1e-12)
    return 1.0 - An @ Bn.T
