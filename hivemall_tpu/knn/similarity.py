"""Similarity UDFs (ref: knn/similarity/*.java)."""

from __future__ import annotations

import math
from typing import Dict, Sequence, Union

import numpy as np


def _to_map(v):
    from .distance import _to_map as f

    return f(v)


def cosine_similarity(a, b) -> float:
    """(ref: knn/similarity/CosineSimilarityUDF.java:39)."""
    ma, mb = _to_map(a), _to_map(b)
    dot = sum(v * mb.get(k, 0.0) for k, v in ma.items())
    na = math.sqrt(sum(v * v for v in ma.values()))
    nb = math.sqrt(sum(v * v for v in mb.values()))
    if na == 0.0 or nb == 0.0:
        return 0.0
    return float(dot / (na * nb))


def angular_similarity(a, b) -> float:
    """1 - acos(cos)/pi (ref: knn/similarity/AngularSimilarityUDF.java:21)."""
    cos = min(1.0, max(-1.0, cosine_similarity(a, b)))
    return float(1.0 - math.acos(cos) / math.pi)


def euclid_similarity(a, b) -> float:
    """1/(1 + euclid_distance) (ref: knn/similarity/EuclidSimilarity.java:37)."""
    from .distance import euclid_distance

    return float(1.0 / (1.0 + euclid_distance(a, b)))


def jaccard_similarity(a, b, k: int = 128) -> float:
    """On b-bit minhash signatures: matching bits scaled to [-1, 1] then
    clipped (ref: knn/similarity/JaccardIndexUDF.java / bBitMinHash usage);
    on sets/feature lists: |A∩B| / |A∪B|."""
    if isinstance(a, (int, np.integer)) and isinstance(b, (int, np.integer)):
        matched = k - popcount_xor(int(a), int(b), k)
        sim = 2.0 * matched / k - 1.0
        return float(max(0.0, sim))
    sa = set(a if not isinstance(a, dict) else a.keys())
    sb = set(b if not isinstance(b, dict) else b.keys())
    union = len(sa | sb)
    if union == 0:
        return 0.0
    return float(len(sa & sb) / union)


def popcount_xor(a: int, b: int, k: int) -> int:
    mask = (1 << k) - 1
    return bin((a ^ b) & mask).count("1")


def distance2similarity(d: float) -> float:
    """1/(1 + d) (ref: knn/similarity/Distance2SimilarityUDF.java:36)."""
    return float(1.0 / (1.0 + d))
