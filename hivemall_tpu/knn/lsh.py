"""Locality-sensitive hashing (ref: knn/lsh/*.java)."""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple, Union

import numpy as np

from ..utils.feature import parse_feature
from ..utils.hashing import murmurhash3_x86_32

_MAX_INT = 2147483647


def _hash_funcs(num_hashes: int, seed: int = 0x9747B28C):
    """Family of murmur-based hash functions, one per minhash
    (ref: utils/hashing/HashFunctionFactory.java)."""
    seeds = []
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    for _ in range(num_hashes):
        seeds.append(int(rng.randint(0, _MAX_INT)))
    return seeds


def minhash(item, features: Sequence[str], num_hashes: int = 5,
            num_keygroups: int = 2) -> Iterator[Tuple[int, object]]:
    """`minhash(item, features)` UDTF — emit (clusterId, item) pairs, one per
    hash, where clusterId packs the num_keygroups smallest weighted hash
    values (ref: knn/lsh/MinHashUDTF.java:55-170; options -hashes 5 -keygroups 2)."""
    parsed = [parse_feature(f) for f in features]
    seeds = _hash_funcs(num_hashes)
    for s in seeds:
        hashes = []
        for name, w in parsed:
            h = abs(murmurhash3_x86_32(str(name), s))
            # weighted hash: larger weight -> smaller effective value
            hv = h / max(w, 1e-9) if w > 0 else float(h) * (1.0 - w + 1.0)
            hashes.append((hv, h))
        hashes.sort()
        k = min(num_keygroups, len(hashes))
        cluster = 0
        for _, h in hashes[:k]:
            cluster = (cluster * 31 + h) & 0x7FFFFFFF
        yield cluster, item


def minhashes(features: Sequence[str], num_hashes: int = 5,
              num_keygroups: int = 2) -> List[int]:
    """`minhashes(features)` UDF — the cluster ids as an array
    (ref: knn/lsh/MinHashesUDF.java)."""
    return [c for c, _ in minhash(None, features, num_hashes, num_keygroups)]


def bbit_minhash(features: Sequence[Union[str, int]], num_hashes: int = 128,
                 b: int = 1) -> int:
    """`bbit_minhash(features)` — pack the lowest b bits of each of k minhash
    values into one integer signature (ref: knn/lsh/bBitMinHashUDF.java:36)."""
    names = [str(parse_feature(str(f))[0]) for f in features]
    seeds = _hash_funcs(num_hashes)
    sig = 0
    mask = (1 << b) - 1
    for i, s in enumerate(seeds):
        mh = min((abs(murmurhash3_x86_32(n, s)) for n in names), default=0)
        sig |= (mh & mask) << (i * b)
    return sig
