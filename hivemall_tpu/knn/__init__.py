from .distance import (  # noqa: F401
    angular_distance,
    cosine_distance,
    euclid_distance,
    hamming_distance,
    jaccard_distance,
    kld,
    manhattan_distance,
    minkowski_distance,
    popcnt,
)
from .similarity import (  # noqa: F401
    angular_similarity,
    cosine_similarity,
    distance2similarity,
    euclid_similarity,
    jaccard_similarity,
)
from .lsh import bbit_minhash, minhash, minhashes  # noqa: F401
