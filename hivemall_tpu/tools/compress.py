"""Compression UDFs (ref: hivemall/tools/compress/{DeflateUDF,InflateUDF}.java,
utils/codec/DeflateCodec.java)."""

from __future__ import annotations

import zlib
from typing import Union


def deflate(data: Union[str, bytes], level: int = -1) -> bytes:
    """zlib-deflate; strings are UTF-8 encoded first."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    return zlib.compress(data, level)


def inflate(data: bytes, as_text: bool = True) -> Union[str, bytes]:
    out = zlib.decompress(data)
    return out.decode("utf-8") if as_text else out
