"""Mapred-context UDFs (ref: hivemall/tools/mapred/*.java).

These existed to expose Hadoop task context inside SQL. In the TPU runtime the
"task" is a jax process: taskid == jax.process_index(), jobid is a stable
per-run identifier, rowid mirrors the reference's sprintf("%s-%d", taskid, seq)
scheme (ref: tools/mapred/RowIdUDF.java).
"""

from __future__ import annotations

import itertools
import os
import uuid
from typing import Optional

_JOB_ID = None
_ROW_COUNTER = itertools.count()


def taskid() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def jobid() -> str:
    global _JOB_ID
    if _JOB_ID is None:
        _JOB_ID = os.environ.get("HIVEMALL_TPU_JOB_ID") or f"job_{uuid.uuid4().hex[:12]}"
    return _JOB_ID


def rowid() -> str:
    """Unique row id "taskid-seq" (ref: tools/mapred/RowIdUDF.java)."""
    return f"{taskid()}-{next(_ROW_COUNTER)}"


def jobconf_gets(key: Optional[str] = None, default: str = "") -> str:
    """JobConf lookup -> environment variables here
    (ref: tools/mapred/JobConfGetsUDF.java)."""
    if key is None:
        return " ".join(f"{k}={v}" for k, v in os.environ.items()
                        if k.startswith("HIVEMALL"))
    return os.environ.get(key.replace(".", "_").upper(), default)


def distcache_gets(path: str, key, default=None):
    """Distributed-cache key/value lookup -> local key-value file
    (ref: tools/mapred/DistributedCacheLookupUDF.java). The file holds
    tab-separated key\tvalue lines."""
    try:
        with open(path) as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if parts and parts[0] == str(key):
                    return parts[1] if len(parts) > 1 else default
    except OSError:
        pass
    return default
