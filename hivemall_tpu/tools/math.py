"""Math UDFs (ref: hivemall/tools/math/SigmoidGenericUDF.java:40)."""

from __future__ import annotations

from typing import Union

import numpy as np


def sigmoid(x: Union[float, np.ndarray]):
    """1 / (1 + e^-x) — the linear-model inference squash used by the SQL
    prediction path (ref: SURVEY.md §3.5)."""
    x = np.asarray(x, dtype=np.float64)
    out = 1.0 / (1.0 + np.exp(-x))
    return float(out) if out.ndim == 0 else out
