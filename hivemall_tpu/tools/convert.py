"""Dataset-format converters — the `resources/misc/*.awk` +
`resources/examples/kddtrack2/kddconv.awk` counterparts, as composable
generators plus a CLI (`python -m hivemall_tpu.tools.convert <name>`),
reading/writing the same TSV row shapes the reference's Hive LOAD expects.

- `libsvm_rows` (ref: resources/misc/conv.awk): "label idx:val idx:val" ->
  (rowid, label, [features]); rowids are 1-based line numbers.
- `kdd_expand` (ref: resources/examples/kddtrack2/kddconv.awk): KDD2012
  Track 2's (rowid, #clicks, #impressions-#clicks, features...) rows
  expand to one labeled row PER impression (1.0 x clicks, 0.0 x
  non-clicks) — how the reference turns aggregated ad logs into per-row
  online-learning input.
- `one_vs_rest` (ref: resources/misc/one-vs-rest.awk): multiclass rows
  (possible_labels, rowid, label, features) expand to one binary row per
  candidate label (+1 for the true label, -1 otherwise) — the manual
  one-vs-rest trick for binary-only learners.
"""

from __future__ import annotations

import re
import sys
from typing import Iterable, Iterator, List, Sequence, Tuple


def libsvm_rows(lines: Iterable[str]) -> Iterator[Tuple[int, str, List[str]]]:
    """svmlight/libsvm lines -> (rowid, label, features). rowid is the
    1-based input line number (conv.awk prints NR)."""
    for nr, line in enumerate(lines, start=1):
        parts = line.split()
        if not parts:
            continue
        yield nr, parts[0], parts[1:]


_NUM_PREFIX = re.compile(r"^[+-]?(\d+(\.\d*)?|\.\d+)")


def _int0(s: str) -> int:
    """awk-style numeric coercion: the leading numeric prefix, truncated
    (int("2.0")=2, int("3abc")=3), non-numeric (e.g. a header cell) -> 0 —
    so a stray header row expands to nothing instead of aborting the run."""
    m = _NUM_PREFIX.match(s.strip())
    return int(float(m.group(0))) if m else 0


def kdd_expand(lines: Iterable[str]) -> Iterator[Tuple[str, float, List[str]]]:
    """Tab-separated (rowid, clicks, non_clicks, feat, feat, ...) ->
    one (rowid, label, features) row per impression."""
    for line in lines:
        parts = line.rstrip("\r\n").split("\t")
        if len(parts) < 4:
            continue
        rowid, clicks, non_clicks = parts[0], _int0(parts[1]), _int0(parts[2])
        features = parts[3:]
        for _ in range(clicks):
            yield rowid, 1.0, features
        for _ in range(non_clicks):
            yield rowid, 0.0, features


def one_vs_rest(rows: Iterable[Tuple[Sequence, object, object, object]]
                ) -> Iterator[Tuple[object, object, int, object]]:
    """(possible_labels, rowid, label, features) -> one
    (rowid, candidate_label, +/-1, features) row per candidate."""
    for possible_labels, rowid, label, features in rows:
        for cand in possible_labels:
            yield rowid, cand, (1 if cand == label else -1), features


def _main(argv: List[str]) -> int:
    usage = ("usage: python -m hivemall_tpu.tools.convert "
             "(libsvm|kdd_expand|one_vs_rest) < input > output.tsv")
    if len(argv) != 1:
        print(usage, file=sys.stderr)
        return 1
    name = argv[0]
    out = sys.stdout
    if name == "libsvm":
        for rowid, label, feats in libsvm_rows(sys.stdin):
            out.write(f"{rowid}\t{label}\t{','.join(feats)}\n")
    elif name == "kdd_expand":
        for rowid, label, feats in kdd_expand(sys.stdin):
            out.write(f"{rowid}\t{label}\t{','.join(feats)}\n")
    elif name == "one_vs_rest":
        # input TSV: possible_labels(comma-joined) \t rowid \t label \t
        # features... (additional tab-separated feature columns are comma-
        # joined into ONE field, like the libsvm/kdd outputs, so the output
        # stays a strict 4-column TSV)
        def rows():
            for line in sys.stdin:
                p = line.rstrip("\r\n").split("\t")
                if len(p) >= 4:
                    yield p[0].split(","), p[1], p[2], ",".join(p[3:])

        for rowid, cand, y, feats in one_vs_rest(rows()):
            out.write(f"{rowid}\t{cand}\t{y}\t{feats}\n")
    else:
        print(usage, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(_main(sys.argv[1:]))
