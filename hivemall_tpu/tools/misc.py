"""Misc UDFs/UDTFs (ref: hivemall/tools/*.java)."""

from __future__ import annotations

import heapq
from typing import Any, Dict, Iterable, Iterator, List, Sequence, Tuple


def generate_series(start: int, end: int) -> List[int]:
    """Inclusive integer series (ref: tools/GenerateSeriesUDTF.java)."""
    return list(range(int(start), int(end) + 1))


def convert_label(label: float) -> float:
    """-1/1 <-> 0/1 label flip (ref: tools/ConvertLabelUDF.java):
    -1 -> 0, 0 -> -1, else pass-through."""
    f = float(label)
    if f == -1.0:
        return 0.0
    if f == 0.0:
        return -1.0
    return f


def x_rank(keys: Iterable) -> Iterator[Tuple[Any, int]]:
    """Per-key rank counter like ROW_NUMBER over sorted input
    (ref: tools/RankSequenceUDF.java / x_rank in define-all.hive)."""
    last = object()
    rank = 0
    for k in keys:
        if k != last:
            rank = 1
            last = k
        else:
            rank += 1
        yield k, rank


def each_top_k(k: int, rows: Iterable[Tuple[Any, float, Sequence]],
               ) -> Iterator[Tuple[int, float, Sequence]]:
    """`each_top_k(k, group, value, args...)` — per-group top-k rows by value
    with their rank (ref: tools/EachTopKUDTF.java:48-140, BoundedPriorityQueue).
    Input rows are (group, value, payload); groups must arrive contiguously
    (the reference has the same requirement). Negative k emits bottom-k."""
    import itertools

    bottom = k < 0
    kk = abs(int(k))
    if kk == 0:
        return

    counter = itertools.count()  # tie-break to keep heap comparisons total

    def flush(heap):
        ordered = sorted(heap, key=lambda t: t[0], reverse=not bottom)
        for rank, (key, _, value, payload) in enumerate(ordered, 1):
            yield rank, value, payload

    cur_group = object()
    heap: List[Tuple] = []
    for group, value, payload in rows:
        if group != cur_group:
            yield from flush(heap)
            heap = []
            cur_group = group
        key = value if not bottom else -value
        item = (key, next(counter), value, payload)
        if len(heap) < kk:
            heapq.heappush(heap, item)
        else:
            heapq.heappushpop(heap, item)
    yield from flush(heap)
