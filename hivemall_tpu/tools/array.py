"""Array UDFs (ref: hivemall/tools/array/*.java)."""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence


def float_array(nDims: int, value: float = 0.0) -> List[float]:
    """`float_array(nDims)` constant vector (ref: tools/array/AllocFloatArrayUDF.java)."""
    return [float(value)] * int(nDims)


def array_remove(arr: Sequence, target) -> List:
    """Remove all occurrences (ref: tools/array/ArrayRemoveUDF.java)."""
    if arr is None:
        return None
    return [x for x in arr if x != target]


def sort_and_uniq_array(arr: Sequence) -> List:
    """(ref: tools/array/SortAndUniqArrayUDF.java)."""
    if arr is None:
        return None
    return sorted(set(arr))


def subarray_startwith(arr: Sequence, key) -> Optional[List]:
    """Subarray from the first element == key (inclusive)
    (ref: tools/array/SubarrayStartWithUDF.java)."""
    if arr is None:
        return None
    try:
        i = list(arr).index(key)
    except ValueError:
        return None
    return list(arr)[i:]


def subarray_endwith(arr: Sequence, key) -> Optional[List]:
    """Subarray up to the first element == key (inclusive)
    (ref: tools/array/SubarrayEndWithUDF.java)."""
    if arr is None:
        return None
    try:
        i = list(arr).index(key)
    except ValueError:
        return None
    return list(arr)[: i + 1]


def subarray(arr: Sequence, from_idx: int, to_idx: int) -> Optional[List]:
    """arr[from:to] (to exclusive, clamped) (ref: tools/array/SubarrayUDF.java)."""
    if arr is None:
        return None
    n = len(arr)
    return list(arr)[max(0, from_idx) : min(n, to_idx)]


def array_concat(*arrays: Sequence) -> List:
    """(ref: tools/array/ArrayConcatUDF.java)."""
    out: List = []
    for a in arrays:
        if a is not None:
            out.extend(a)
    return out


def array_avg(rows: Iterable[Sequence[float]]) -> List[float]:
    """Element-wise average over grouped arrays (ref: tools/array/ArrayAvgGenericUDAF.java)."""
    total: List[float] = []
    n = 0
    for row in rows:
        if row is None:
            continue
        if not total:
            total = [0.0] * len(row)
        for i, v in enumerate(row):
            total[i] += float(v)
        n += 1
    return [t / n for t in total] if n else []


def array_sum(rows: Iterable[Sequence[float]]) -> List[float]:
    """Element-wise sum over grouped arrays (ref: tools/array/ArraySumUDAF.java)."""
    total: List[float] = []
    for row in rows:
        if row is None:
            continue
        if not total:
            total = [0.0] * len(row)
        for i, v in enumerate(row):
            total[i] += float(v)
    return total


def to_string_array(arr: Sequence) -> List[str]:
    """(ref: tools/array/ToStringArrayUDF.java)."""
    if arr is None:
        return None
    return [None if x is None else str(x) for x in arr]


def array_intersect(*arrays: Sequence) -> List:
    """Intersection preserving first-array order (ref: tools/array/ArrayIntersectUDF.java)."""
    if not arrays or arrays[0] is None:
        return []
    out = []
    rest = [set(a) for a in arrays[1:] if a is not None]
    seen = set()
    for x in arrays[0]:
        if x in seen:
            continue
        if all(x in s for s in rest):
            out.append(x)
            seen.add(x)
    return out


def collect_all(values: Iterable) -> List:
    """Group-collect (ref: tools/array/CollectAllUDAF.java)."""
    return list(values)
