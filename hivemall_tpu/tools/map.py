"""Map UDFs (ref: hivemall/tools/map/*.java)."""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterable, Tuple


def map_get_sum(m: Dict, keys: Iterable) -> float:
    """Sum of values at keys (missing -> 0) (ref: tools/map/MapGetSumUDF.java)."""
    return float(sum(float(m.get(k, 0.0)) for k in keys))


def map_tail_n(m: Dict, n: int) -> Dict:
    """Last N entries by key order (ref: tools/map/MapTailNUDF.java)."""
    items = sorted(m.items(), key=lambda kv: kv[0])
    return dict(items[-n:])


def to_map(kv_pairs: Iterable[Tuple]) -> Dict:
    """Group rows (key, value) -> map (ref: tools/map/UDAFToMap.java)."""
    out: Dict = {}
    for k, v in kv_pairs:
        if k is not None:
            out[k] = v
    return out


def to_ordered_map(kv_pairs: Iterable[Tuple], reverse: bool = False) -> "OrderedDict":
    """Group rows -> key-ordered map (ref: tools/map/UDAFToOrderedMap.java)."""
    out = to_map(kv_pairs)
    return OrderedDict(sorted(out.items(), key=lambda kv: kv[0], reverse=reverse))
