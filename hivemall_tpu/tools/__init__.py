from .array import (  # noqa: F401
    array_avg,
    array_concat,
    array_intersect,
    array_remove,
    array_sum,
    collect_all,
    float_array,
    sort_and_uniq_array,
    subarray,
    subarray_endwith,
    subarray_startwith,
    to_string_array,
)
from .bits import bits_collect, bits_or, to_bits, unbits  # noqa: F401
from .compress import deflate, inflate  # noqa: F401
from .map import map_get_sum, map_tail_n, to_map, to_ordered_map  # noqa: F401
from .math import sigmoid  # noqa: F401
from .misc import (  # noqa: F401
    convert_label,
    each_top_k,
    generate_series,
    x_rank,
)
from .text import (  # noqa: F401
    base91,
    is_stopword,
    normalize_unicode,
    split_words,
    tokenize,
    unbase91,
)
from .mapred import distcache_gets, jobconf_gets, jobid, rowid, taskid  # noqa: F401
from .convert import kdd_expand, libsvm_rows, one_vs_rest  # noqa: F401
