"""Bitset UDFs (ref: hivemall/tools/bits/*.java)."""

from __future__ import annotations

from typing import Iterable, List


def to_bits(indexes: Iterable[int]) -> List[int]:
    """Index list -> packed int64 words (ref: tools/bits/ToBitsUDF.java)."""
    words: List[int] = []
    for i in indexes:
        i = int(i)
        if i < 0:
            raise ValueError(f"negative index {i}")
        w = i >> 6
        while len(words) <= w:
            words.append(0)
        words[w] |= 1 << (i & 63)
    return words


def unbits(words: Iterable[int]) -> List[int]:
    """Packed words -> index list (ref: tools/bits/UnBitsUDF.java)."""
    out: List[int] = []
    for w_idx, w in enumerate(words):
        w = int(w)
        for b in range(64):
            if w & (1 << b):
                out.append(w_idx * 64 + b)
    return out


def bits_or(*bitsets: Iterable[int]) -> List[int]:
    """OR of packed bitsets (ref: tools/bits/BitsORUDF.java)."""
    out: List[int] = []
    for bs in bitsets:
        if bs is None:
            continue
        bs = list(bs)
        while len(out) < len(bs):
            out.append(0)
        for i, w in enumerate(bs):
            out[i] |= int(w)
    return out


def bits_collect(index_groups: Iterable[int]) -> List[int]:
    """Aggregate indexes into one bitset (ref: tools/bits/BitsCollectUDAF.java)."""
    return to_bits(index_groups)
