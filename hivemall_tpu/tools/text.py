"""Text UDFs (ref: hivemall/tools/text/*.java, utils/codec/Base91.java)."""

from __future__ import annotations

import re
import unicodedata
from typing import List, Union

# basE91 alphabet (Joachim Henke's standard table, also used by the reference's
# utils/codec/Base91.java)
_B91_ALPHABET = (
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789"
    "!#$%&()*+,./:;<=>?@[]^_`{|}~\""
)
_B91_DECODE = {c: i for i, c in enumerate(_B91_ALPHABET)}


def base91(data: Union[bytes, str]) -> str:
    """basE91 encode (ref: tools/text/Base91UDF.java, utils/codec/Base91.java)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    b = 0
    n = 0
    out: List[str] = []
    for byte in data:
        b |= byte << n
        n += 8
        if n > 13:
            v = b & 8191
            if v > 88:
                b >>= 13
                n -= 13
            else:
                v = b & 16383
                b >>= 14
                n -= 14
            out.append(_B91_ALPHABET[v % 91])
            out.append(_B91_ALPHABET[v // 91])
    if n:
        out.append(_B91_ALPHABET[b % 91])
        if n > 7 or b > 90:
            out.append(_B91_ALPHABET[b // 91])
    return "".join(out)


def unbase91(text: str, as_text: bool = False) -> Union[bytes, str]:
    """basE91 decode (ref: tools/text/Unbase91UDF.java)."""
    v = -1
    b = 0
    n = 0
    out = bytearray()
    for c in text:
        if c not in _B91_DECODE:
            continue
        d = _B91_DECODE[c]
        if v < 0:
            v = d
        else:
            v += d * 91
            b |= v << n
            n += 13 if (v & 8191) > 88 else 14
            while n > 7:
                out.append(b & 255)
                b >>= 8
                n -= 8
            v = -1
    if v >= 0:
        out.append((b | v << n) & 255)
    return out.decode("utf-8") if as_text else bytes(out)


def ascii85(data: Union[bytes, str]) -> str:
    """Ascii85 encode (ref: utils/io/ASCII85OutputStream.java substrate)."""
    import base64

    if isinstance(data, str):
        data = data.encode("utf-8")
    return base64.a85encode(data).decode("ascii")


def unascii85(text: str, as_text: bool = False) -> Union[bytes, str]:
    import base64

    out = base64.a85decode(text.encode("ascii"))
    return out.decode("utf-8") if as_text else out


_STOPWORDS = frozenset(
    """a about above after again against all am an and any are aren't as at be
    because been before being below between both but by can't cannot could
    couldn't did didn't do does doesn't doing don't down during each few for
    from further had hadn't has hasn't have haven't having he he'd he'll he's
    her here here's hers herself him himself his how how's i i'd i'll i'm i've
    if in into is isn't it it's its itself let's me more most mustn't my myself
    no nor not of off on once only or other ought our ours ourselves out over
    own same shan't she she'd she'll she's should shouldn't so some such than
    that that's the their theirs them themselves then there there's these they
    they'd they'll they're they've this those through to too under until up
    very was wasn't we we'd we'll we're we've were weren't what what's when
    when's where where's which while who who's whom why why's with won't would
    wouldn't you you'd you'll you're you've your yours yourself yourselves""".split()
)


def is_stopword(word: str) -> bool:
    """English stopword test (ref: tools/text/StopwordUDF.java)."""
    return word.lower() in _STOPWORDS


def tokenize(text: str, to_lower: bool = False) -> List[str]:
    """Simple word tokenizer (ref: tools/text/TokenizeUDF.java)."""
    if to_lower:
        text = text.lower()
    return re.findall(r"\w+", text, re.UNICODE)


def split_words(text: str, regex: str = r"[\s]+") -> List[str]:
    """`split_words(query, regex)` (ref: tools/text/SplitWordsUDF.java)."""
    return [w for w in re.split(regex, text) if w]


def normalize_unicode(text: str, form: str = "NFKC") -> str:
    """`normalize_unicode(str[, form])` (ref: tools/text/NormalizeUnicodeUDF.java)."""
    return unicodedata.normalize(form, text)
