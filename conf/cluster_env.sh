#!/bin/sh
# Per-deployment settings for bin/hivemall_tpu_{cluster,daemon}.sh
# (counterpart of the reference's conf/mixserv_env.sh).

# The training program every worker runs after joining the cluster, as
# launcher arguments — e.g. "examples/elastic_ctr_training.py --epochs 4"
# or "-m my_team.train". Empty = join, report the global device view, exit
# (a connectivity check, the `mixserv_cluster.sh status` analog).
#HIVEMALL_TPU_APP="examples/elastic_ctr_training.py"

# Coordination-service port on the first WORKER_LIST host
# (11212 kept from the reference's MixEnv.java:21 for familiarity).
#HIVEMALL_TPU_COORD_PORT=11212

#HIVEMALL_TPU_PYTHON=python
#HIVEMALL_TPU_LOG_DIR=
#HIVEMALL_TPU_KEEP_LOGS=5

# Per-worker HTTP scrape endpoint (the reference's JMX MBean analog):
# GET /metrics (prometheus text), GET /healthz. 0 = ephemeral port.
# SECURITY: the endpoint is unauthenticated and reveals process/device
# info. It binds 127.0.0.1 by default; a remote scraper needs an explicit
# HIVEMALL_TPU_METRICS_HOST=0.0.0.0 (or the scrape interface's address)
# opt-in below — only widen it on a trusted network.
#HIVEMALL_TPU_METRICS_PORT=9010
#HIVEMALL_TPU_METRICS_HOST=127.0.0.1
