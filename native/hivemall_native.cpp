// hivemall-tpu native host ops.
//
// The reference's performance-critical host-side pieces are hand-written Java
// data structures (SURVEY.md §2.17 [native-equiv]): MurmurHash3
// (utils/hashing/MurmurHash3.java:26-144), the feature parsers, and the NIO
// staging buffers. Here they are C++: bulk feature hashing and padded-CSR
// block packing feed the TPU input pipeline without Python-loop overhead.
//
// Exposed as a plain C ABI consumed via ctypes (hivemall_tpu/native/__init__.py).
// Build: scripts/build_native.sh (cmake or direct g++).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <unordered_map>
#include <utility>
#include <vector>

// no-alias promise for the batched-apply hot loops (the plan guarantees
// each table row is read/written through exactly one slot per chunk)
#if defined(__GNUC__) || defined(__clang__)
#define HM_RESTRICT __restrict__
#else
#define HM_RESTRICT
#endif

extern "C" {

// ------------------------------------------------------------- abi version
// Must match PLAN_ABI_VERSION in hivemall_tpu/ops/scatter.py — bump both in
// the same commit whenever the plan layout or any exported signature
// changes. The Python loader calls hm_plan_abi_version() at load time and
// refuses a stale .so; graftcheck G025 cross-checks the two literals (and
// every hm_* signature) statically.
enum { HM_PLAN_ABI_VERSION = 1 };

int64_t hm_plan_abi_version(void) {
    return HM_PLAN_ABI_VERSION;
}

// ---------------------------------------------------------------- murmur3

static inline uint32_t rotl32(uint32_t x, int8_t r) {
    return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16;
    h *= 0x85ebca6bU;
    h ^= h >> 13;
    h *= 0xc2b2ae35U;
    h ^= h >> 16;
    return h;
}

// MurmurHash3_x86_32 over a byte buffer; returns the SIGNED 32-bit value the
// JVM reference returns (bit-identical; seed 0x9747b28c is the reference's).
int32_t hm_murmur3_x86_32(const uint8_t* data, int64_t len, uint32_t seed) {
    const int64_t nblocks = len / 4;
    uint32_t h1 = seed;
    const uint32_t c1 = 0xcc9e2d51U;
    const uint32_t c2 = 0x1b873593U;

    const uint32_t* blocks = reinterpret_cast<const uint32_t*>(data);
    for (int64_t i = 0; i < nblocks; i++) {
        uint32_t k1;
        std::memcpy(&k1, blocks + i, 4);  // little-endian load
        k1 *= c1;
        k1 = rotl32(k1, 15);
        k1 *= c2;
        h1 ^= k1;
        h1 = rotl32(h1, 13);
        h1 = h1 * 5 + 0xe6546b64U;
    }

    const uint8_t* tail = data + nblocks * 4;
    uint32_t k1 = 0;
    switch (len & 3) {
        case 3: k1 ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
        case 2: k1 ^= static_cast<uint32_t>(tail[1]) << 8; [[fallthrough]];
        case 1:
            k1 ^= tail[0];
            k1 *= c1;
            k1 = rotl32(k1, 15);
            k1 *= c2;
            h1 ^= k1;
    }

    h1 ^= static_cast<uint32_t>(len);
    return static_cast<int32_t>(fmix32(h1));
}

// Bulk hash: `n` strings concatenated in `buf` with offsets[n+1]; results
// folded into [0, num_features) with Java floor-mod semantics
// (ref: MurmurHash3.java:40-46).
void hm_murmur3_bulk(const uint8_t* buf, const int64_t* offsets, int64_t n,
                     uint32_t seed, int64_t num_features, int64_t* out) {
    for (int64_t i = 0; i < n; i++) {
        const int64_t start = offsets[i];
        const int64_t len = offsets[i + 1] - start;
        int64_t h = hm_murmur3_x86_32(buf + start, len, seed);
        int64_t r = h % num_features;
        if (r < 0) r += num_features;
        out[i] = r;
    }
}

// ---------------------------------------------------------------- CSR pack

// Pack variable-length hashed rows into a padded [n_rows, width] block
// (core/batch.py layout: pad index == dims -> OOB drop, pad value == 0).
// rows are concatenated in `indices`/`values` with `offsets[n_rows+1]`.
void hm_pack_block(const int64_t* indices, const float* values,
                   const int64_t* offsets, int64_t n_rows, int64_t width,
                   int64_t dims, int32_t* out_idx, float* out_val,
                   int32_t* out_nnz) {
    for (int64_t r = 0; r < n_rows; r++) {
        const int64_t start = offsets[r];
        int64_t k = offsets[r + 1] - start;
        if (k > width) k = width;
        int32_t* oi = out_idx + r * width;
        float* ov = out_val + r * width;
        int64_t c = 0;
        for (; c < k; c++) {
            oi[c] = static_cast<int32_t>(indices[start + c] % dims);
            ov[c] = values[start + c];
        }
        for (; c < width; c++) {
            oi[c] = static_cast<int32_t>(dims);
            ov[c] = 0.0f;
        }
        out_nnz[r] = static_cast<int32_t>(k);
    }
}

// ------------------------------------------------------------- record shards

// Decode the body of a HMTR1 record shard (hivemall_tpu/io/records.py):
// per row: u8 nnz | varint delta ids | f32[nnz] values | f32 label.
// Pass 1 (out_* null): returns total nnz. Pass 2: fills row_offsets[n+1],
// indices/values[total_nnz], labels[n]. Returns total nnz, or -1 on corrupt
// input.
int64_t hm_decode_records(const uint8_t* data, int64_t len, int64_t n_rows,
                          int64_t* row_offsets, int64_t* indices, float* values,
                          float* labels) {
    int64_t pos = 0;
    int64_t total = 0;
    for (int64_t r = 0; r < n_rows; r++) {
        if (pos >= len) return -1;
        const int nnz = data[pos++];
        if (row_offsets) row_offsets[r] = total;
        int64_t prev = 0;
        for (int k = 0; k < nnz; k++) {
            int64_t v = 0;
            int shift = 0;
            while (true) {
                if (pos >= len || shift > 63) return -1;
                const uint8_t b = data[pos++];
                v |= static_cast<int64_t>(b & 0x7F) << shift;
                if (!(b & 0x80)) break;
                shift += 7;
            }
            prev += v;
            if (indices) indices[total + k] = prev;
        }
        if (pos + 4 * nnz + 4 > len) return -1;
        if (values) std::memcpy(values + total, data + pos, 4 * nnz);
        pos += 4 * nnz;
        if (labels) std::memcpy(labels + r, data + pos, 4);
        pos += 4;
        total += nnz;
    }
    if (row_offsets) row_offsets[n_rows] = total;
    return total;
}

// Encode rows into an HMTR1 shard body (the write side of hm_decode_records;
// hivemall_tpu/io/records.py format). Rows are concatenated in
// `indices`/`values` with `offsets[n_rows+1]`; each row is sorted by feature
// id here so ids delta-code monotonically. Returns bytes written, or -1 when
// a row exceeds 255 nnz / ids are negative / `cap` is too small (size the
// buffer with hm_encode_records_bound).
int64_t hm_encode_records_bound(const int64_t* offsets, int64_t n_rows) {
    // worst case per row: 1 (nnz) + 10 (varint) * nnz + 4 * nnz + 4 (label)
    const int64_t total_nnz = offsets[n_rows];
    return n_rows * 5 + total_nnz * 14;
}

int64_t hm_encode_records(const int64_t* indices, const float* values,
                          const int64_t* offsets, const float* labels,
                          int64_t n_rows, uint8_t* out, int64_t cap) {
    int64_t pos = 0;
    std::vector<std::pair<int64_t, float>> row;
    for (int64_t r = 0; r < n_rows; r++) {
        const int64_t start = offsets[r];
        const int64_t nnz = offsets[r + 1] - start;
        if (nnz > 255) return -1;
        row.clear();
        for (int64_t k = 0; k < nnz; k++) {
            if (indices[start + k] < 0) return -1;
            row.emplace_back(indices[start + k], values[start + k]);
        }
        // stable, id-only: equal-id entries (hash collisions) keep input
        // order so the byte stream matches the Python fallback exactly
        std::stable_sort(row.begin(), row.end(),
                         [](const std::pair<int64_t, float>& a,
                            const std::pair<int64_t, float>& b) {
                             return a.first < b.first;
                         });
        if (pos + 1 + nnz * 14 + 4 > cap) return -1;
        out[pos++] = static_cast<uint8_t>(nnz);
        int64_t prev = 0;
        for (int64_t k = 0; k < nnz; k++) {
            uint64_t d = static_cast<uint64_t>(row[k].first - prev);
            prev = row[k].first;
            while (true) {
                const uint8_t b = d & 0x7F;
                d >>= 7;
                if (d) {
                    out[pos++] = b | 0x80;
                } else {
                    out[pos++] = b;
                    break;
                }
            }
        }
        for (int64_t k = 0; k < nnz; k++) {
            std::memcpy(out + pos, &row[k].second, 4);
            pos += 4;
        }
        std::memcpy(out + pos, labels + r, 4);
        pos += 4;
    }
    return pos;
}

// ------------------------------------------------------------ zigzag-LEB128

// Bulk signed-int codec (ref: utils/codec/ZigZagLEB128Codec.java) — the model
// blob compression hot path (encode_sparse_model delta streams). Returns
// bytes written (encode) / bytes consumed (decode), or -1 on overflow/corrupt.
int64_t hm_zigzag_leb128_encode(const int64_t* vals, int64_t n, uint8_t* out,
                                int64_t cap) {
    int64_t pos = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t u = (static_cast<uint64_t>(vals[i]) << 1) ^
                     static_cast<uint64_t>(vals[i] >> 63);
        if (pos + 10 > cap) return -1;
        while (true) {
            const uint8_t b = u & 0x7F;
            u >>= 7;
            if (u) {
                out[pos++] = b | 0x80;
            } else {
                out[pos++] = b;
                break;
            }
        }
    }
    return pos;
}

int64_t hm_zigzag_leb128_decode(const uint8_t* buf, int64_t len, int64_t n,
                                int64_t* out) {
    int64_t pos = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t u = 0;
        int shift = 0;
        while (true) {
            if (pos >= len || shift > 63) return -1;
            const uint8_t b = buf[pos++];
            // at shift 63 only bit 0 of the payload fits in 64 bits; a wider
            // final byte means the stream encodes a >64-bit value (the Python
            // big-int path owns those) — reject rather than silently wrap
            if (shift == 63 && (b & 0x7E)) return -1;
            u |= static_cast<uint64_t>(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        out[i] = static_cast<int64_t>(u >> 1) ^ -static_cast<int64_t>(u & 1);
    }
    return pos;
}

// Parse a "idx:value" / "idx" feature byte-string (int features) without
// Python per-token overhead. Returns 0 on success.
int32_t hm_parse_int_feature(const uint8_t* s, int64_t len, int64_t* out_idx,
                             float* out_val) {
    int64_t i = 0;
    int64_t idx = 0;
    bool any = false;
    for (; i < len && s[i] != ':'; i++) {
        if (s[i] < '0' || s[i] > '9') return -1;
        idx = idx * 10 + (s[i] - '0');
        any = true;
    }
    if (!any) return -1;
    *out_idx = idx;
    if (i == len) {
        *out_val = 1.0f;
        return 0;
    }
    // value part
    char tmp[64];
    int64_t vlen = len - i - 1;
    if (vlen <= 0 || vlen >= 63) return -1;
    std::memcpy(tmp, s + i + 1, vlen);
    tmp[vlen] = '\0';
    char* end = nullptr;
    *out_val = std::strtof(tmp, &end);
    return (end && *end == '\0') ? 0 : -1;
}

// Bulk "name[:value]" feature parsing — the host pipeline's front door
// (ref: model/FeatureValue.java:74-93 split-at-first-colon grammar;
// ftvec/hashing/FeatureHashingUDF.java:172 string-name hashing). Tokens
// arrive as one concatenated utf-8 buffer + offsets; per token this writes
// the hashed/modded index and the value without Python per-token overhead.
// Numeric names (optional +/- then digits only, <=18 digits) index the
// space directly with floor-mod (Java %-then-fixup); anything else
// murmur-hashes (seed 0x9747b28c) then floor-mods. Returns 0, or
// -(token+1) on the first malformed token (caller falls back to the Python
// parser so error behavior stays identical).
int64_t hm_parse_features_batch(const uint8_t* buf, const int64_t* offsets,
                                int64_t n_tokens, int64_t num_features,
                                int64_t* out_idx, float* out_val) {
    const uint32_t seed = 0x9747b28cU;
    for (int64_t t = 0; t < n_tokens; t++) {
        const uint8_t* s = buf + offsets[t];
        const int64_t len = offsets[t + 1] - offsets[t];
        if (len <= 0) return -(t + 1);
        // split at the FIRST ':'
        int64_t pos = -1;
        for (int64_t i = 0; i < len; i++) {
            if (s[i] == ':') { pos = i; break; }
        }
        if (pos == 0) return -(t + 1);
        const int64_t name_len = (pos < 0) ? len : pos;
        // value
        float val = 1.0f;
        if (pos >= 0) {
            const int64_t vlen = len - pos - 1;
            if (vlen <= 0 || vlen >= 63) return -(t + 1);
            char tmp[64];
            std::memcpy(tmp, s + pos + 1, vlen);
            tmp[vlen] = '\0';
            // strict value grammar: plain decimal/scientific literals only.
            // strtof accepts more than Python float() (hex floats,
            // "nan(chars)", locale comma decimals) — decline anything
            // outside [0-9.eE+-] so the Python parser defines semantics
            for (int64_t i = 0; i < vlen; i++) {
                const char c = tmp[i];
                if (!((c >= '0' && c <= '9') || c == '.' || c == 'e' ||
                      c == 'E' || c == '+' || c == '-')) {
                    return -(t + 1);
                }
            }
            char* end = nullptr;
            val = std::strtof(tmp, &end);
            if (!end || *end != '\0') return -(t + 1);
        }
        // name: pure optional-sign integer -> direct index, else hash
        bool numeric = name_len > 0 && name_len <= 19;
        int64_t start = 0;
        bool neg = false;
        if (numeric && (s[0] == '+' || s[0] == '-')) {
            neg = (s[0] == '-');
            start = 1;
            if (name_len == 1) numeric = false;
        }
        int64_t iv = 0;
        bool numeric_ish = true;  // only [0-9+-_ \t] but not strictly numeric
        for (int64_t i = 0; i < name_len; i++) {
            const uint8_t c = s[i];
            if (!((c >= '0' && c <= '9') || c == '+' || c == '-' ||
                  c == '_' || c == ' ' || c == '\t')) {
                numeric_ish = false;
                break;
            }
        }
        if (numeric) {
            if (name_len - start > 18) {
                numeric = false;
            } else {
                for (int64_t i = start; i < name_len; i++) {
                    if (s[i] < '0' || s[i] > '9') { numeric = false; break; }
                    iv = iv * 10 + (s[i] - '0');
                }
            }
        }
        // " 5" / "1_0" etc: Python's int() would accept these where the
        // strict scan above does not — decline to the Python parser rather
        // than silently hashing what Python would index
        if (!numeric && numeric_ish) return -(t + 1);
        int64_t idx;
        if (numeric) {
            if (neg) iv = -iv;
            idx = iv % num_features;
            if (idx < 0) idx += num_features;  // floor-mod, Java fixup
        } else {
            int64_t h = hm_murmur3_x86_32(s, name_len, seed);
            idx = h % num_features;
            if (idx < 0) idx += num_features;
        }
        out_idx[t] = idx;
        out_val[t] = val;
    }
    return 0;
}

// ------------------------------------------------------ lattice tokenizer

// Bulk Viterbi segmentation for the Japanese lattice tokenizer — the C
// twin of hivemall_tpu/nlp/lattice.py::LatticeTokenizer._viterbi (which
// remains the semantic authority; the Python wrapper parity-tests and
// falls back). The reference's analyzer is JVM-native Kuromoji
// (ref: nlp/.../KuromojiUDF.java:55-86); this is its host-native analog.
//
// Inputs are CODEPOINT arrays with per-char CLASS ids precomputed by
// Python (so unicode isspace/isdigit/isalnum semantics never diverge):
//   classes: 0=hira 1=kata 2=kanji 3=num 4=latin 5=space 6=punct
// The lexicon arrives as codepoint surfaces + per-surface (pos, cost)
// entry lists; candidate iteration order matches the Python exactly
// (dictionary lengths ascending with entries in stored order, then
// unknown lengths ascending, strict < updates) so ties break identically.
namespace lattice {

struct SurfKey {
    const uint32_t* p;
    int32_t len;
    bool operator==(const SurfKey& o) const {
        if (len != o.len) return false;
        return std::memcmp(p, o.p, len * 4) == 0;
    }
};

struct SurfHash {
    size_t operator()(const SurfKey& k) const {
        uint64_t h = 1469598103934665603ULL;
        for (int32_t i = 0; i < k.len; i++) {
            h ^= k.p[i];
            h *= 1099511628211ULL;
        }
        return (size_t)h;
    }
};

}  // namespace lattice

int64_t hm_lattice_tokenize_bulk(
    const uint32_t* cps, const uint8_t* classes, const int64_t* text_offsets,
    int64_t n_texts,
    // lexicon: surfaces as codepoints + per-surface entry ranges
    const uint32_t* surf_buf, const int64_t* surf_offsets,
    const int64_t* entry_offsets, const int16_t* entry_pos,
    const int32_t* entry_cost, int64_t n_surfaces, int32_t max_word,
    // connection matrix [n_pos, n_pos] and unknown model per class id 0..4
    const int32_t* conn, int32_t n_pos,
    const int32_t* unk_base, const int32_t* unk_per, const int16_t* unk_pos,
    // outputs: per-token (start char, length, pos id) + per-text counts
    int32_t* out_start, int32_t* out_len, int16_t* out_pos,
    int64_t* out_counts) {
    using lattice::SurfKey;
    using lattice::SurfHash;

    std::unordered_map<SurfKey, std::pair<int64_t, int64_t>, SurfHash> lex;
    lex.reserve((size_t)n_surfaces * 2);
    for (int64_t s = 0; s < n_surfaces; s++) {
        SurfKey k{surf_buf + surf_offsets[s],
                  (int32_t)(surf_offsets[s + 1] - surf_offsets[s])};
        lex.emplace(k, std::make_pair(entry_offsets[s], entry_offsets[s + 1]));
    }

    const int64_t INF = (int64_t)1 << 60;
    int64_t out_n = 0;

    // scratch (sized to the longest segment lazily)
    std::vector<int64_t> best_cost;
    std::vector<int32_t> best_prev, best_len;
    std::vector<int16_t> best_pos;
    std::vector<int32_t> tok_start_rev, tok_len_rev;
    std::vector<int16_t> tok_pos_rev;

    for (int64_t t = 0; t < n_texts; t++) {
        const int64_t t0 = text_offsets[t], t1 = text_offsets[t + 1];
        int64_t count = 0;
        int64_t i = t0;
        while (i < t1) {
            if (classes[i] >= 5) {  // space/punct: segment break
                i++;
                continue;
            }
            int64_t j = i;
            while (j < t1 && classes[j] < 5) j++;
            // Viterbi over segment [i, j) with a state per (position, pos):
            // mirrors lattice.py::_viterbi — collapsing to one state per
            // position breaks the POS-bigram model (a dearer prefix whose
            // final pos connects better downstream must survive; see the
            // Python twin's comment / the round-5 blind3 生まれ+た case).
            const int64_t n = j - i;
            const uint32_t* s = cps + i;
            const uint8_t* cls = classes + i;
            const int64_t S = n_pos + 1;  // state n_pos = BOS
            best_cost.assign((n + 1) * S, INF);
            best_prev.assign((n + 1) * S, -1);
            best_len.assign((n + 1) * S, 0);
            best_pos.assign((n + 1) * S, -1);  // prev STATE (pos row) taken
            best_cost[0 * S + n_pos] = 0;
            for (int64_t p = 0; p < n; p++) {
                // gather candidate list once per position
                const uint8_t c = cls[p];
                int64_t run = 1;
                while (p + run < n && cls[p + run] == c) run++;
                int64_t lens[8];
                int64_t n_lens = 0;
                if (c == 1 || c == 3 || c == 4) {  // kata/num/latin
                    lens[n_lens++] = run;
                } else if (c == 2) {  // kanji: 1..min(run,4) (+run if >4)
                    const int64_t top = std::min<int64_t>(run, 4);
                    for (int64_t L = 1; L <= top; L++) lens[n_lens++] = L;
                    if (run > 4) lens[n_lens++] = run;
                } else {  // hira: 1..min(run,3)
                    const int64_t top = std::min<int64_t>(run, 3);
                    for (int64_t L = 1; L <= top; L++) lens[n_lens++] = L;
                }
                const int64_t ub = unk_base[c], up = unk_per[c];
                const int16_t upos = unk_pos[c];
                // hash probes are state-independent: resolve the position's
                // dictionary hits + unknown suppressions ONCE, then relax
                // every live state against the cached list (the per-state
                // loop would otherwise re-run identical lex.find probes
                // S = n_pos+1 times in the bulk kernel's hot path)
                struct DictHit { int32_t L; int64_t e0, e1; };
                DictHit hits[64];
                int64_t n_hits = 0;
                const int64_t maxL = std::min<int64_t>(max_word, n - p);
                for (int64_t L = 1; L <= maxL && n_hits < 64; L++) {
                    SurfKey k{s + p, (int32_t)L};
                    auto it = lex.find(k);
                    if (it == lex.end()) continue;
                    hits[n_hits++] = DictHit{(int32_t)L, it->second.first,
                                             it->second.second};
                }
                bool unk_ok[8];
                for (int64_t li = 0; li < n_lens; li++) {
                    const int64_t L = lens[li];
                    SurfKey k{s + p, (int32_t)L};
                    unk_ok[li] = !(L <= max_word && lex.find(k) != lex.end());
                }
                for (int64_t st = 0; st < S; st++) {
                    const int64_t c0 = best_cost[p * S + st];
                    if (c0 >= INF) continue;
                    const int16_t pos_i = (st == n_pos) ? -1 : (int16_t)st;
                    // dictionary candidates (lengths ascending, entry order
                    // — the tie-break order lattice.py mirrors)
                    for (int64_t h = 0; h < n_hits; h++) {
                        const int64_t L = hits[h].L;
                        for (int64_t e = hits[h].e0; e < hits[h].e1; e++) {
                            const int16_t pos = entry_pos[e];
                            const int64_t connc =
                                (pos_i < 0) ? 0 : conn[pos_i * n_pos + pos];
                            const int64_t total = c0 + entry_cost[e] + connc;
                            int64_t* cell = &best_cost[(p + L) * S + pos];
                            if (total < *cell) {
                                *cell = total;
                                best_prev[(p + L) * S + pos] = (int32_t)p;
                                best_len[(p + L) * S + pos] = (int32_t)L;
                                best_pos[(p + L) * S + pos] = (int16_t)st;
                            }
                        }
                    }
                    // unknown candidates over the same-class run
                    for (int64_t li = 0; li < n_lens; li++) {
                        if (!unk_ok[li]) continue;
                        const int64_t L = lens[li];
                        const int64_t connc =
                            (pos_i < 0) ? 0 : conn[pos_i * n_pos + upos];
                        const int64_t total = c0 + ub + up * L + connc;
                        int64_t* cell = &best_cost[(p + L) * S + upos];
                        if (total < *cell) {
                            *cell = total;
                            best_prev[(p + L) * S + upos] = (int32_t)p;
                            best_len[(p + L) * S + upos] = (int32_t)L;
                            best_pos[(p + L) * S + upos] = (int16_t)st;
                        }
                    }
                }
            }
            // cheapest end state, then backtrack (or the whole-segment
            // fallback the Python has)
            int64_t end_st = -1, end_cost = INF;
            for (int64_t st = 0; st < S; st++) {
                if (best_cost[n * S + st] < end_cost) {
                    end_cost = best_cost[n * S + st];
                    end_st = st;
                }
            }
            tok_start_rev.clear();
            tok_len_rev.clear();
            tok_pos_rev.clear();
            if (end_st < 0 && n > 0) {
                // unreachable end: emit the segment whole as its first
                // char's unknown pos (lattice.py's fallback)
                tok_start_rev.push_back((int32_t)(i - t0));
                tok_len_rev.push_back((int32_t)n);
                tok_pos_rev.push_back(unk_pos[cls[0]]);
            } else {
                int64_t pcur = n;
                int64_t stcur = end_st;
                while (pcur > 0) {
                    const int32_t prev = best_prev[pcur * S + stcur];
                    if (prev < 0) return -1;  // corrupt lattice
                    tok_start_rev.push_back((int32_t)(i - t0 + prev));
                    tok_len_rev.push_back(best_len[pcur * S + stcur]);
                    tok_pos_rev.push_back((int16_t)stcur);
                    const int16_t pst = best_pos[pcur * S + stcur];
                    pcur = prev;
                    stcur = pst;
                }
            }
            for (int64_t r = (int64_t)tok_start_rev.size() - 1; r >= 0; r--) {
                out_start[out_n] = tok_start_rev[r];
                out_len[out_n] = tok_len_rev[r];
                out_pos[out_n] = tok_pos_rev[r];
                out_n++;
                count++;
            }
            i = j;
        }
        out_counts[t] = count;
    }
    return out_n;
}

// --------------------------------------------------------- forest evaluator

// Bulk StackMachine evaluation: T compiled opcode programs (the tree export
// format, hivemall_tpu/models/trees/vm.py compile_script_arrays encoding)
// over N rows of F raw features -> out[T*N] leaf values. Mirrors
// StackMachine.eval exactly (comparisons pop (lower, upper), fall through
// when `upper OP lower` holds; one-shot visit guard per op). Returns 0, or
// -1 on a malformed program (bad feature index, stack misuse, loop).
enum {
    HM_OP_PUSH_FEATURE = 0,
    HM_OP_PUSH_CONST = 1,
    HM_OP_POP = 2,
    HM_OP_GOTO = 3,
    HM_OP_IFEQ = 4,
    HM_OP_IFGE = 5,
    HM_OP_IFGT = 6,
    HM_OP_IFLE = 7,
    HM_OP_IFLT = 8,
    HM_OP_CALL_END = 9,
};

int64_t hm_forest_eval(const int8_t* ops, const int32_t* argi,
                       const double* argf, const int64_t* offsets, int64_t T,
                       const double* X, int64_t N, int64_t F, double* out) {
    for (int64_t t = 0; t < T; t++) {
        const int64_t base = offsets[t];
        const int64_t n = offsets[t + 1] - base;
        if (n <= 0) return -1;
        for (int64_t r = 0; r < N; r++) {
            const double* x = X + r * F;
            double stack[64];
            int sp = 0;
            int64_t ip = 0, steps = 0;
            double result = 0.0;
            bool done = false;
            while (ip >= 0 && ip < n) {
                if (++steps > n) return -1;  // revisit = infinite loop
                const int8_t op = ops[base + ip];
                const int32_t ai = argi[base + ip];
                switch (op) {
                    case HM_OP_PUSH_FEATURE:
                        if (ai < 0 || ai >= F || sp >= 64) return -1;
                        stack[sp++] = x[ai];
                        ip++;
                        break;
                    case HM_OP_PUSH_CONST:
                        if (sp >= 64) return -1;
                        stack[sp++] = argf[base + ip];
                        ip++;
                        break;
                    case HM_OP_POP:
                        if (sp < 1) return -1;
                        result = stack[--sp];
                        ip++;
                        break;
                    case HM_OP_GOTO:
                        ip = ai;
                        break;
                    case HM_OP_IFEQ:
                    case HM_OP_IFGE:
                    case HM_OP_IFGT:
                    case HM_OP_IFLE:
                    case HM_OP_IFLT: {
                        if (sp < 2) return -1;
                        const double lower = stack[--sp];
                        const double upper = stack[--sp];
                        bool ok;
                        switch (op) {
                            case HM_OP_IFEQ: ok = upper == lower; break;
                            case HM_OP_IFGE: ok = upper >= lower; break;
                            case HM_OP_IFGT: ok = upper > lower; break;
                            case HM_OP_IFLE: ok = upper <= lower; break;
                            default: ok = upper < lower; break;
                        }
                        ip = ok ? ip + 1 : ai;
                        break;
                    }
                    case HM_OP_CALL_END:
                        if (sp < 1) return -1;
                        result = stack[--sp];
                        ip = n;  // halt
                        done = true;
                        break;
                    default:
                        return -1;
                }
            }
            if (!done && steps == 0) return -1;
            out[t * N + r] = result;
        }
    }
    return 0;
}

// ------------------------------------------------- reference anchor loop
//
// The reference's per-row AROW hot loop, transliterated to C so the anchor
// the bench divides by is MEASURED on this host instead of assumed
// (VERDICT r3 missing #2). Semantics per row (one Hive mapper's work,
// classifier/AROWClassifierUDTF.java:99-150 + the per-set clock/delta
// bookkeeping of model/DenseModel.java:193-201):
//   score = sum w[i]*x, variance = sum cov[i]*x^2   (calcScoreAndVariance)
//   m = score*y; if m < 1: beta = 1/(var+r), alpha = (1-m)*beta
//   per feature: cv = cov*x; w += y*alpha*cv; cov -= beta*cv*cv
//   per set: clocks[i]++, deltaUpdates[i]++ (wrapping like short/byte)
// This deliberately EXCLUDES the JVM's string parse + ObjectInspector +
// boxed-object costs, so it upper-bounds (flatters) the reference mapper.
// Returns the count of margin-violating rows so the work can't be
// dead-code-eliminated.
//
// `touched` (nullable): monotone per-feature was-ever-set flags for the
// -native_scan execution backend's model emission — the wrap-prone
// clock/delta counters mirror DenseModel and CANNOT serve as touched
// (a count that wraps to 0 would silently drop the feature's model row).
// Anchor measurements pass NULL so the timed loop stays the pure
// reference transliteration.
int64_t hm_arow_reference_rowloop(const int32_t* idx, const float* val,
                                  const float* labels, int64_t n_rows,
                                  int64_t width, float r,
                                  float* w, float* cov,
                                  int16_t* clocks, int8_t* deltas,
                                  uint8_t* touched) {
    int64_t violations = 0;
    for (int64_t row = 0; row < n_rows; ++row) {
        const int32_t* ki = idx + row * width;
        const float* kv = val + row * width;
        const float y = labels[row] > 0.f ? 1.f : -1.f;
        float score = 0.f, variance = 0.f;
        for (int64_t j = 0; j < width; ++j) {
            const float x = kv[j];
            score += w[ki[j]] * x;
            variance += cov[ki[j]] * x * x;
        }
        const float m = score * y;
        if (m < 1.f) {
            ++violations;
            const float beta = 1.f / (variance + r);
            const float alpha = (1.f - m) * beta;
            for (int64_t j = 0; j < width; ++j) {
                const int32_t k = ki[j];
                const float cv = cov[k] * kv[j];
                w[k] += y * alpha * cv;
                cov[k] -= beta * cv * cv;
                clocks[k] = (int16_t)(clocks[k] + 1);
                deltas[k] = (int8_t)(deltas[k] + 1);
                if (touched) touched[k] = 1;
            }
        }
    }
    return violations;
}

// The reference's per-row FM (train_fm, classification) hot loop, same
// purpose as hm_arow_reference_rowloop: a measured train_fm anchor.
// Semantics per row (fm/FactorizationMachineUDTF.java:369-393 trainTheta +
// fm/FactorizationMachineModel.java:136-160 predict, :209-247 updates),
// with the fixed-eta schedule and the adaptive-lambda path off (defaults):
//   p = w0 + sum wi*xi + 0.5*sum_f[(sum Vif*xi)^2 - sum (Vif*xi)^2]
//   dloss = (sigmoid(p*y) - 1)*y
//   w0  -= eta*(dloss + 2*l0*w0)
//   wi  -= eta*(dloss*xi + 2*lw*wi)
//   Vif -= eta*(dloss*xi*(sumVfX[f] - Vif*xi) + 2*lv*Vif)   (gradV, :76)
// V is [dims, k] row-major. Returns sign-error count (prevents DCE).
// `touched` nullable like hm_arow_reference_rowloop's: monotone flags for
// the -native_scan backend; anchors pass NULL.
int64_t hm_fm_reference_rowloop(const int32_t* idx, const float* val,
                                const float* labels, int64_t n_rows,
                                int64_t width, int64_t k,
                                float eta, float lambda,
                                float* w0_inout, float* w, float* V,
                                uint8_t* touched) {
    float w0 = *w0_inout;
    double sumVfX[64];  // k <= 64 (reference default 5)
    if (k > 64) return -1;
    int64_t errors = 0;
    for (int64_t row = 0; row < n_rows; ++row) {
        const int32_t* ki = idx + row * width;
        const float* kv = val + row * width;
        const float y = labels[row] > 0.f ? 1.f : -1.f;
        double p = w0;
        for (int64_t j = 0; j < width; ++j) p += (double)w[ki[j]] * kv[j];
        for (int64_t f = 0; f < k; ++f) {
            double s = 0.0, s2 = 0.0;
            for (int64_t j = 0; j < width; ++j) {
                const double vx = (double)V[(int64_t)ki[j] * k + f] * kv[j];
                s += vx;
                s2 += vx * vx;
            }
            sumVfX[f] = s;
            p += 0.5 * (s * s - s2);
        }
        if (p * y < 0.0) ++errors;
        const double z = p * y;
        const double sig = 1.0 / (1.0 + std::exp(-z));
        const double dloss = (sig - 1.0) * y;
        w0 -= eta * ((float)dloss + 2.f * lambda * w0);
        for (int64_t j = 0; j < width; ++j) {
            const int32_t i = ki[j];
            const double xi = kv[j];
            w[i] -= eta * ((float)(dloss * xi) + 2.f * lambda * w[i]);
            float* vi = V + (int64_t)i * k;
            for (int64_t f = 0; f < k; ++f) {
                const double h = xi * (sumVfX[f] - (double)vi[f] * xi);
                vi[f] -= eta * ((float)(dloss * h) + 2.f * lambda * vi[f]);
            }
            if (touched) touched[i] = 1;
        }
    }
    *w0_inout = w0;
    return errors;
}

// ------------------------------------------------- native batched apply
//
// The -batch B -native_apply execution backend: consume a host-built
// StagedDedupPlan (ops/scatter.py — the PR 11 sort/segment structure,
// VERBATIM, frozen ABI below) and apply a whole staged block's minibatch
// updates in one pass, with no XLA in the loop. The XLA batch backend's
// binding constraint is the final scatter, which XLA:CPU executes
// element-at-a-time (~15M elt/s measured); here gather, batch closed form,
// segment reduction and scatter-back are plain contiguous loops the
// compiler vectorizes, and the table walk is sequential (plan reps are
// ascending feature ids).
//
// Plan ABI (frozen, v1 — hivemall_tpu/ops/scatter.py::plan_abi_arrays):
//   order    int32 [N]  permutation sorting the chunk's flat lane ids
//   lane_seg int32 [N]  slot id of each ORIGINAL lane
//   rep      int32 [U]  ascending unique feature ids; pads >= dims
//   starts   int32 [U]  inclusive start of each slot's run in sorted order
//   ends     int32 [U]  exclusive end (== start on pad slots)
// All C-contiguous; a block's main chunks arrive stacked with a leading
// [nb] axis (chunk c at offset c*N / c*U), the tail chunk as its own
// arrays. N = chunk_rows * width.
//
// Semantics per chunk = core/batch_update.py::chunk_update exactly
// (the engine's minibatch accumulate-then-apply, RegressionBaseUDTF.java:
// 236-295 FloatAccumulator): every row computes against the CHUNK-start
// tables, per-slot delta sums divide by per-slot update counts
// (mini_avg), one add per live slot. f32 accumulation like the XLA path's
// cumsum — equal up to reduction order; the 0/1 counts are exact.

enum {
    HM_BATCH_RULE_PERCEPTRON = 0,
    HM_BATCH_RULE_CW = 1,
    HM_BATCH_RULE_AROW = 2,
    HM_BATCH_RULE_AROWH = 3,
};

namespace batch_apply {

struct Scratch {
    std::vector<float> uwc;          // [U*2] interleaved (w, cov) uniques
    std::vector<float> acc;          // [U*4] interleaved (dw, dcov, cnt, -)
    std::vector<float> score, var;   // [B] row scalars
    std::vector<float> upd, coef, beta, aphi;  // [B] row coefficients
};

// One chunk, four passes. Hashed CTR ids make the plan's sorted runs
// SHORT (zipf-like duplicates: ~2 lanes per unique slot at the bench
// shapes), so per-segment sweeps drown in loop setup; the hot passes here
// run in LANE order instead — sequential reads of lane_seg/val, with the
// per-slot state compacted into interleaved scratch rows ([U*2] gathered
// w+cov, [U*4] delta accumulators: one cache line per lane touch). Only
// the table edges (gather, apply) walk the [U] slots, in ascending
// feature-id order.
//   1. lane pass #1: per-row score/variance (register accumulators, one
//      scratch line per lane);
//   2. per-row rule closed form -> margin/violation masks and the
//      coefficients that linearize every lane delta;
//   3. lane pass #2: scatter-accumulate (dw, dcov, count) per slot;
//   4. slot pass: ONE count-averaged read-modify-write per live feature.
static void apply_chunk(int32_t rule_id, float r, float cpar, float phi,
                        const float* HM_RESTRICT val,
                        const float* HM_RESTRICT labels,
                        int64_t bsz, int64_t width,
                        const int32_t* HM_RESTRICT lane_seg,
                        const int32_t* HM_RESTRICT rep,
                        int64_t n_slots, int64_t dims,
                        float* HM_RESTRICT w, float* HM_RESTRICT cov,
                        int8_t* HM_RESTRICT touched, int mini_avg,
                        Scratch& s, double* loss_out) {
    const bool use_cov = rule_id != HM_BATCH_RULE_PERCEPTRON;
    // gather each unique feature ONCE (ascending ids: a sequential table
    // walk); pad slots read the fills (w 0, cov 1 — fresh variance)
    {
        float* HM_RESTRICT uwc = s.uwc.data();
        for (int64_t u = 0; u < n_slots; u++) {
            const int32_t rp = rep[u];
            const bool live = rp >= 0 && rp < dims;
            uwc[u * 2] = live ? w[rp] : 0.f;
            uwc[u * 2 + 1] = use_cov ? (live ? cov[rp] : 1.f) : 0.f;
        }
    }
    // pass 1: row scalars in lane order (sequential lane_seg/val reads,
    // register accumulators — no store-to-load dependences)
    {
        const float* HM_RESTRICT uwc = s.uwc.data();
        float* HM_RESTRICT score = s.score.data();
        float* HM_RESTRICT var = s.var.data();
        for (int64_t b = 0; b < bsz; b++) {
            const float* HM_RESTRICT v = val + b * width;
            const int32_t* HM_RESTRICT ls = lane_seg + b * width;
            float sc = 0.f, va = 0.f;
            if (use_cov) {
                for (int64_t k = 0; k < width; k++) {
                    const float* uv = uwc + int64_t{2} * ls[k];
                    sc += uv[0] * v[k];
                    va += uv[1] * v[k] * v[k];
                }
            } else {
                for (int64_t k = 0; k < width; k++) {
                    sc += uwc[int64_t{2} * ls[k]] * v[k];
                }
            }
            score[b] = sc;
            var[b] = va;
        }
    }
    // pass 2: the rule's batch closed form per row, folded into per-row
    // coefficients so pass 3 rebuilds any lane's delta from (row coeffs,
    // lane value, slot cov) without materializing [B, K] delta tensors
    double loss = 0.0;
    for (int64_t b = 0; b < bsz; b++) {
        const float score = s.score[b];
        const float var = use_cov ? s.var[b] : 0.f;
        const float y = labels[b];
        float upd = 0.f, coef = 0.f, beta = 0.f, aphi = 0.f;
        switch (rule_id) {
            case HM_BATCH_RULE_PERCEPTRON: {
                // (ref: PerceptronUDTF.java:44-50)
                upd = (y * score <= 0.f) ? 1.f : 0.f;
                coef = upd * y;  // dw = coef * x
                loss += upd;
                break;
            }
            case HM_BATCH_RULE_CW: {
                // (ref: ConfidenceWeightedUDTF.java:126-164)
                const float sy = score * y;
                const float bq = 1.f + 2.f * phi * sy;
                float disc = bq * bq - 8.f * phi * (sy - phi * var);
                if (disc < 0.f) disc = 0.f;
                const float den = 4.f * phi * var;
                const float gamma =
                    (den == 0.f) ? 0.f : (-bq + std::sqrt(disc)) / den;
                upd = (gamma > 0.f) ? 1.f : 0.f;
                const float alpha = upd * gamma;
                coef = alpha * y;        // dw = coef * cov * x
                aphi = 2.f * alpha * phi;  // dcov = cov/(1+aphi*x^2*cov)-cov
                loss += (sy < 0.f) ? 1.0 : 0.0;
                break;
            }
            case HM_BATCH_RULE_AROW:
            case HM_BATCH_RULE_AROWH: {
                // (ref: AROWClassifierUDTF.java:101-147, :190-209)
                const float m = score * y;
                const float bet = 1.f / (var + r);
                float alpha_scale;
                if (rule_id == HM_BATCH_RULE_AROWH) {
                    const float l = cpar - m;
                    alpha_scale = l > 0.f ? l : 0.f;
                    upd = (alpha_scale > 0.f) ? 1.f : 0.f;
                    loss += alpha_scale;
                } else {
                    upd = (m < 1.f) ? 1.f : 0.f;
                    alpha_scale = 1.f - m;
                    loss += (m < 0.f) ? 1.0 : 0.0;
                }
                coef = upd * alpha_scale * bet * y;  // dw = coef * cov * x
                beta = upd * bet;  // dcov = -beta * (cov * x)^2
                break;
            }
        }
        s.upd[b] = upd;
        s.coef[b] = coef;
        s.beta[b] = beta;
        s.aphi[b] = aphi;
    }
    // pass 3: scatter-accumulate every lane's (dw, dcov, count) into the
    // compact per-slot accumulator rows — lane-order sequential reads,
    // one interleaved scratch line per lane write
    {
        float* HM_RESTRICT acc = s.acc.data();
        const float* HM_RESTRICT uwc = s.uwc.data();
        std::memset(acc, 0, sizeof(float) * 4 * n_slots);
        const float* HM_RESTRICT updv = s.upd.data();
        const float* HM_RESTRICT coefv = s.coef.data();
        const float* HM_RESTRICT betav = s.beta.data();
        const float* HM_RESTRICT aphiv = s.aphi.data();
        for (int64_t b = 0; b < bsz; b++) {
            // non-violating row: every lane delta and count is exactly 0
            // (CW's per-lane dcov too — alpha == 0 makes den == 1), so
            // skipping matches the XLA path bit-for-bit, like the
            // reference row loop's margin branch
            if (updv[b] == 0.f) continue;
            const float* HM_RESTRICT v = val + b * width;
            const int32_t* HM_RESTRICT ls = lane_seg + b * width;
            const float cb = coefv[b], bb = betav[b], ab = aphiv[b];
            switch (rule_id) {
                case HM_BATCH_RULE_PERCEPTRON:
                    for (int64_t k = 0; k < width; k++) {
                        float* a = acc + int64_t{4} * ls[k];
                        a[0] += cb * v[k];
                        a[2] += 1.f;
                    }
                    break;
                case HM_BATCH_RULE_CW:
                    for (int64_t k = 0; k < width; k++) {
                        const int32_t u = ls[k];
                        const float x = v[k];
                        const float cl = uwc[int64_t{2} * u + 1];
                        float* a = acc + int64_t{4} * u;
                        a[0] += cb * cl * x;
                        const float den = 1.f + ab * x * x * cl;
                        a[1] += cl / den - cl;
                        a[2] += 1.f;
                    }
                    break;
                default:  // arow / arowh
                    for (int64_t k = 0; k < width; k++) {
                        const int32_t u = ls[k];
                        const float cv = uwc[int64_t{2} * u + 1] * v[k];
                        float* a = acc + int64_t{4} * u;
                        a[0] += cb * cv;
                        a[1] -= bb * cv * cv;
                        a[2] += 1.f;
                    }
                    break;
            }
        }
        // pass 4: apply — ONE count-averaged read-modify-write per live
        // slot (ascending feature ids: a sequential table walk),
        // count-averaged like the reference's FloatAccumulator
        for (int64_t u = 0; u < n_slots; u++) {
            const float cnt = acc[u * 4 + 2];
            if (cnt == 0.f) continue;
            const int32_t rp = rep[u];
            if (rp < 0 || rp >= dims) continue;  // pad slot: drop
            const float denom = mini_avg ? (cnt > 1.f ? cnt : 1.f) : 1.f;
            w[rp] += acc[u * 4] / denom;
            if (use_cov) cov[rp] += acc[u * 4 + 1] / denom;
            if (touched) touched[rp] = 1;
        }
    }
    *loss_out += loss;
}

}  // namespace batch_apply

// Apply one staged block through the plan(s): `nb` stacked main chunks of
// `bsz` rows (plan arrays with a leading [nb] axis) then the optional
// tail chunk (its own plan). Returns 0, or -1 on malformed arguments
// (bad rule id, missing cov table, row-count mismatch). Accumulates the
// block's loss sum into *loss_out (caller zeroes it).
int64_t hm_batch_apply_block(
    int32_t rule_id, float r, float cpar, float phi,
    const float* val, const float* labels, int64_t n_rows, int64_t width,
    int64_t nb, int64_t bsz, int64_t slots_u,
    const int32_t* order, const int32_t* lane_seg, const int32_t* rep,
    const int32_t* starts, const int32_t* ends,
    int64_t tail_rows, int64_t tail_u,
    const int32_t* t_order, const int32_t* t_lane_seg, const int32_t* t_rep,
    const int32_t* t_starts, const int32_t* t_ends,
    int64_t dims, float* w, float* cov, int8_t* touched,
    int32_t mini_avg, double* loss_out) {
    if (rule_id < HM_BATCH_RULE_PERCEPTRON ||
        rule_id > HM_BATCH_RULE_AROWH || width <= 0 || dims <= 0 ||
        loss_out == nullptr || w == nullptr) {
        return -1;
    }
    if (rule_id != HM_BATCH_RULE_PERCEPTRON && cov == nullptr) return -1;
    if (nb * bsz + tail_rows != n_rows) return -1;
    if (nb > 0 && (order == nullptr || lane_seg == nullptr ||
                   rep == nullptr || starts == nullptr || ends == nullptr)) {
        return -1;
    }
    if (tail_rows > 0 &&
        (t_order == nullptr || t_lane_seg == nullptr || t_rep == nullptr ||
         t_starts == nullptr || t_ends == nullptr)) {
        return -1;
    }
    const int64_t max_b = bsz > tail_rows ? bsz : tail_rows;
    const int64_t max_u = slots_u > tail_u ? slots_u : tail_u;
    batch_apply::Scratch s;
    s.uwc.resize(max_u * 2);
    s.acc.resize(max_u * 4);
    s.score.resize(max_b);
    s.var.resize(max_b);
    s.upd.resize(max_b);
    s.coef.resize(max_b);
    s.beta.resize(max_b);
    s.aphi.resize(max_b);
    *loss_out = 0.0;
    const int64_t lanes = bsz * width;
    // order/starts/ends are ABI fields the XLA path and future kernels
    // replay; this kernel's hot passes run in lane order (short zipf
    // segments — see apply_chunk) and consume lane_seg + rep only
    (void)order;
    (void)starts;
    (void)ends;
    (void)t_order;
    (void)t_starts;
    (void)t_ends;
    for (int64_t c = 0; c < nb; c++) {
        batch_apply::apply_chunk(
            rule_id, r, cpar, phi, val + c * lanes, labels + c * bsz, bsz,
            width, lane_seg + c * lanes, rep + c * slots_u,
            slots_u, dims, w, cov, touched, mini_avg, s, loss_out);
    }
    if (tail_rows > 0) {
        batch_apply::apply_chunk(
            rule_id, r, cpar, phi, val + nb * lanes, labels + nb * bsz,
            tail_rows, width, t_lane_seg, t_rep,
            tail_u, dims, w, cov, touched, mini_avg, s, loss_out);
    }
    return 0;
}

}  // extern "C"
