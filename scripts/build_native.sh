#!/usr/bin/env bash
# Build the native host-ops shared library (native/hivemall_native.cpp) into
# hivemall_tpu/native/libhivemall_native.so. Pure C ABI, consumed via ctypes.
#
# --sanitize=MODE builds an instrumented variant next to the optimized one:
#   --sanitize=address,undefined -> libhivemall_native.asan.so  (ASan+UBSan)
#   --sanitize=thread            -> libhivemall_native.tsan.so  (TSan)
# Suffixed outputs so a sanitizer or -O0 build can never be mistaken for the
# optimized library; hivemall_tpu.native selects a variant at load via
# HIVEMALL_TPU_NATIVE_SANITIZE= (see scripts/test.sh gate 11). Sanitizer
# runtimes are NOT linked into a -shared .so — run with
# LD_PRELOAD="$(g++ -print-file-name=libasan.so) $(g++ -print-file-name=libubsan.so)".
#
# --if-stale: rebuild only when the .so is missing, its build stamp (compiler
# version + flags + source sha256) mismatches, or — plain variant only — it
# is unloadable on THIS host (the PR 11 GLIBCXX-mismatch pathology) or
# predates the newest required symbol. The stamp is what makes flag changes
# count as staleness: before it, `--if-stale` only compared mtimes, so a
# stray -O0 or sanitizer build of the same source looked "fresh" forever.
# Exits 0 WITHOUT building when no C++ compiler is present —
# hivemall_tpu.native then reports unavailability loudly (warnings +
# load_error()) and the native bench gates skip with the reason in-artifact.
# A present compiler that fails to build is a hard error: scripts/test.sh
# runs this un-guarded so a broken toolchain fails tier-1 instead of
# shipping a stale library.
set -euo pipefail
cd "$(dirname "$0")/.."

SRC=native/hivemall_native.cpp
# bumped with the plan ABI (ops/scatter.py PLAN_ABI_VERSION): a loadable
# .so missing this symbol predates the current ABI and must be rebuilt
# (the loader also calls it at runtime and refuses on version mismatch)
PROBE_SYMBOL=hm_plan_abi_version

IF_STALE=0
SANITIZE=""
for arg in "$@"; do
  case "$arg" in
    --if-stale) IF_STALE=1 ;;
    --sanitize=*) SANITIZE="${arg#--sanitize=}" ;;
    *) echo "build_native.sh: unknown argument: $arg" >&2; exit 2 ;;
  esac
done

case "$SANITIZE" in
  "")
    SO=hivemall_tpu/native/libhivemall_native.so
    FLAGS="-O3 -march=native"
    PROBE_LOAD=1 ;;  # the optimized .so must CDLL cleanly standalone
  address|undefined|address,undefined|undefined,address)
    SO=hivemall_tpu/native/libhivemall_native.asan.so
    FLAGS="-O1 -g -fno-omit-frame-pointer -fsanitize=address,undefined -fno-sanitize-recover=all"
    PROBE_LOAD=0 ;;  # needs LD_PRELOADed runtimes; CDLL probe would lie
  thread)
    SO=hivemall_tpu/native/libhivemall_native.tsan.so
    FLAGS="-O1 -g -fno-omit-frame-pointer -fsanitize=thread"
    PROBE_LOAD=0 ;;
  *)
    echo "build_native.sh: unknown --sanitize mode: $SANITIZE" \
         "(expected address,undefined | thread)" >&2
    exit 2 ;;
esac
STAMP="$SO.stamp"

stamp_content() {
  # compiler identity + exact flags + source hash: any drift in any of the
  # three means the binary on disk is not the binary these inputs produce
  echo "compiler: $(g++ --version 2>/dev/null | head -n 1)"
  echo "flags: $FLAGS -fPIC -shared -std=c++17"
  echo "source: $(sha256sum "$SRC" | cut -d' ' -f1)"
}

if [[ "$IF_STALE" == 1 ]]; then
  fresh=0
  if [[ -f "$SO" && -f "$STAMP" ]] && command -v g++ >/dev/null 2>&1 \
      && [[ "$(stamp_content)" == "$(cat "$STAMP")" ]]; then
    if [[ "$PROBE_LOAD" == 1 ]]; then
      if python - "$SO" "$PROBE_SYMBOL" <<'EOF'
import ctypes, sys
try:
    lib = ctypes.CDLL(sys.argv[1])
except OSError:
    sys.exit(1)  # present but unloadable on this host: stale
sys.exit(0 if hasattr(lib, sys.argv[2]) else 1)
EOF
      then fresh=1; fi
    else
      fresh=1  # stamp match is the whole check for sanitizer variants
    fi
  fi
  if [[ "$fresh" == 1 ]]; then
    if [[ "$PROBE_LOAD" == 1 ]]; then
      echo "native: $SO is fresh (stamp matches, loads, exports $PROBE_SYMBOL)"
    else
      echo "native: $SO is fresh (stamp matches)"
    fi
    exit 0
  fi
  if ! command -v g++ >/dev/null 2>&1; then
    echo "native: $SO is stale/missing and no g++ is available;" \
         "skipping build — hivemall_tpu.native will report the" \
         "load failure loudly and native gates skip with the reason" >&2
    exit 0
  fi
fi

mkdir -p hivemall_tpu/native
# shellcheck disable=SC2086  # FLAGS is a deliberate word-split flag list
g++ $FLAGS -fPIC -shared -std=c++17 \
    "$SRC" \
    -o "$SO"
stamp_content > "$STAMP"
echo "built $SO (stamp: $STAMP)"
