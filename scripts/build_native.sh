#!/usr/bin/env bash
# Build the native host-ops shared library (native/hivemall_native.cpp) into
# hivemall_tpu/native/libhivemall_native.so. Pure C ABI, consumed via ctypes.
set -euo pipefail
cd "$(dirname "$0")/.."
mkdir -p hivemall_tpu/native
g++ -O3 -march=native -fPIC -shared -std=c++17 \
    native/hivemall_native.cpp \
    -o hivemall_tpu/native/libhivemall_native.so
echo "built hivemall_tpu/native/libhivemall_native.so"
