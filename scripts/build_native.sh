#!/usr/bin/env bash
# Build the native host-ops shared library (native/hivemall_native.cpp) into
# hivemall_tpu/native/libhivemall_native.so. Pure C ABI, consumed via ctypes.
#
# --if-stale: rebuild only when the .so is missing, older than its source,
# unloadable on THIS host (the PR 11 GLIBCXX-mismatch pathology: a .so built
# elsewhere fails CDLL and everything silently fell back to Python), or
# predates the newest required symbol. Exits 0 WITHOUT building when no C++
# compiler is present — hivemall_tpu.native then reports unavailability
# loudly (warnings + load_error()) and the native bench gates skip with the
# reason in-artifact. A present compiler that fails to build is a hard
# error: scripts/test.sh runs this un-guarded so a broken toolchain fails
# tier-1 instead of shipping a stale library.
set -euo pipefail
cd "$(dirname "$0")/.."

SO=hivemall_tpu/native/libhivemall_native.so
SRC=native/hivemall_native.cpp
# bumped with the plan ABI (ops/scatter.py PLAN_ABI_VERSION): a loadable
# .so missing this symbol predates the current ABI and must be rebuilt
PROBE_SYMBOL=hm_batch_apply_block

if [[ "${1:-}" == "--if-stale" ]]; then
  fresh=0
  if [[ -f "$SO" && "$SO" -nt "$SRC" ]]; then
    if python - "$SO" "$PROBE_SYMBOL" <<'EOF'
import ctypes, sys
try:
    lib = ctypes.CDLL(sys.argv[1])
except OSError:
    sys.exit(1)  # present but unloadable on this host: stale
sys.exit(0 if hasattr(lib, sys.argv[2]) else 1)
EOF
    then fresh=1; fi
  fi
  if [[ "$fresh" == 1 ]]; then
    echo "native: $SO is fresh (loads, exports $PROBE_SYMBOL)"
    exit 0
  fi
  if ! command -v g++ >/dev/null 2>&1; then
    echo "native: $SO is stale/missing and no g++ is available;" \
         "skipping build — hivemall_tpu.native will report the" \
         "load failure loudly and native gates skip with the reason" >&2
    exit 0
  fi
fi

mkdir -p hivemall_tpu/native
g++ -O3 -march=native -fPIC -shared -std=c++17 \
    native/hivemall_native.cpp \
    -o "$SO"
echo "built $SO"
