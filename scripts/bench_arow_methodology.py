"""Methodology disambiguation for the AROW headline number.

Round 1 self-reported 702M rows/s; the round-2 driver recorded 469M for the
same metric name (BENCH_r02.json). This script runs the SAME workload
(AROW minibatch, 2^22 dims, 32 nnz, 16384-row blocks, HBM-staged) under
three timing methodologies so the gap is attributable, not guessed:

1. python-loop  — bench.py's loop: each step dispatched from Python, one
   block_until_ready at the end. Includes per-step Python/relay dispatch
   overhead whenever dispatch cannot stay ahead of 23us steps.
2. device-scan  — the whole epoch as ONE lax.scan jitted over the staged
   blocks: zero per-step dispatch, pure device compute. The framework's
   actual deployment shape (the training loop lives on device).
3. single-step  — per-step wall time of an isolated step (what round 1's
   0.023 ms profile measured), extrapolated.

Round-4 honesty note: through the axon relay, `block_until_ready` can
return before the producing execution finishes (measured — see PERF.md and
runtime/benchmark.py), so every methodology now ends its timed region with
a VALUE fetch of a result scalar, which no runtime can satisfy early.

Prints one JSON line per methodology. Rerunnable:
    python scripts/bench_arow_methodology.py [--rounds N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

DIMS = 1 << 22
BATCH = 16384
WIDTH = 32
N_BLOCKS = 8


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=None,
                    help="timing rounds (default: 40 on accelerators, 2 on cpu)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from hivemall_tpu.core.engine import make_train_fn, make_train_step
    from hivemall_tpu.core.state import init_linear_state
    from hivemall_tpu.models.classifier import AROW

    platform = jax.devices()[0].platform
    rng = np.random.RandomState(0)
    idx = (rng.zipf(1.3, size=(N_BLOCKS, BATCH, WIDTH)) % DIMS).astype(np.int32)
    val = np.ones((N_BLOCKS, BATCH, WIDTH), dtype=np.float32)
    lab = np.sign(rng.randn(N_BLOCKS, BATCH)).astype(np.float32)
    idx_d = jnp.asarray(idx)
    val_d = jnp.asarray(val)
    lab_d = jnp.asarray(lab)
    rounds = args.rounds if args.rounds is not None \
        else (40 if platform != "cpu" else 2)
    print(f"# platform={platform} rounds={rounds}", file=sys.stderr)

    def report(name, rows, secs):
        print(json.dumps({
            "metric": f"arow_methodology_{name}_{platform}",
            "value": round(rows / secs, 1),
            "unit": "rows/sec",
            "vs_baseline": round(rows / secs / 2.5e5, 3),
            "wall_s": round(secs, 4),
        }), flush=True)

    # 1. python-loop (bench.py methodology)
    step = make_train_step(AROW, {"r": 0.1}, mode="minibatch", donate=True)
    state = init_linear_state(DIMS, use_covariance=True)
    state, loss = step(state, idx_d[0], val_d[0], lab_d[0])
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    total = 0
    for _ in range(rounds):
        for b in range(N_BLOCKS):
            state, loss = step(state, idx_d[b], val_d[b], lab_d[b])
            total += BATCH
    _ = float(loss)  # value fetch: un-fakeable sync (see runtime/benchmark.py)
    report("python_loop", total, time.perf_counter() - t0)
    del state

    # 2. device-scan: the whole multi-round epoch is one jitted program
    fn = make_train_fn(AROW, {"r": 0.1}, mode="minibatch")

    @jax.jit
    def epoch(state, idx, val, lab):
        def body(s, blk):
            s, loss = fn(s, *blk)
            return s, loss

        return jax.lax.scan(body, state, (idx, val, lab))

    state = init_linear_state(DIMS, use_covariance=True)
    state, losses = epoch(state, idx_d, val_d, lab_d)
    jax.block_until_ready(losses)
    t0 = time.perf_counter()
    total = 0
    for _ in range(rounds):
        state, losses = epoch(state, idx_d, val_d, lab_d)
        total += N_BLOCKS * BATCH
    _ = float(losses[-1])  # value fetch: un-fakeable sync
    report("device_scan", total, time.perf_counter() - t0)
    del state

    # 3. single-step wall time, synchronized each step (profile methodology)
    step2 = make_train_step(AROW, {"r": 0.1}, mode="minibatch", donate=True)
    state = init_linear_state(DIMS, use_covariance=True)
    state, loss = step2(state, idx_d[0], val_d[0], lab_d[0])
    jax.block_until_ready(loss)
    n = max(rounds // 2, 2)
    t0 = time.perf_counter()
    for i in range(n):
        state, loss = step2(state, idx_d[i % N_BLOCKS], val_d[i % N_BLOCKS],
                            lab_d[i % N_BLOCKS])
        _ = float(loss)  # value fetch: un-fakeable per-step sync
    report("single_step_sync", n * BATCH, time.perf_counter() - t0)


if __name__ == "__main__":
    main()
