import sys, os; sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
"""Hardware validation of the Pallas scan backend: compile (interpret=False)
on the attached TPU, compare against the engine's reference-exact scan mode
for every rule family, and time Pallas-vs-engine sequential throughput."""
import time

import numpy as np
import jax

from hivemall_tpu.core.engine import make_train_step
from hivemall_tpu.core.state import init_linear_state
from hivemall_tpu.kernels.linear_scan import make_pallas_scan_step
from hivemall_tpu.models.classifier import AROW


from tests.pallas_cases import generic_rules as rules
from tests.pallas_cases import make_block_data as data


def main():
    platform = jax.devices()[0].platform
    assert platform == "tpu", f"need the TPU chip, got {platform}"

    D = 256
    idx, val, y = data(D=D)
    state = init_linear_state(D, use_covariance=True)
    step = make_train_step(AROW, {"r": 0.1}, mode="scan", donate=False)
    ref_state, _ = step(state, idx, val, y)
    got_state, _ = make_pallas_scan_step(AROW, {"r": 0.1})(
        init_linear_state(D, use_covariance=True), idx, val, y)
    np.testing.assert_allclose(np.asarray(got_state.weights),
                               np.asarray(ref_state.weights),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_state.covars),
                               np.asarray(ref_state.covars),
                               rtol=1e-4, atol=1e-5)
    print("AROW via generic pallas backend: compiled, matches engine scan")

    for i, (rule, hyper, binary) in enumerate(rules()):
        idx, val, y = data(B=48, K=8, D=128, seed=i)
        if not binary:
            y = (y * 0.3).astype(np.float32)
        kw = dict(use_covariance=rule.use_covariance,
                  slot_names=rule.slot_names, global_names=rule.global_names)
        ref, ref_loss = make_train_step(rule, hyper, mode="scan", donate=False)(
            init_linear_state(128, **kw), idx, val, y)
        got, got_loss = make_pallas_scan_step(rule, hyper)(
            init_linear_state(128, **kw), idx, val, y)
        np.testing.assert_allclose(np.asarray(got.weights),
                                   np.asarray(ref.weights), rtol=1e-4, atol=1e-5)
        assert abs(float(got_loss) - float(ref_loss)) < 1e-3 + 1e-4 * abs(float(ref_loss))
        print(f"{rule.name}: compiled, matches engine scan")
        n_verified = i + 2  # + the AROW case above

    # partial-progress line: a relay drop during the (long) timing runs
    # below must still leave the correctness result published
    import json
    print(json.dumps({
        "metric": "pallas_rule_families_hardware_verified_tpu",
        "value": n_verified, "unit": "rule_families",
    }), flush=True)

    # throughput: sequential semantics, Pallas VMEM kernel vs engine HBM scan
    B, K, Dbig = 4096, 16, 1 << 18
    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    idx = jnp.asarray((rng.zipf(1.3, size=(B, K)) % Dbig).astype(np.int32))
    val = jnp.ones((B, K), np.float32)
    y = jnp.asarray(np.sign(rng.randn(B)).astype(np.float32))

    def timeit(step, st):
        # verified sync: end every timed window with a VALUE FETCH of a
        # scalar carried through the step chain — block_until_ready
        # through the axon relay can acknowledge before execution
        # finishes (PERF.md round-4b retraction)
        st2, loss = step(st, idx, val, y)
        float(loss)
        t0 = time.perf_counter()
        n = 10
        for _ in range(n):
            st2, loss = step(st2, idx, val, y)
        float(loss)
        return (time.perf_counter() - t0) / n

    eng = timeit(make_train_step(AROW, {"r": 0.1}, mode="scan", donate=False),
                 init_linear_state(Dbig, use_covariance=True))
    print(json.dumps({
        "metric": "engine_scan_arow_seq_4096x16_2^18_tpu",
        "value": round(B / eng, 1), "unit": "rows/sec",
        "ms_per_block": round(eng * 1e3, 3),
    }), flush=True)
    pal = timeit(make_pallas_scan_step(AROW, {"r": 0.1}),
                 init_linear_state(Dbig, use_covariance=True))
    print(f"sequential AROW [B={B},K={K},D=2^18]: engine scan "
          f"{eng*1e3:.1f} ms/block ({B/eng:,.0f} rows/s), pallas "
          f"{pal*1e3:.1f} ms/block ({B/pal:,.0f} rows/s), "
          f"speedup {eng/pal:.1f}x")
    print(json.dumps({
        "metric": "pallas_vmem_scan_arow_seq_4096x16_2^18_tpu",
        "value": round(B / pal, 1), "unit": "rows/sec",
        "engine_scan_rows_per_sec": round(B / eng, 1),
        "speedup_vs_engine_scan": round(eng / pal, 2),
        "ms_per_block": round(pal * 1e3, 3),
    }), flush=True)


if __name__ == "__main__":
    main()
