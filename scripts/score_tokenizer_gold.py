"""Score the built-in tokenize_ja lattice analyzer against the gold
segmentation fixture; prints one JSON line (the number PERF.md cites).

Run: python scripts/score_tokenizer_gold.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    from hivemall_tpu.nlp import tokenize_ja
    from hivemall_tpu.nlp.evaluate import load_gold, segmentation_prf
    from hivemall_tpu.nlp.tokenizer import backend_name

    data_dir = os.path.join(os.path.dirname(__file__), "..", "tests", "data")
    for tag, fname in (("dev", "tokenize_ja_gold.tsv"),
                       ("heldout", "tokenize_ja_heldout.tsv"),
                       ("blind2", "tokenize_ja_blind2.tsv"),
                       ("blind3", "tokenize_ja_blind3.tsv"),
                       ("blind4", "tokenize_ja_blind4.tsv"),
                       ("blind5", "tokenize_ja_blind5.tsv"),
                       ("blind6", "tokenize_ja_blind6.tsv")):
        gold = load_gold(os.path.join(data_dir, fname))
        pairs = [(toks, tokenize_ja(sent)) for sent, toks in gold]
        m = segmentation_prf(pairs)
        print(json.dumps({
            "metric": f"tokenize_ja_{tag}_f1",
            "value": round(m["f1"], 4),
            "unit": "span_f1",
            "precision": round(m["precision"], 4),
            "recall": round(m["recall"], 4),
            "sentences": len(gold),
            "gold_tokens": m["gold_tokens"],
            "backend": backend_name(),
        }))


if __name__ == "__main__":
    main()
