"""Forest-training benchmark: batched level-synchronous growth (grow_forest)
vs the per-tree loop (grow_tree) on the same bootstrap bags, plus a GBT
mode timing single-device vs data-parallel boosting rounds.

Usage: python scripts/bench_forest.py [N] [F] [T]
       python scripts/bench_forest.py --gbt [N] [F] [rounds]
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from hivemall_tpu.models.trees.binning import bin_data, make_bins
from hivemall_tpu.models.trees.grow import grow_forest, grow_tree


def main_gbt(args):
    """Single-device vs data-parallel GBT rounds (the psum'd histogram
    build, parallel/forest_shard.train_gbt_data_parallel)."""
    import jax

    from hivemall_tpu.models.trees.forest import \
        train_gradient_tree_boosting_classifier
    from hivemall_tpu.parallel import make_mesh
    from hivemall_tpu.parallel.forest_shard import train_gbt_data_parallel

    N = int(args[0]) if len(args) > 0 else 50000
    F = int(args[1]) if len(args) > 1 else 20
    rounds = int(args[2]) if len(args) > 2 else 16
    rng = np.random.RandomState(0)
    X = rng.rand(N, F)
    y = ((X[:, 0] > 0.5) ^ (X[:, 1] > 0.5) | (X[:, 2] > 0.8)).astype(int)
    opts = f"-trees {rounds} -iters {rounds} -depth 6 -seed 3"
    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)

    # warm both paths at the TIMED shapes (full N and depth — the jitted
    # histogram builders retrace per (N, S_pad), so a sliver warm-up would
    # leave compiles inside the timed region)
    warm = "-trees 2 -iters 2 -depth 6 -seed 1"
    train_gradient_tree_boosting_classifier(X, y, warm)
    train_gbt_data_parallel(X, y, warm, mesh)

    t0 = time.perf_counter()
    single = train_gradient_tree_boosting_classifier(X, y, opts)
    t_single = time.perf_counter() - t0
    t0 = time.perf_counter()
    par = train_gbt_data_parallel(X, y, opts, mesh)
    t_par = time.perf_counter() - t0
    acc_s = float(np.mean(single.predict(X) == y))
    acc_p = float(np.mean(par.predict(X) == y))
    print(json.dumps({
        "metric": f"gbt_{rounds}rounds_{N}rows_{F}feat_depth6_dataparallel_"
                  f"{jax.devices()[0].platform}",
        "value": round(t_par, 3),
        "unit": "sec",
        "single_device_sec": round(t_single, 3),
        "n_devices": n_dev,
        "speedup": round(t_single / t_par, 2),
        "train_acc_single": round(acc_s, 4),
        "train_acc_parallel": round(acc_p, 4),
    }), flush=True)


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--gbt":
        return main_gbt(sys.argv[2:])
    N = int(sys.argv[1]) if len(sys.argv) > 1 else 20000
    F = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    T = int(sys.argv[3]) if len(sys.argv) > 3 else 32
    rng = np.random.RandomState(0)
    X = rng.rand(N, F)
    y = ((X[:, 0] > 0.5) ^ (X[:, 1] > 0.5) | (X[:, 2] > 0.8)).astype(int)
    bins = make_bins(X, ["Q"] * F)
    Xb = bin_data(X, bins)
    n_bins = max(b.n_bins for b in bins)
    W = np.stack([
        np.bincount(np.random.RandomState(100 + t).randint(0, N, N),
                    minlength=N).astype(np.float32) for t in range(T)])
    kw = dict(n_bins=n_bins, classification=True, n_classes=2,
              max_depth=10, min_split=2, min_leaf=1, max_leaf_nodes=256,
              num_vars=max(1, int(np.sqrt(F))))

    def run_batched():
        return grow_forest(Xb, y, W, np.zeros(F, bool),
                           rngs=[np.random.RandomState(t) for t in range(T)],
                           strategy="batched", **kw)

    def run_per_tree():
        return [grow_tree(Xb, y, W[t], np.zeros(F, bool),
                          rng=np.random.RandomState(t), **kw)
                for t in range(T)]

    # warm up compiles on a tiny forest first
    small = dict(kw)
    grow_forest(Xb[:512], y[:512], W[:2, :512], np.zeros(F, bool),
                rngs=[np.random.RandomState(0), np.random.RandomState(1)], **small)
    grow_tree(Xb[:512], y[:512], W[0, :512], np.zeros(F, bool),
              rng=np.random.RandomState(0), **small)

    t0 = time.perf_counter()
    forest = run_batched()
    t_batched = time.perf_counter() - t0
    t0 = time.perf_counter()
    solo = run_per_tree()
    t_per_tree = time.perf_counter() - t0
    nodes = sum(t.n_nodes for t in forest)
    nodes_solo = sum(t.n_nodes for t in solo)
    print(f"rows={N} features={F} trees={T} nodes batched={nodes} per-tree={nodes_solo}")
    print(f"batched grow_forest: {t_batched:.2f}s   per-tree grow_tree loop: "
          f"{t_per_tree:.2f}s   speedup {t_per_tree / t_batched:.2f}x")
    import jax

    print(json.dumps({
        "metric": f"forest_grow_{T}trees_{N}rows_{F}feat_depth10_batched_"
                  f"{jax.devices()[0].platform}",
        "value": round(t_batched, 3),
        "unit": "sec",
        "per_tree_loop_sec": round(t_per_tree, 3),
        "batched_speedup": round(t_per_tree / t_batched, 2),
        "nodes": int(nodes),
        # grow_forest(strategy="auto") picks per_tree when unsharded — flag
        # loudly if this platform's data ever contradicts that default
        "default_strategy": "per_tree",
        "default_is_fastest": bool(t_per_tree <= t_batched),
    }), flush=True)


if __name__ == "__main__":
    main()
