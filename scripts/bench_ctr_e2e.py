"""North-star end-to-end benchmark: KDD2012-Track2-shaped CTR training to a
held-out logloss target, for train_arow AND train_fm, scored with the
scoreKDD protocol (AUC / NWMAE / WRMSE).

BASELINE.json's north star: beat the Hive-on-YARN + MixServer path on
KDD2012 Track 2 CTR at equal logloss. The actual KDD dataset cannot be
downloaded in this image (zero egress), so this generates a seeded
KDD-shaped stand-in ON DEVICE (so the axon tunnel never throttles it):

- 2^22 hashed feature dims (the reference's default dense-model space is
  2^24, LearnerBaseUDTF.java:90; KDD Track 2's active dimensionality after
  hashing fits 2^22), 32 nnz/row categorical features with a log-uniform
  (heavy-tailed) id distribution like hashed CTR traffic;
- ground-truth logistic CTR model w* ~ N(0, 1.5/sqrt(32)), bias -2.0
  (mean CTR ~12%), clicks ~ Bernoulli(sigmoid(w*.x + b));
- train on `--train-rows` impressions, evaluate held-out logloss on
  `--test-rows` impressions, score AUC/NWMAE/WRMSE per the reference's
  scorer semantics (ref: resources/examples/kddtrack2/scoreKDD.py:1-40;
  vectorized in examples/score_ctr.py).

Equal-logloss protocol: the engine's minibatch path is the reference's own
documented mini-batch semantic (RegressionBaseUDTF.java:236-295) with
minibatch(1) == scan invariant-tested (tests/test_engine_invariants.py);
the achieved held-out logloss is reported next to the Bayes floor (binary
entropy of the true CTR, computable because the generator is known). The
reference wall-clock comparison is the documented JVM per-row hot-loop
anchor of 2.5e5 rows/s (BASELINE.md: the repo publishes no numbers; this is
the measured order of magnitude of a single Hive mapper on this update
family) extrapolated to the same number of row-updates. vs_baseline =
anchor_wall_clock / our_wall_clock.

Prints one JSON line per workload plus a combined summary line. Rerunnable:
    python scripts/bench_ctr_e2e.py [--train-rows N] [--epochs-fm N] ...
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

ANCHOR_ROWS_PER_SEC = 250_000.0  # BASELINE.md JVM mapper anchor
DIMS = 1 << 22
WIDTH = 32
BATCH = 16384
BIAS = -2.0
SIGMA_W = 1.5 / np.sqrt(WIDTH)


def gen_blocks(key, n_blocks, dims, batch, width, w_true, perm=None):
    """Generate stacked CTR blocks on device: ids log-uniform over [1, dims)
    then spread hash-uniformly by `perm` (murmur-hashed features keep their
    frequency but land uniformly over the table — raw log-uniform ids would
    cluster the hot head in the first cache lines, a contiguity gift no real
    hashed data gives the host anchor; pure relabeling, the learning problem
    is identical), values 1.0 (categorical), clicks
    Bernoulli(sigmoid(w*.x + bias)).

    Returns device arrays shaped [n_blocks, batch, ...] so the epoch loop can
    be ONE jitted `lax.scan` (the framework's deployment shape — io/records.py
    prefetch + on-device epoch replay; the reference likewise replays epochs
    from its NIO buffer, FactorizationMachineUDTF.java:521)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def all_blocks(k):
        def one(_, kb):
            k1, k2 = jax.random.split(kb)
            u = jax.random.uniform(k1, (batch, width))
            idx = (jnp.exp(u * jnp.log(float(dims))).astype(jnp.int32)) % dims
            if perm is not None:
                idx = perm[idx]
            score = BIAS + jnp.sum(w_true[idx], axis=1)
            p = jax.nn.sigmoid(score)
            click = jax.random.bernoulli(k2, p).astype(jnp.float32)
            return None, (idx, click * 2.0 - 1.0, p)

        keys = jax.random.split(k, n_blocks)
        _, (idx, lab, p) = jax.lax.scan(one, None, keys)
        return idx, lab, p

    idx, lab, p = all_blocks(key)
    jax.block_until_ready(idx)
    return idx, lab, p


def eval_logloss(scores, labels01):
    import jax.numpy as jnp
    import jax

    p = jax.nn.sigmoid(scores)
    eps = 1e-7
    p = jnp.clip(p, eps, 1 - eps)
    return -jnp.mean(labels01 * jnp.log(p) + (1 - labels01) * jnp.log1p(-p)), p


def eval_held_out(score_fn, test_blocks):
    """Held-out logloss + flat (p_hat, y01) arrays over stacked test blocks;
    `score_fn(idx_block) -> scores [B]`."""
    import jax.numpy as jnp

    te_idx, te_lab, _ = test_blocks
    lls, ps, labs = [], [], []
    for b in range(te_idx.shape[0]):
        score = score_fn(te_idx[b])
        y01 = (te_lab[b] + 1.0) * 0.5
        ll, p = eval_logloss(score, y01)
        lls.append(ll)
        ps.append(p)
        labs.append(y01)
    logloss = float(jnp.mean(jnp.stack(lls)))
    return logloss, np.concatenate([np.asarray(x) for x in ps]), \
        np.concatenate([np.asarray(x) for x in labs])


def run_arow(train_blocks, test_blocks, epochs, values):
    import jax
    import jax.numpy as jnp

    from hivemall_tpu.core.engine import make_epoch, make_predict, make_train_fn
    from hivemall_tpu.core.state import init_linear_state
    from hivemall_tpu.models.classifier import AROW

    fn = make_train_fn(AROW, {"r": 0.1}, mode="minibatch")
    predict = make_predict(use_covariance=True)
    tr_idx, tr_lab, _ = train_blocks
    epoch = make_epoch(lambda s, bidx, blab: fn(s, bidx, values, blab))

    # AOT-compile the epoch without executing it (donated args); the timing
    # loop calls the compiled executable directly
    warm = init_linear_state(DIMS, use_covariance=True)
    epoch_c = epoch.lower(warm, tr_idx, tr_lab).compile()
    del warm

    state = init_linear_state(DIMS, use_covariance=True)
    t0 = time.perf_counter()
    for _ in range(epochs):
        state, losses = epoch_c(state, tr_idx, tr_lab)
    # value fetch, not block_until_ready: through the axon relay the latter
    # can acknowledge before execution finishes (runtime/benchmark.py).
    # Explicit raise, not assert: -O must never strip the sync.
    got = float(state.step)
    if got != epochs * tr_idx.shape[0] * BATCH:
        raise RuntimeError(f"step counter {got} != expected")
    train_s = time.perf_counter() - t0

    logloss, p_hat, y01 = eval_held_out(
        lambda bidx: predict(state, bidx, values)[0], test_blocks)
    return train_s, logloss, p_hat, y01


def run_fm(train_blocks, test_blocks, epochs, values):
    import jax
    import jax.numpy as jnp

    from hivemall_tpu.core.engine import make_epoch
    from hivemall_tpu.models.fm import FMHyper, init_fm_state, make_fm_step

    hyper = FMHyper(factors=5, classification=True)
    fm_fn = make_fm_step(hyper, mode="minibatch", jit=False)
    va = jnp.zeros((BATCH,), jnp.float32)
    tr_idx, tr_lab, _ = train_blocks
    epoch = make_epoch(lambda s, bidx, blab: fm_fn(s, bidx, values, blab, va))

    warm = init_fm_state(DIMS, hyper)
    epoch_c = epoch.lower(warm, tr_idx, tr_lab).compile()
    del warm

    state = init_fm_state(DIMS, hyper)
    t0 = time.perf_counter()
    for _ in range(epochs):
        state, losses = epoch_c(state, tr_idx, tr_lab)
    # value fetch (un-fakeable sync; see runtime/benchmark.py); explicit
    # raise, not assert: -O must never strip the sync
    got = float(state.step)
    if got != epochs * tr_idx.shape[0] * BATCH:
        raise RuntimeError(f"step counter {got} != expected")
    train_s = time.perf_counter() - t0

    @jax.jit
    def fm_scores(st, idx, val):
        wg = st.w.at[idx].get(mode="fill", fill_value=0.0)
        vg = st.v.at[idx].get(mode="fill", fill_value=0.0)
        linear = st.w0 + jnp.sum(wg * val, axis=1)
        sum_vfx = jnp.einsum("bkf,bk->bf", vg, val)
        sum_v2x2 = jnp.einsum("bkf,bk->bf", vg * vg, val * val)
        return linear + 0.5 * jnp.sum(sum_vfx ** 2 - sum_v2x2, axis=1)

    logloss, p_hat, y01 = eval_held_out(
        lambda bidx: fm_scores(state, bidx, values), test_blocks)
    return train_s, logloss, p_hat, y01


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--train-rows", type=int, default=1 << 21)
    ap.add_argument("--test-rows", type=int, default=1 << 18)
    ap.add_argument("--epochs-arow", type=int, default=2)
    ap.add_argument("--epochs-fm", type=int, default=3)
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    n_train_blocks = max(1, args.train_rows // BATCH)
    n_test_blocks = max(1, args.test_rows // BATCH)

    key = jax.random.PRNGKey(args.seed)
    kw, kd = jax.random.split(key)
    w_true = jax.random.normal(kw, (DIMS,)) * SIGMA_W
    perm = jax.random.permutation(jax.random.fold_in(kd, 2), DIMS
                                  ).astype(jnp.int32)

    t0 = time.perf_counter()
    train_blocks = gen_blocks(jax.random.fold_in(kd, 0), n_train_blocks,
                              DIMS, BATCH, WIDTH, w_true, perm)
    test_blocks = gen_blocks(jax.random.fold_in(kd, 1), n_test_blocks,
                             DIMS, BATCH, WIDTH, w_true, perm)
    gen_s = time.perf_counter() - t0

    # Measured hot-loop anchor on a host sample of the SAME data: the C
    # transliteration of the reference's per-row update (parse/boxing
    # excluded — flatters the reference; on this 260MB-L3 host the whole
    # 2^22 model is cache-resident, so this is a strict upper bound on any
    # real mapper). vs_baseline stays the r1-r4-continuity JVM-mapper
    # system anchor (BASELINE.md estimate, includes parse/ser); the
    # measured loop rides alongside as its own labeled field.
    anchors_measured = {}
    try:
        from hivemall_tpu.runtime.benchmark import measure_reference_rowloops

        n_sample = min(16, n_train_blocks)
        s_idx = np.asarray(train_blocks[0][:n_sample]).reshape(-1, WIDTH)
        s_lab = np.asarray(train_blocks[1][:n_sample]).reshape(-1)
        s_val = np.ones_like(s_idx, dtype=np.float32)
        raw = measure_reference_rowloops(s_idx, s_val, s_lab, DIMS, k=5)
        if "arow_rows_per_sec" in raw:
            anchors_measured["train_arow"] = raw["arow_rows_per_sec"]
        if "fm_rows_per_sec" in raw:
            anchors_measured["train_fm"] = raw["fm_rows_per_sec"]
    except Exception as e:  # noqa: BLE001 - anchor is auxiliary
        print(f"measured anchor unavailable: {e}", file=sys.stderr)
    values = jnp.ones((BATCH, WIDTH), jnp.float32)

    # Bayes floor: logloss of the true CTR as predictor (binary entropy)
    pe = jnp.clip(test_blocks[2], 1e-7, 1 - 1e-7)
    bayes_ll = float(-jnp.mean(pe * jnp.log(pe) + (1 - pe) * jnp.log1p(-pe)))

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples"))
    from score_ctr import score_click_auc, score_nwmae, score_wrmse

    results = {}
    for name, runner, epochs in (
        ("train_arow", run_arow, args.epochs_arow),
        ("train_fm", run_fm, args.epochs_fm),
    ):
        train_s, logloss, p_hat, y01 = runner(train_blocks, test_blocks,
                                              epochs, values)
        clicks = y01
        impressions = np.ones_like(y01)
        auc = score_click_auc(clicks, impressions, p_hat)
        nwmae = score_nwmae(clicks, impressions, p_hat)
        wrmse = score_wrmse(clicks, impressions, p_hat)
        n_updates = n_train_blocks * BATCH * epochs
        anchor_s = n_updates / ANCHOR_ROWS_PER_SEC
        rec = {
            "metric": f"ctr_e2e_{name}_wall_clock_{platform}",
            "value": round(train_s, 4),
            "unit": "sec",
            "vs_baseline": round(anchor_s / train_s, 1),
            "rows_per_sec": round(n_updates / train_s, 1),
            "held_out_logloss": round(logloss, 5),
            "bayes_logloss_floor": round(bayes_ll, 5),
            "auc": round(auc, 5),
            "nwmae": round(nwmae, 5),
            "wrmse": round(wrmse, 5),
            "train_rows": n_train_blocks * BATCH,
            "epochs": epochs,
            "anchor_wall_clock_sec": round(anchor_s, 1),
        }
        if name in anchors_measured:
            m = anchors_measured[name]
            rec["measured_hot_loop_anchor_rows_per_sec"] = round(m, 1)
            rec["vs_measured_hot_loop"] = round(
                (n_updates / m) / train_s, 3)
        results[name] = rec
        print(json.dumps(rec), flush=True)

    summary = {
        "metric": f"ctr_e2e_best_vs_anchor_{platform}",
        "value": max(r["vs_baseline"] for r in results.values()),
        "unit": "x_speedup_at_equal_logloss",
        "vs_baseline": max(r["vs_baseline"] for r in results.values()),
        "datagen_sec": round(gen_s, 2),
    }
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
